"""ObjectRef — a distributed future (reference: python/ray/_raylet.pyx
ObjectRef + ownership tracked by src/ray/core_worker/reference_count.h).

A ref is a handle to an object owned by some worker. Local handle lifetime
feeds the owner's reference count: creating/deserializing a ref registers
it, `__del__` releases it. Serializing a ref (into task args or a `put`)
goes through the core worker so the owner can pin the object until the
borrower registers.
"""

from __future__ import annotations

from ray_tpu._private import global_state
from ray_tpu._private.ids import ObjectID


class ObjectRef:
    __slots__ = ("_id", "_owner_addr", "_plasma", "_registered", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_addr: str = "",
                 plasma: bool = False, _register: bool = True):
        self._id = object_id
        self._owner_addr = owner_addr
        self._plasma = plasma
        self._registered = False
        if _register:
            cw = global_state.get_core_worker()
            if cw is not None:
                cw.register_ref(self)
                self._registered = True

    def id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def task_id(self):
        return self._id.task_id()

    @property
    def owner_address(self) -> str:
        return self._owner_addr

    def is_plasma(self) -> bool:
        return self._plasma

    def future(self):
        """An asyncio-compatible concurrent future for this ref."""
        cw = global_state.require_core_worker()
        return cw.as_future(self)

    def __await__(self):
        # resolve_async delivers through the loop's coalesced call queue:
        # a batch of N awaited results costs one loop wakeup, not N
        # (wrap_future(self.future()) paid one self-pipe write per ref).
        cw = global_state.require_core_worker()
        return cw.resolve_async(self).__await__()

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __reduce__(self):
        cw = global_state.get_core_worker()
        if cw is not None:
            desc = cw.serialize_ref(self)
        else:
            desc = {"id": self._id.binary(), "owner": self._owner_addr,
                    "plasma": self._plasma}
        return (_rehydrate_ref, (desc,))

    def __del__(self):
        try:
            if self._registered:
                cw = global_state.get_core_worker()
                if cw is not None:
                    cw.release_ref(self._id)
        except BaseException:
            # Interpreter shutdown can tear modules down under us.
            pass


def _rehydrate_ref(desc: dict) -> "ObjectRef":
    cw = global_state.get_core_worker()
    if cw is not None:
        return cw.deserialize_ref(desc)
    return ObjectRef(ObjectID(desc["id"]), desc.get("owner", ""),
                     desc.get("plasma", False), _register=False)
