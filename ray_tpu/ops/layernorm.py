"""Fused LayerNorm / RMSNorm Pallas kernels.

One pass through VMEM: moments + normalize + affine in a single kernel so
the activation never round-trips to HBM between the reduction and the
scale (XLA usually fuses this too — the kernel guarantees it and is the
template for fancier fusions like norm+residual+quant).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _layernorm_kernel(x_ref, w_ref, b_ref, o_ref, *, eps: float):
    x = x_ref[...].astype(jnp.float32)
    mean = x.mean(-1, keepdims=True)
    var = ((x - mean) ** 2).mean(-1, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    o_ref[...] = (y * w_ref[...] + b_ref[...]).astype(o_ref.dtype)


def _rmsnorm_kernel(x_ref, w_ref, o_ref, *, eps: float):
    x = x_ref[...].astype(jnp.float32)
    ms = (x * x).mean(-1, keepdims=True)
    y = x * jax.lax.rsqrt(ms + eps)
    o_ref[...] = (y * w_ref[...]).astype(o_ref.dtype)


def _is_tpu() -> bool:
    try:
        return jax.devices()[0].platform not in ("cpu",)
    except Exception:
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def layernorm(x, weight, bias, eps: float = 1e-5):
    """x: [..., D]; weight/bias: [D]. Fused pallas forward; analytic
    backward in plain JAX (XLA fuses it into adjacent matmul epilogues)."""
    return _layernorm_fwd_impl(x, weight, bias, eps=eps)


def _layernorm_fwd_impl(x, weight, bias, *, eps: float,
                        block_rows: int = 256):
    orig_shape = x.shape
    d = orig_shape[-1]
    n = 1
    for s in orig_shape[:-1]:
        n *= s
    xf = x.reshape(n, d)
    block = min(block_rows, n)
    if n % block:
        block = n  # fall back to one block
    out = pl.pallas_call(
        functools.partial(_layernorm_kernel, eps=eps),
        grid=(n // block,),
        in_specs=[
            pl.BlockSpec((block, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), x.dtype),
        interpret=not _is_tpu(),
    )(xf, weight, bias)
    return out.reshape(orig_shape)


def _layernorm_fwd(x, weight, bias, eps):
    return layernorm(x, weight, bias, eps), (x, weight)


def _layernorm_bwd(eps, res, g):
    x, weight = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    mean = xf.mean(-1, keepdims=True)
    var = ((xf - mean) ** 2).mean(-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    xhat = (xf - mean) * inv
    gw = gf * weight.astype(jnp.float32)
    dx = inv * (gw - gw.mean(-1, keepdims=True)
                - xhat * (gw * xhat).mean(-1, keepdims=True))
    red = tuple(range(x.ndim - 1))
    dw = (gf * xhat).sum(red)
    db = gf.sum(red)
    return (dx.astype(x.dtype), dw.astype(weight.dtype),
            db.astype(weight.dtype))


layernorm.defvjp(_layernorm_fwd, _layernorm_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rmsnorm(x, weight, eps: float = 1e-6):
    return _rmsnorm_fwd_impl(x, weight, eps=eps)


def _rmsnorm_fwd_impl(x, weight, *, eps: float, block_rows: int = 256):
    orig_shape = x.shape
    d = orig_shape[-1]
    n = 1
    for s in orig_shape[:-1]:
        n *= s
    xf = x.reshape(n, d)
    block = min(block_rows, n)
    if n % block:
        block = n
    out = pl.pallas_call(
        functools.partial(_rmsnorm_kernel, eps=eps),
        grid=(n // block,),
        in_specs=[
            pl.BlockSpec((block, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), x.dtype),
        interpret=not _is_tpu(),
    )(xf, weight)
    return out.reshape(orig_shape)


def _rmsnorm_fwd(x, weight, eps):
    return rmsnorm(x, weight, eps), (x, weight)


def _rmsnorm_bwd(eps, res, g):
    x, weight = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    ms = (xf * xf).mean(-1, keepdims=True)
    inv = jax.lax.rsqrt(ms + eps)
    gw = gf * weight.astype(jnp.float32)
    d = x.shape[-1]
    dx = inv * gw - xf * (inv ** 3) * (gw * xf).sum(-1, keepdims=True) / d
    red = tuple(range(x.ndim - 1))
    dw = (gf * xf * inv).sum(red)
    return dx.astype(x.dtype), dw.astype(weight.dtype)


rmsnorm.defvjp(_rmsnorm_fwd, _rmsnorm_bwd)
