"""Fused LayerNorm / RMSNorm Pallas kernels.

One pass through VMEM: moments + normalize + affine in a single kernel so
the activation never round-trips to HBM between the reduction and the
scale (XLA usually fuses this too — the kernel guarantees it and is the
template for fancier fusions like norm+residual+quant).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _layernorm_kernel(x_ref, w_ref, b_ref, o_ref, *, eps: float):
    x = x_ref[...].astype(jnp.float32)
    mean = x.mean(-1, keepdims=True)
    var = ((x - mean) ** 2).mean(-1, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    o_ref[...] = (y * w_ref[...] + b_ref[...]).astype(o_ref.dtype)


def _rmsnorm_kernel(x_ref, w_ref, o_ref, *, eps: float):
    x = x_ref[...].astype(jnp.float32)
    ms = (x * x).mean(-1, keepdims=True)
    y = x * jax.lax.rsqrt(ms + eps)
    o_ref[...] = (y * w_ref[...]).astype(o_ref.dtype)


def _is_tpu() -> bool:
    try:
        return jax.devices()[0].platform not in ("cpu",)
    except Exception:
        return False


def layernorm(x, weight, bias, *, eps: float = 1e-5, block_rows: int = 256):
    """x: [..., D]; weight/bias: [D]."""
    orig_shape = x.shape
    d = orig_shape[-1]
    n = int(jnp.prod(jnp.asarray(orig_shape[:-1]))) if len(orig_shape) > 1 else 1
    xf = x.reshape(n, d)
    block = min(block_rows, n)
    if n % block:
        block = n  # fall back to one block
    out = pl.pallas_call(
        functools.partial(_layernorm_kernel, eps=eps),
        grid=(n // block,),
        in_specs=[
            pl.BlockSpec((block, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), x.dtype),
        interpret=not _is_tpu(),
    )(xf, weight, bias)
    return out.reshape(orig_shape)


def rmsnorm(x, weight, *, eps: float = 1e-6, block_rows: int = 256):
    orig_shape = x.shape
    d = orig_shape[-1]
    n = 1
    for s in orig_shape[:-1]:
        n *= s
    xf = x.reshape(n, d)
    block = min(block_rows, n)
    if n % block:
        block = n
    out = pl.pallas_call(
        functools.partial(_rmsnorm_kernel, eps=eps),
        grid=(n // block,),
        in_specs=[
            pl.BlockSpec((block, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), x.dtype),
        interpret=not _is_tpu(),
    )(xf, weight)
    return out.reshape(orig_shape)
