"""Fused batchnorm backward as a Pallas TPU kernel.

PERF.md's profile shows BN backward is the bandwidth tax on the ResNet
headline bench: its two per-channel reductions (Σdy and Σdy·x̂) re-read
every activation, and XLA fuses them into the dW-conv fusions where they
compete for the same HBM streams. This kernel computes BOTH reductions in
ONE pass over (x, dy) tiles — each bf16 tile is read once into VMEM and
feeds both fp32 accumulators — so the backward costs exactly one extra
read of x and dy beyond the unavoidable dx write. The dx elementwise that
follows is left in plain JAX on purpose: it is a pure map, so XLA fuses
it with the neighboring conv backward exactly like the baseline.

Semantically identical to the XLA path in models/resnet.py `_bn` (same
one-pass E[x²]−E[x]² variance with the same clamp), selected by
`ResNetConfig(bn_mode="pallas")` and A/B-able via RAY_TPU_BENCH_BN.

Reference analog: the reference trains ResNet through cuDNN's fused
batchnorm backward (torch BatchNorm2d → cudnnBatchNormalizationBackward);
this is the TPU-native equivalent of that single-pass reduction fusion.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl


def _is_tpu() -> bool:
    # interpret mode everywhere the Mosaic TPU compiler isn't: CPU and
    # GPU backends. Unknown platform names (the axon TPU plugin may not
    # report the stock "tpu" string) are assumed TPU-compilable.
    try:
        return jax.devices()[0].platform not in ("cpu", "gpu", "cuda",
                                                 "rocm")
    except Exception:
        return False


def _sums_kernel(x_ref, dy_ref, mean_ref, inv_ref, sdy_ref, sdyx_ref):
    """Grid (C_blocks, M_blocks), M innermost (sequential on TPU): each
    step streams one [bm, bc] tile of x and dy through VMEM and folds both
    per-channel partial sums into the [1, bc] fp32 accumulators."""
    mi = pl.program_id(1)
    x = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    xhat = (x - mean_ref[...]) * inv_ref[...]
    p_sdy = dy.sum(axis=0, keepdims=True)
    p_sdyx = (dy * xhat).sum(axis=0, keepdims=True)

    @pl.when(mi == 0)
    def _init():
        sdy_ref[...] = p_sdy
        sdyx_ref[...] = p_sdyx

    @pl.when(mi != 0)
    def _acc():
        sdy_ref[...] += p_sdy
        sdyx_ref[...] += p_sdyx


def _pick_block_m(m: int) -> int | None:
    for bm in (1024, 512, 256, 128, 64, 32, 16, 8):
        if m % bm == 0:
            return bm
    return None


def _bn_bwd_sums(x2, dy2, mean, inv, *, interpret: bool):
    """x2, dy2: [M, C]. Returns (Σdy, Σdy·x̂): two [C] fp32 vectors in one
    HBM pass. Falls back to XLA reductions when M isn't 8-tileable."""
    m, c = x2.shape
    bm = _pick_block_m(m)
    bc = c if c < 128 else 128
    if bm is None or c % bc:
        xf = x2.astype(jnp.float32)
        dyf = dy2.astype(jnp.float32)
        xhat = (xf - mean) * inv
        return dyf.sum(0), (dyf * xhat).sum(0)
    kernel = _sums_kernel
    sdy, sdyx = pl.pallas_call(
        kernel,
        grid=(c // bc, m // bm),
        in_specs=[
            pl.BlockSpec((bm, bc), lambda ci, mi: (mi, ci)),
            pl.BlockSpec((bm, bc), lambda ci, mi: (mi, ci)),
            pl.BlockSpec((1, bc), lambda ci, mi: (0, ci)),
            pl.BlockSpec((1, bc), lambda ci, mi: (0, ci)),
        ],
        out_specs=[
            pl.BlockSpec((1, bc), lambda ci, mi: (0, ci)),
            pl.BlockSpec((1, bc), lambda ci, mi: (0, ci)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, c), jnp.float32),
            jax.ShapeDtypeStruct((1, c), jnp.float32),
        ],
        interpret=interpret,
    )(x2, dy2, mean.reshape(1, c), inv.reshape(1, c))
    return sdy[0], sdyx[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def bn_train(x, scale, bias, eps: float = 1e-5):
    """Training-mode batchnorm over NHW: x [N,H,W,C] (any float dtype),
    scale/bias [C] fp32 → (y [N,H,W,C] x.dtype, mean [C] f32, var [C] f32).

    mean/var are auxiliary outputs for the running-stats update — they
    carry no gradient (the caller feeds them into non-differentiated
    state). Forward math matches models/resnet.py `_bn` exactly; backward
    runs the Pallas one-pass dual reduction.
    """
    y, mean, var, _ = _bn_fwd_math(x, scale, bias, eps)
    return y, mean, var


def _bn_fwd_math(x, scale, bias, eps):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=(0, 1, 2))
    # clamp: one-pass E[x²]−E[x]² can dip negative from fp32 rounding
    var = jnp.maximum(
        jnp.mean(jnp.square(xf), axis=(0, 1, 2)) - jnp.square(mean), 0.0)
    inv = lax.rsqrt(var + eps)
    a = inv * scale
    offset = bias - mean * a
    y = x * a.astype(x.dtype) + offset.astype(x.dtype)
    return y, mean, var, inv


def _bn_train_fwd(x, scale, bias, eps):
    y, mean, var, inv = _bn_fwd_math(x, scale, bias, eps)
    return (y, mean, var), (x, mean, inv, scale)


def _bn_train_bwd(eps, residuals, cotangents):
    x, mean, inv, scale = residuals
    dy, _g_mean, _g_var = cotangents  # mean/var are aux state: no grad
    n, h, w, c = x.shape
    m = n * h * w
    x2 = x.reshape(m, c)
    dy2 = dy.reshape(m, c)
    sdy, sdyx = _bn_bwd_sums(x2, dy2, mean, inv, interpret=not _is_tpu())
    # dx = inv·scale · (dy − Σdy/M − x̂ · Σ(dy·x̂)/M); pure map, so XLA
    # fuses it into the adjacent conv backward like the baseline BN did
    a = (inv * scale).astype(x.dtype)
    k1 = (sdy / m).astype(x.dtype)
    k2 = (sdyx / m * inv).astype(x.dtype)  # folds x̂ = (x−mean)·inv
    mu = mean.astype(x.dtype)
    dx = a * (dy - k1 - (x - mu) * k2)
    return dx.astype(x.dtype), sdyx, sdy


bn_train.defvjp(_bn_train_fwd, _bn_train_bwd)
