"""Flash attention as a Pallas TPU kernel.

Blockwise streaming-softmax attention: Q blocks stream through VMEM, K/V
are scanned in blocks, the MXU does the two matmuls per block, and the
running (max, denom) accumulators live in f32 — the standard flash
schedule, written for the TPU memory hierarchy (HBM→VMEM via BlockSpecs).

Backward uses recompute (custom_vjp whose bwd re-runs dense attention in
checkpointed blocks) — flash-style memory: nothing but (q, k, v, o, lse) is
saved. On CPU (tests) the kernel runs in interpret mode.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                  scale: float):
    qi = pl.program_id(1)
    q = q_ref[...]  # [block_q, d]
    t = k_ref.shape[0]
    d = q.shape[-1]
    block_q = q.shape[0]

    def body(ki, carry):
        o, m, l = carry
        k = k_ref[pl.ds(ki * block_k, block_k), :]  # [block_k, d]
        v = v_ref[pl.ds(ki * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(-1)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        o_new = o * corr[:, None] + pv
        return o_new, m_new, l_new

    o0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    num_k = t // block_k
    if causal:
        # only scan K blocks at or before this Q block
        num_k_active = jnp.minimum(
            num_k, (qi + 1) * block_q // block_k + (block_q % block_k != 0))
        o, m, l = jax.lax.fori_loop(0, num_k_active, body, (o0, m0, l0))
    else:
        o, m, l = jax.lax.fori_loop(0, num_k, body, (o0, m0, l0))
    denom = jnp.where(l > 0, l, 1.0)
    o_ref[...] = (o / denom[:, None]).astype(o_ref.dtype)


def _flash_aligned(t: int, d: int, block_q: int, block_k: int) -> bool:
    """Mosaic constraints: K/V dynamic-slice starts must be provably
    8-aligned (sublane) and the lane dim 128-padded; unaligned shapes go
    through the dense path (short sequences — dense is fine there)."""
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    return (t % block_q == 0 and t % block_k == 0
            and block_q % 8 == 0 and block_k % 8 == 0 and d % 8 == 0)


def _flash_fwd_impl(q, k, v, *, causal: bool, scale: float, block_q: int,
                    block_k: int, interpret: bool):
    b, t, h, d = q.shape
    if not _flash_aligned(t, d, block_q, block_k):
        if t >= 512:
            import warnings

            warnings.warn(
                f"flash_attention: seq {t} / head_dim {d} not tile-aligned;"
                " falling back to dense O(T^2) attention — pad the sequence"
                " to a multiple of 8 for the pallas kernel", stacklevel=2)
        return _dense_attention(q, k, v, causal, scale)
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    # fold batch and heads; layout [B*H, T, D]
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, t, d)

    kernel = functools.partial(_flash_kernel, block_k=block_k,
                               causal=causal, scale=scale)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, t // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, t, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((None, t, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _dense_attention(q, k, v, causal, scale, q_offset=0, pad_mask=None):
    """Reference/fallback path. q_offset shifts the causal mask (used by
    the blockwise backward); pad_mask: [B, Tk] bool, True = real token."""
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        mask = (q_offset + jnp.arange(tq))[:, None] >= jnp.arange(tk)[None, :]
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    if pad_mask is not None:
        scores = jnp.where(pad_mask[:, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v).astype(q.dtype)


def masked_attention(q, k, v, pad_mask, causal=False, scale=None):
    """Attention with key padding mask (BERT-style batches). pad_mask:
    [B, T] bool. Dense path — padded fine-tune batches are short."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    return _dense_attention(q, k, v, causal, scale, pad_mask=pad_mask)


def _is_tpu() -> bool:
    try:
        return jax.devices()[0].platform not in ("cpu",)
    except Exception:
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True, scale: float | None = None,
                    block_q: int = 128, block_k: int = 128):
    """q, k, v: [B, T, H, D]. Returns [B, T, H, D]."""
    actual_scale = scale if scale is not None else q.shape[-1] ** -0.5
    return _flash_fwd_impl(q, k, v, causal=causal, scale=actual_scale,
                           block_q=block_q, block_k=block_k,
                           interpret=not _is_tpu())


def _fwd(q, k, v, causal, scale, block_q, block_k):
    out = flash_attention(q, k, v, causal, scale, block_q, block_k)
    return out, (q, k, v)


def _bwd(causal, scale, block_q, block_k, residuals, g):
    """Blockwise-remat backward: scan over Q blocks, each recomputing its
    attention against full K/V and accumulating dk/dv. Peak extra memory is
    one [B, H, block_q, T] score block (linear in T), not the full T×T
    matrix — flash-style memory from only (q, k, v) residuals."""
    q, k, v = residuals
    actual_scale = scale if scale is not None else q.shape[-1] ** -0.5
    b, t, h, d = q.shape
    bq = min(block_q, t)

    if t % bq:
        # unaligned fallback: single checkpointed dense block
        def f(q, k, v):
            return _dense_attention(q, k, v, causal, actual_scale)

        _, vjp = jax.vjp(jax.checkpoint(f), q, k, v)
        return vjp(g)

    n = t // bq
    qb = jnp.moveaxis(q.reshape(b, n, bq, h, d), 1, 0)   # [n, B, bq, H, D]
    gb = jnp.moveaxis(g.reshape(b, n, bq, h, d), 1, 0)

    def body(carry, inp):
        dk, dv = carry
        i, q_blk, g_blk = inp

        def f(q_blk, k, v):
            return _dense_attention(q_blk, k, v, causal, actual_scale,
                                    q_offset=i * bq)

        _, vjp = jax.vjp(f, q_blk, k, v)
        dq_blk, dk_i, dv_i = vjp(g_blk)
        return (dk + dk_i, dv + dv_i), dq_blk

    (dk, dv), dq = jax.lax.scan(
        body, (jnp.zeros_like(k, jnp.float32), jnp.zeros_like(v, jnp.float32)),
        (jnp.arange(n), qb, gb))
    dq = jnp.moveaxis(dq, 0, 1).reshape(b, t, h, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_fwd, _bwd)
