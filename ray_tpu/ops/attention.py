"""Flash attention as a Pallas TPU kernel.

Blockwise streaming-softmax attention: Q blocks stream through VMEM, K/V
are scanned in blocks, the MXU does the two matmuls per block, and the
running (max, denom) accumulators live in f32 — the standard flash
schedule, written for the TPU memory hierarchy (HBM→VMEM via BlockSpecs).

Backward uses recompute (custom_vjp whose bwd re-runs dense attention in
checkpointed blocks) — flash-style memory: nothing but (q, k, v, o, lse) is
saved. On CPU (tests) the kernel runs in interpret mode.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                  scale: float, q_block: int):
    qi = pl.program_id(1)
    q = q_ref[...]  # [block_q, d]
    t = k_ref.shape[0]
    d = q.shape[-1]
    block_q = q.shape[0]

    def body(ki, carry):
        o, m, l = carry
        k = k_ref[pl.ds(ki * block_k, block_k), :]  # [block_k, d]
        v = v_ref[pl.ds(ki * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(-1)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        o_new = o * corr[:, None] + pv
        return o_new, m_new, l_new

    o0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    num_k = t // block_k
    if causal:
        # only scan K blocks at or before this Q block
        num_k_active = jnp.minimum(
            num_k, (qi + 1) * block_q // block_k + (block_q % block_k != 0))
        o, m, l = jax.lax.fori_loop(0, num_k_active, body, (o0, m0, l0))
    else:
        o, m, l = jax.lax.fori_loop(0, num_k, body, (o0, m0, l0))
    denom = jnp.where(l > 0, l, 1.0)
    o_ref[...] = (o / denom[:, None]).astype(o_ref.dtype)


def _flash_fwd_impl(q, k, v, *, causal: bool, scale: float, block_q: int,
                    block_k: int, interpret: bool):
    b, t, h, d = q.shape
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if t % block_q or t % block_k:
        raise ValueError(f"sequence length {t} must divide block sizes")
    # fold batch and heads; layout [B*H, T, D]
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, t, d)

    kernel = functools.partial(_flash_kernel, block_k=block_k,
                               causal=causal, scale=scale, q_block=block_q)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, t // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, t, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((None, t, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _dense_attention(q, k, v, causal, scale):
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v).astype(q.dtype)


def _is_tpu() -> bool:
    try:
        return jax.devices()[0].platform not in ("cpu",)
    except Exception:
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True, scale: float | None = None,
                    block_q: int = 128, block_k: int = 128):
    """q, k, v: [B, T, H, D]. Returns [B, T, H, D]."""
    actual_scale = scale if scale is not None else q.shape[-1] ** -0.5
    return _flash_fwd_impl(q, k, v, causal=causal, scale=actual_scale,
                           block_q=block_q, block_k=block_k,
                           interpret=not _is_tpu())


def _fwd(q, k, v, causal, scale, block_q, block_k):
    out = flash_attention(q, k, v, causal, scale, block_q, block_k)
    return out, (q, k, v)


def _bwd(causal, scale, block_q, block_k, residuals, g):
    q, k, v = residuals
    actual_scale = scale if scale is not None else q.shape[-1] ** -0.5

    # Rematerialized dense backward (flash-style memory: only q,k,v saved).
    def f(q, k, v):
        return _dense_attention(q, k, v, causal, actual_scale)

    _, vjp = jax.vjp(jax.checkpoint(f), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
