"""Public exception types (capability parity: python/ray/exceptions.py)."""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all ray_tpu errors."""


class TaskError(RayTpuError):
    """A task raised an exception during execution.

    Stored as the task's result object; re-raised (with the remote traceback
    appended) when the caller `get`s the result — matching the reference's
    RayTaskError behavior (python/ray/exceptions.py RayTaskError).
    """

    def __init__(self, cause_cls_name: str, cause_repr: str, traceback_str: str,
                 proctitle: str = ""):
        self.cause_cls_name = cause_cls_name
        self.cause_repr = cause_repr
        self.traceback_str = traceback_str
        self.proctitle = proctitle
        super().__init__(self._format())

    def _format(self) -> str:
        return (
            f"Task raised {self.cause_cls_name}: {self.cause_repr}\n"
            f"Remote traceback:\n{self.traceback_str}"
        )

    def __reduce__(self):
        return (TaskError, (self.cause_cls_name, self.cause_repr,
                            self.traceback_str, self.proctitle))


class WorkerCrashedError(RayTpuError):
    """The worker executing the task died unexpectedly."""


class ActorError(RayTpuError):
    """Base for actor-related failures."""


class ActorDiedError(ActorError):
    """The actor is dead; pending and future calls fail with this."""

    def __init__(self, actor_id_hex: str = "", reason: str = ""):
        self.actor_id_hex = actor_id_hex
        self.reason = reason
        super().__init__(f"Actor {actor_id_hex} is dead: {reason or 'unknown'}")

    def __reduce__(self):
        return (ActorDiedError, (self.actor_id_hex, self.reason))


class ActorUnavailableError(ActorError):
    """The actor is temporarily unreachable (e.g. restarting)."""


class ObjectLostError(RayTpuError):
    """The object's value was lost (all copies evicted/node died) and could
    not be reconstructed from lineage."""

    def __init__(self, object_id_hex: str = ""):
        self.object_id_hex = object_id_hex
        super().__init__(f"Object {object_id_hex} was lost and is unrecoverable")

    def __reduce__(self):
        return (ObjectLostError, (self.object_id_hex,))


class ObjectStoreFullError(RayTpuError):
    """The shared-memory object store is out of memory even after spilling."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """`get` exceeded its timeout."""


class TaskCancelledError(RayTpuError):
    """The task was cancelled via `ray_tpu.cancel`."""

    def __init__(self, task_id_hex: str = ""):
        self.task_id_hex = task_id_hex
        super().__init__(f"Task {task_id_hex} was cancelled")

    def __reduce__(self):
        return (TaskCancelledError, (self.task_id_hex,))


class RuntimeEnvSetupError(RayTpuError):
    """Failed to set up the environment for a task/actor."""


class NodeDiedError(RayTpuError):
    """A node in the cluster was declared dead."""


class ServeOverloadedError(RayTpuError):
    """A Serve endpoint shed this request at admission: the router's
    bounded queue was already at `max_queued_requests` depth. The typed
    503 of the serving tier — callers should back off `retry_after_s`
    and retry; the HTTP proxy maps it to 503 + Retry-After."""

    def __init__(self, endpoint: str = "", queued: int = 0,
                 max_queued: int = 0, retry_after_s: float = 1.0):
        self.endpoint = endpoint
        self.queued = queued
        self.max_queued = max_queued
        self.retry_after_s = retry_after_s
        super().__init__(
            f"endpoint {endpoint!r} overloaded: {queued} queued >= "
            f"max_queued_requests={max_queued}; retry after "
            f"{retry_after_s:.1f}s")

    def __reduce__(self):
        return (ServeOverloadedError,
                (self.endpoint, self.queued, self.max_queued,
                 self.retry_after_s))


class SequenceAborted(RayTpuError):
    """A streaming inference sequence was aborted before it finished:
    the client disconnected mid-stream, the KV page pool was exhausted,
    or the hosting engine shut down. The sequence's KV pages are freed
    on the abort path; any reader still parked on the stream surfaces
    this instead of hanging."""

    def __init__(self, seq_id: str = "", reason: str = ""):
        self.seq_id = seq_id
        self.reason = reason
        super().__init__(
            f"sequence {seq_id or '?'} aborted: {reason or 'aborted'}")

    def __reduce__(self):
        return (SequenceAborted, (self.seq_id, self.reason))


class PlacementGroupInfeasibleError(RayTpuError):
    """The GCS determined this placement group cannot be reserved on
    the CURRENT fleet (e.g. STRICT_SPREAD wanting more distinct nodes
    than exist). Unlike a PENDING group — which is merely waiting for
    resources to free — an infeasible one needs the cluster to GROW;
    ready()/wait() surface this typed instead of blocking forever.
    The group stays registered: a joining node flips it back to
    PENDING and retries."""

    def __init__(self, pg_id_hex: str = "", detail: str = ""):
        self.pg_id_hex = pg_id_hex
        self.detail = detail
        super().__init__(
            f"placement group {pg_id_hex or '?'} is infeasible on the "
            f"current fleet: {detail or 'needs more nodes'}")

    def __reduce__(self):
        return (PlacementGroupInfeasibleError,
                (self.pg_id_hex, self.detail))


class ReplicaGroupDied(RayTpuError):
    """A sharded Serve replica group lost a member (or its leader) while
    this request was in flight. The whole gang is being restarted by the
    controller; the request did NOT complete. Retryable once the gang is
    back (the HTTP proxy maps it to 503)."""

    def __init__(self, backend: str = "", group: str = "",
                 reason: str = ""):
        self.backend = backend
        self.group = group
        self.reason = reason
        super().__init__(
            f"replica group {group or '?'} of backend {backend!r} died "
            f"mid-request: {reason or 'member lost'}")

    def __reduce__(self):
        return (ReplicaGroupDied, (self.backend, self.group, self.reason))
