"""Public exception types (capability parity: python/ray/exceptions.py)."""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all ray_tpu errors."""


class TaskError(RayTpuError):
    """A task raised an exception during execution.

    Stored as the task's result object; re-raised (with the remote traceback
    appended) when the caller `get`s the result — matching the reference's
    RayTaskError behavior (python/ray/exceptions.py RayTaskError).
    """

    def __init__(self, cause_cls_name: str, cause_repr: str, traceback_str: str,
                 proctitle: str = ""):
        self.cause_cls_name = cause_cls_name
        self.cause_repr = cause_repr
        self.traceback_str = traceback_str
        self.proctitle = proctitle
        super().__init__(self._format())

    def _format(self) -> str:
        return (
            f"Task raised {self.cause_cls_name}: {self.cause_repr}\n"
            f"Remote traceback:\n{self.traceback_str}"
        )

    def __reduce__(self):
        return (TaskError, (self.cause_cls_name, self.cause_repr,
                            self.traceback_str, self.proctitle))


class WorkerCrashedError(RayTpuError):
    """The worker executing the task died unexpectedly."""


class ActorError(RayTpuError):
    """Base for actor-related failures."""


class ActorDiedError(ActorError):
    """The actor is dead; pending and future calls fail with this."""

    def __init__(self, actor_id_hex: str = "", reason: str = ""):
        self.actor_id_hex = actor_id_hex
        self.reason = reason
        super().__init__(f"Actor {actor_id_hex} is dead: {reason or 'unknown'}")

    def __reduce__(self):
        return (ActorDiedError, (self.actor_id_hex, self.reason))


class ActorUnavailableError(ActorError):
    """The actor is temporarily unreachable (e.g. restarting)."""


class ObjectLostError(RayTpuError):
    """The object's value was lost (all copies evicted/node died) and could
    not be reconstructed from lineage."""

    def __init__(self, object_id_hex: str = ""):
        self.object_id_hex = object_id_hex
        super().__init__(f"Object {object_id_hex} was lost and is unrecoverable")

    def __reduce__(self):
        return (ObjectLostError, (self.object_id_hex,))


class ObjectStoreFullError(RayTpuError):
    """The shared-memory object store is out of memory even after spilling."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """`get` exceeded its timeout."""


class TaskCancelledError(RayTpuError):
    """The task was cancelled via `ray_tpu.cancel`."""

    def __init__(self, task_id_hex: str = ""):
        self.task_id_hex = task_id_hex
        super().__init__(f"Task {task_id_hex} was cancelled")

    def __reduce__(self):
        return (TaskCancelledError, (self.task_id_hex,))


class RuntimeEnvSetupError(RayTpuError):
    """Failed to set up the environment for a task/actor."""


class NodeDiedError(RayTpuError):
    """A node in the cluster was declared dead."""
