"""Cluster dashboard — HTTP views over the control plane (reference:
python/ray/dashboard + the new_dashboard agent/head split; here a single
aiohttp process reading the GCS + raylets over the existing RPC layer).

Endpoints:
    /            tiny HTML overview (auto-refreshing)
    /api/nodes   node table incl. per-node availability
    /api/actors  actor table (id, state, name, node, restarts)
    /api/metrics gcs + per-raylet metric snapshots
    /api/objects per-node object store usage
    /api/timeline chrome-trace JSON of recorded profile spans
    /api/trace   Perfetto JSON of the trace table (?trace_id= one tree)
    /api/profile cluster flamegraph off the continuous-profiler ring
                 (?component=, ?since=, ?format=collapsed|perfetto|raw)
    /api/metrics/history per-source metric time series (?samples=N)
    /api/events  structured cluster events ring
    /api/state   live debug_state of every process (?component=serve|
                 placement|tasks|actors|objects|leases|transfers|
                 collectives, ?workers=0; `placement` is the per-pg
                 bundle→node table with topology coords + strategy/
                 cost-model; `serve` includes per-gang decode-batch
                 occupancy, per-session KV page counts and stream
                 backlog for streaming backends)
    /api/doctor  stall-doctor findings (age vs max(floor, K*p99))
"""

from __future__ import annotations

import asyncio
import json

from ray_tpu._private import rpc
from ray_tpu._private.common import ResourceSet

_PAGE = """<!doctype html><meta http-equiv=refresh content=2>
<title>ray_tpu dashboard</title>
<style>body{font-family:monospace;margin:2em}table{border-collapse:collapse}
td,th{border:1px solid #999;padding:4px 8px;text-align:left}</style>
<h2>ray_tpu cluster</h2><div id=c>loading…</div>
<script>
// Escape EVERYTHING interpolated into innerHTML: actor/class names are
// user-controlled (the reference dashboard had exactly this XSS class).
const esc=v=>String(v).replace(/[&<>"']/g,
  c=>({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',"'":'&#39;'}[c]));
fetch('/api/nodes').then(r=>r.json()).then(ns=>{
 let h='<h3>nodes</h3><table><tr><th>node</th><th>address</th><th>head</th>'
   +'<th>total</th><th>available</th></tr>';
 for(const n of ns){h+=`<tr><td>${esc(n.node_id)}</td>`
   +`<td>${esc(n.address)}</td><td>${esc(n.is_head)}</td>`
   +`<td>${esc(JSON.stringify(n.total))}</td>`
   +`<td>${esc(JSON.stringify(n.available))}</td></tr>`}
 h+='</table>';
 fetch('/api/actors').then(r=>r.json()).then(as_=>{
  h+='<h3>actors</h3><table><tr><th>actor</th><th>class</th><th>state</th>'
    +'<th>name</th><th>restarts</th></tr>';
  for(const a of as_){h+=`<tr><td>${esc(a.actor_id)}</td>`
    +`<td>${esc(a.class_name)}</td><td>${esc(a.state)}</td>`
    +`<td>${esc(a.name)}</td><td>${esc(a.num_restarts)}</td></tr>`}
  h+='</table>';document.getElementById('c').innerHTML=h})})
</script>"""


class Dashboard:
    """Serves cluster state pulled from the GCS address."""

    def __init__(self, gcs_address: str, host: str = "127.0.0.1",
                 port: int = 0):
        self.gcs_address = gcs_address
        self.host = host
        self.port = port
        self._site_port = None

    async def _gcs(self, method: str, data=None):
        conn = await rpc.connect(self.gcs_address, name="dashboard")
        try:
            return await conn.call(method, data or {}, timeout=10)
        finally:
            await conn.close()

    async def _raylet(self, address: str, method: str, data=None):
        conn = await rpc.connect(address, name="dashboard")
        try:
            return await conn.call(method, data or {}, timeout=10)
        finally:
            await conn.close()

    # -- endpoint payloads ----------------------------------------------

    async def nodes(self) -> list[dict]:
        nodes = await self._gcs("get_all_nodes")
        avail = await self._gcs("get_available_resources")
        out = []
        for n in nodes:
            out.append({
                "node_id": n["node_id"].hex()[:12],
                "address": n["address"],
                "hostname": n.get("hostname", ""),
                "is_head": bool(n.get("is_head")),
                "total": ResourceSet.from_raw(n["resources"]).to_dict(),
                "available": ResourceSet.from_raw(
                    avail.get(n["node_id"], {})).to_dict(),
            })
        return out

    async def actors(self) -> list[dict]:
        actors = await self._gcs("list_actors")
        return [{
            "actor_id": a["actor_id"].hex()[:12],
            "class_name": a.get("class_name", ""),
            "state": a["state"],
            "name": a.get("name", ""),
            "node": (a["node_id"].hex()[:12] if a.get("node_id") else ""),
            "num_restarts": a.get("num_restarts", 0),
        } for a in actors]

    async def metrics(self) -> dict:
        out = {"gcs": await self._gcs("get_metrics")}
        nodes = await self._gcs("get_all_nodes")

        async def one(n):
            try:
                return (n["node_id"].hex()[:12],
                        await self._raylet(n["address"], "get_metrics"))
            except Exception:
                return None

        got = await asyncio.gather(*(one(n) for n in nodes))
        out["raylets"] = dict(p for p in got if p)
        return out

    async def objects(self) -> list[dict]:
        nodes = await self._gcs("get_all_nodes")
        out = []
        for n in nodes:
            try:
                info = await self._raylet(n["address"], "cluster_info")
            except Exception:
                continue
            out.append({"node_id": n["node_id"].hex()[:12],
                        "num_objects": info["num_local_objects"],
                        "store_used_bytes": info["store_used"],
                        "num_workers": info["num_workers"],
                        # bulk transfer plane (raylet/transfer.py):
                        # cumulative pull bytes, striped pulls, live
                        # in-flight chunks and sender-side pins
                        "transfer": info.get("transfer", {})})
        return out

    async def logs(self, node: str | None = None, file: str | None = None,
                   lines: int = 200):
        """Per-node log browsing via each raylet's get_logs handler —
        logs stay node-local, pulled on demand (the scalable agent model;
        reference: dashboard/agent.py log routes). Without `node`: map of
        node -> log file list. With node (+optional file): that node's
        files, or the file's tail."""
        nodes = await self._gcs("get_all_nodes")
        by_id = {n["node_id"].hex()[:12]: n for n in nodes}
        if node is None:
            async def one(nid, n):
                try:
                    return nid, await self._raylet(n["address"],
                                                   "get_logs")
                except Exception:
                    return None
            got = await asyncio.gather(
                *(one(nid, n) for nid, n in by_id.items()))
            return dict(p for p in got if p)
        n = by_id.get(node[:12])
        if n is None:
            return {"error": f"unknown node {node!r}"}
        payload = {"lines": lines}
        if file:
            payload["file"] = file
        return await self._raylet(n["address"], "get_logs", payload)

    async def timeline(self) -> list[dict]:
        from ray_tpu._private.profiling import to_chrome_trace

        return to_chrome_trace(await self._gcs("get_profile_events"))

    async def trace(self, trace_id: str | None = None) -> list[dict]:
        """Perfetto/chrome-trace JSON of the GCS trace table — the
        causally-linked span trees (all traces, or one by hex id)."""
        from ray_tpu._private.profiling import spans_to_chrome_trace

        rows = await self._gcs("get_trace_spans", {"trace_id": trace_id})
        return spans_to_chrome_trace(rows)

    async def profile(self, component: str | None = None,
                      since: float | None = None,
                      fmt: str = "collapsed"):
        """Cluster-wide flamegraph off the GCS profile ring
        (sampling_profiler.py): ?format=collapsed (text lines) |
        perfetto (merged tracks) | raw (ring batches);
        ?component= one process class, ?since= unix-seconds floor."""
        from ray_tpu._private import sampling_profiler as _sprof

        batches = await self._gcs("get_profile_samples",
                                  {"component": component,
                                   "since": since})
        if fmt == "raw":
            return batches
        if fmt == "perfetto":
            return _sprof.samples_to_chrome_trace(batches)
        return {
            "collapsed": _sprof.collapse_text(batches, component),
            "components": _sprof.components_of(batches),
            "samples": sum(b.get("samples", 0) for b in batches),
        }

    async def metrics_history(self, samples: int = 0) -> dict:
        """Per-source metric time series from the GCS ring buffers."""
        return await self._gcs("get_metrics_history", {"samples": samples})

    async def events(self) -> list[dict]:
        return await self._gcs("get_events")

    async def state(self, component: str | None = None,
                    include_workers: bool = True):
        """Live cluster introspection (debug_state of every process);
        ?component=placement|tasks|actors|objects|leases|transfers|
        collectives returns flat rows instead of the full tree
        (placement: per-pg bundle→node rows with topology coords and
        the chosen strategy/cost-model)."""
        from ray_tpu._private import debug_state

        conns: dict[str, object] = {}
        gcs = await rpc.connect(self.gcs_address, name="dashboard")
        try:
            async def gcs_call(method, data):
                return await gcs.call(method, data, timeout=10)

            async def peer_dial(address):
                conn = conns.get(address)
                if conn is None or conn.closed:
                    conn = conns[address] = await rpc.connect(
                        address, name="dashboard")
                return conn

            snap = await debug_state.collect_cluster_state_async(
                gcs_call, peer_dial, include_workers=include_workers)
        finally:
            for conn in conns.values():
                await conn.close()
            await gcs.close()
        if component:
            return debug_state.flatten(snap, component)
        return snap

    async def doctor(self) -> dict:
        """Stall-doctor findings over the live snapshot + histograms."""
        from ray_tpu._private import debug_state

        snap = await self.state()
        metrics = await self.metrics()
        findings = debug_state.diagnose(snap, metrics)
        return {"findings": findings,
                "collected_at": snap.get("collected_at")}

    # -- server ----------------------------------------------------------

    async def run(self, ready_cb=None):
        from aiohttp import web

        def jroute(fn):
            async def handler(request):
                return web.json_response(await fn())
            return handler

        app = web.Application()
        app.router.add_get("/", lambda r: web.Response(
            text=_PAGE, content_type="text/html"))
        app.router.add_get("/api/nodes", jroute(self.nodes))
        app.router.add_get("/api/actors", jroute(self.actors))
        app.router.add_get("/api/metrics", jroute(self.metrics))
        app.router.add_get("/api/objects", jroute(self.objects))
        app.router.add_get("/api/timeline", jroute(self.timeline))
        app.router.add_get("/api/events", jroute(self.events))

        async def trace_handler(request):
            return web.json_response(await self.trace(
                trace_id=request.rel_url.query.get("trace_id")))

        async def history_handler(request):
            try:
                samples = int(request.rel_url.query.get("samples", 0))
            except ValueError:
                return web.json_response(
                    {"error": "samples must be an integer"}, status=400)
            return web.json_response(await self.metrics_history(samples))

        async def profile_handler(request):
            q = request.rel_url.query
            try:
                since = float(q["since"]) if "since" in q else None
            except ValueError:
                return web.json_response(
                    {"error": "since must be a unix timestamp"},
                    status=400)
            fmt = q.get("format", "collapsed")
            if fmt not in ("collapsed", "perfetto", "raw"):
                return web.json_response(
                    {"error": "format must be collapsed|perfetto|raw"},
                    status=400)
            return web.json_response(await self.profile(
                component=q.get("component"), since=since, fmt=fmt),
                dumps=lambda o: json.dumps(o, default=_hexify))

        app.router.add_get("/api/trace", trace_handler)
        app.router.add_get("/api/profile", profile_handler)
        app.router.add_get("/api/metrics/history", history_handler)

        async def state_handler(request):
            q = request.rel_url.query
            component = q.get("component")
            from ray_tpu._private.debug_state import COMPONENTS

            if component and component not in COMPONENTS:
                return web.json_response(
                    {"error": f"component must be one of {COMPONENTS}"},
                    status=400)
            try:
                return web.json_response(await self.state(
                    component=component,
                    include_workers=q.get("workers", "1") != "0"))
            except Exception as e:
                return web.json_response({"error": str(e)}, status=500)

        async def doctor_handler(request):
            try:
                return web.json_response(await self.doctor())
            except Exception as e:
                return web.json_response({"error": str(e)}, status=500)

        app.router.add_get("/api/state", state_handler)
        app.router.add_get("/api/doctor", doctor_handler)

        async def logs_handler(request):
            q = request.rel_url.query
            try:
                lines = int(q.get("lines", 200))
            except ValueError:
                return web.json_response(
                    {"error": f"lines={q.get('lines')!r} is not a "
                              f"number"}, status=400)
            try:
                return web.json_response(await self.logs(
                    node=q.get("node"), file=q.get("file"), lines=lines))
            except Exception as e:
                return web.json_response({"error": str(e)}, status=400)

        app.router.add_get("/api/logs", logs_handler)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, self.host, self.port)
        await site.start()
        self._site_port = site._server.sockets[0].getsockname()[1]
        if ready_cb:
            ready_cb(self._site_port)
        while True:
            await asyncio.sleep(3600)


def _hexify(obj):
    if isinstance(obj, bytes):
        return obj.hex()
    return repr(obj)


def main():
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8265)
    args = parser.parse_args()
    dash = Dashboard(args.gcs_address, args.host, args.port)
    asyncio.run(dash.run(ready_cb=lambda p: print(
        f"dashboard at http://{args.host}:{p}", flush=True)))


if __name__ == "__main__":
    main()
