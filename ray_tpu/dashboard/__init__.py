from ray_tpu.dashboard.dashboard import Dashboard

__all__ = ["Dashboard"]
