"""RemoteFunction — the `@ray_tpu.remote` wrapper for plain functions
(reference: python/ray/remote_function.py:27, _remote :169)."""

from __future__ import annotations

import cloudpickle

from ray_tpu._private import global_state


class RemoteFunction:
    def __init__(self, fn, *, num_returns=1, num_cpus=None, num_tpus=None,
                 resources=None, max_retries=None, accelerator_type=None):
        self._function = fn
        self._name = getattr(fn, "__qualname__", str(fn))
        self._num_returns = num_returns
        self._num_cpus = num_cpus
        self._num_tpus = num_tpus
        self._resources = resources or {}
        self._max_retries = max_retries
        self._accelerator_type = accelerator_type
        self._pickled = None
        self._fn_id = None
        # cached static spec prefix for the default-options hot path,
        # rebuilt if the core worker changed (re-init) — see
        # CoreWorker.make_task_template
        self._template = None
        self._template_cw = None
        self.__doc__ = fn.__doc__

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self._name} cannot be called directly; use "
            f"{self._name}.remote()."
        )

    def __getstate__(self):
        # A RemoteFunction can travel inside task args / actor state; the
        # cached spec template holds this process's CoreWorker (sockets,
        # threads) and must never be pickled with it.
        state = self.__dict__.copy()
        state["_template"] = None
        state["_template_cw"] = None
        return state

    def options(self, **opts):
        parent = self

        class _Wrapped:
            def remote(self, *args, **kwargs):
                return parent._remote(args, kwargs, opts)

        return _Wrapped()

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs, {})

    def _resources_dict(self, opts) -> dict:
        resources = dict(self._resources)
        resources.update(opts.get("resources") or {})
        num_cpus = opts.get("num_cpus", self._num_cpus)
        num_tpus = opts.get("num_tpus", self._num_tpus)
        resources["CPU"] = 1 if num_cpus is None else num_cpus
        if num_tpus:
            resources["TPU"] = num_tpus
        accel = opts.get("accelerator_type", self._accelerator_type)
        if accel:
            # constraint resource advertised by matching nodes (reference:
            # util/accelerators — accelerator_type:<name> sliver request)
            from ray_tpu.util.accelerators import accelerator_resource

            resources.setdefault(accelerator_resource(accel), 0.001)
        return resources

    def _remote(self, args, kwargs, opts):
        cw = global_state.require_core_worker()
        if self._fn_id is None:
            self._pickled = cloudpickle.dumps(self._function)
        fn_id = cw.export_function(self._pickled)
        self._fn_id = fn_id
        if not opts and not getattr(cw, "_legacy", False):
            # hot path: the whole static spec prefix (descriptor, owner,
            # quantized resources) is built once per (function, worker)
            # and submit pays one dict copy per call
            if self._template is None or self._template_cw is not cw:
                self._template = cw.make_task_template(
                    fn_id=fn_id,
                    name=self._name,
                    num_returns=self._num_returns,
                    resources=self._resources_dict(opts),
                    max_retries=self._max_retries,
                )
                self._template_cw = cw
            refs = cw.submit_task(args=args, kwargs=kwargs,
                                  template=self._template)
            if self._num_returns == 1:
                return refs[0]
            return refs
        num_returns = opts.get("num_returns", self._num_returns)
        pg = opts.get("placement_group")
        pg_id = None
        bundle_index = opts.get("placement_group_bundle_index", -1)
        if pg is not None:
            pg_id = pg.id.binary()
        refs = cw.submit_task(
            fn_id=fn_id,
            name=opts.get("name", self._name),
            args=args,
            kwargs=kwargs,
            num_returns=num_returns,
            resources=self._resources_dict(opts),
            max_retries=opts.get("max_retries", self._max_retries),
            placement_group=pg_id,
            bundle_index=bundle_index,
        )
        if num_returns == 1:
            return refs[0]
        return refs
