"""Native TPE searcher — Tree-structured Parzen Estimator (the algorithm
behind the reference's BOHB/hyperopt integrations:
python/ray/tune/suggest/bohb.py TuneBOHB, suggest/hyperopt.py — rebuilt
dependency-free; Bergstra et al. 2011).

After `n_initial` random configs, observed trials split into a top
`gamma` quantile ("good") and the rest ("bad"). Each dimension gets a
kernel-density model per split; candidates are drawn from the good model
and scored by the density ratio l_good/l_bad — the candidate maximizing
the ratio (highest expected improvement) is suggested next. Works
directly on the tune search-space Domains (sample.py): numeric domains
use Gaussian kernels (log-space for LogUniform), Choice uses smoothed
categorical counts."""

from __future__ import annotations

import math
import random

from ray_tpu.tune import sample as S
from ray_tpu.tune.search.searcher import Searcher


def _is_numeric(domain) -> bool:
    return isinstance(domain, (S.Uniform, S.LogUniform, S.Randint,
                               S.QRandint, S.Normal))


def _to_internal(domain, v: float) -> float:
    if isinstance(domain, S.LogUniform):
        return math.log(v, domain.base)
    return float(v)


def _from_internal(domain, z: float):
    if isinstance(domain, S.LogUniform):
        v = domain.base ** z
        return min(max(v, domain.lower), domain.upper)
    if isinstance(domain, S.Randint):
        return min(max(int(round(z)), domain.lower), domain.upper - 1)
    if isinstance(domain, S.QRandint):
        q = domain.q
        v = int(round(z / q)) * q
        return min(max(v, domain.lower), domain.upper)
    if isinstance(domain, S.Uniform):
        return min(max(z, domain.lower), domain.upper)
    return z


def _bounds(domain) -> tuple[float, float]:
    if isinstance(domain, S.LogUniform):
        return domain._log
    if isinstance(domain, S.Normal):
        return (domain.mean - 4 * domain.sd, domain.mean + 4 * domain.sd)
    hi = domain.upper - 1 if isinstance(domain, S.Randint) else domain.upper
    return (float(domain.lower), float(hi))


class _NumericKDE:
    """1-D Parzen window: Gaussians at each observation, clipped range."""

    def __init__(self, points: list[float], lo: float, hi: float):
        self.points = points
        self.lo, self.hi = lo, hi
        spread = (hi - lo) or 1.0
        # Scott-style bandwidth with a floor so singleton/tight clusters
        # still explore
        n = max(len(points), 1)
        self.bw = max(spread * n ** (-0.2) * 0.5, spread * 0.02)

    def sample(self, rng: random.Random) -> float:
        if not self.points:
            return rng.uniform(self.lo, self.hi)
        center = rng.choice(self.points)
        return min(max(rng.gauss(center, self.bw), self.lo), self.hi)

    def logpdf(self, x: float) -> float:
        if not self.points:
            return -math.log(self.hi - self.lo or 1.0)
        acc = 0.0
        inv = 1.0 / (self.bw * math.sqrt(2 * math.pi))
        for c in self.points:
            acc += inv * math.exp(-0.5 * ((x - c) / self.bw) ** 2)
        return math.log(acc / len(self.points) + 1e-300)


class _CategoricalModel:
    def __init__(self, values: list, categories: list):
        self.categories = categories
        counts = {i: 1.0 for i in range(len(categories))}  # +1 smoothing
        for v in values:
            counts[categories.index(v)] += 1.0
        total = sum(counts.values())
        self.probs = [counts[i] / total for i in range(len(categories))]

    def sample(self, rng: random.Random):
        return rng.choices(self.categories, weights=self.probs)[0]

    def logpdf(self, v) -> float:
        return math.log(self.probs[self.categories.index(v)] + 1e-300)


class TPESearcher(Searcher):
    def __init__(self, space: dict | None = None,
                 metric: str | None = None, mode: str | None = None,
                 n_initial: int = 10, gamma: float = 0.25,
                 n_candidates: int = 24, seed: int | None = None):
        super().__init__(metric, mode)
        self._space = dict(space or {})
        self.n_initial = n_initial
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._rng = random.Random(seed)
        self._live: dict[str, dict] = {}
        self._observed: list[tuple[dict, float]] = []

    def set_search_properties(self, metric, mode, config) -> bool:
        super().set_search_properties(metric, mode, config)
        if config:
            # pull Domain leaves out of a tune.run config dict
            for k, v in config.items():
                if isinstance(v, S.Domain) and k not in self._space:
                    self._space[k] = v
        return True

    def _random_config(self) -> dict:
        return {k: d.sample(self._rng) for k, d in self._space.items()}

    def _model_for(self, domain, rows: list):
        if isinstance(domain, S.Choice):
            return _CategoricalModel(rows, domain.categories)
        lo, hi = _bounds(domain)
        return _NumericKDE([_to_internal(domain, v) for v in rows], lo, hi)

    def suggest(self, trial_id: str) -> dict | None:
        if not self._space:
            raise ValueError("TPESearcher needs a search space (pass "
                             "`space=` or Domains in the run config)")
        if len(self._observed) < self.n_initial:
            config = self._random_config()
        else:
            ranked = sorted(self._observed, key=lambda p: p[1],
                            reverse=True)
            n_good = max(1, int(len(ranked) * self.gamma))
            good = [c for c, _ in ranked[:n_good]]
            bad = [c for c, _ in ranked[n_good:]] or good
            config = {}
            for key, domain in self._space.items():
                g = self._model_for(domain, [c[key] for c in good])
                b = self._model_for(domain, [c[key] for c in bad])
                if isinstance(domain, S.Choice):
                    cands = [g.sample(self._rng)
                             for _ in range(self.n_candidates)]
                    best = max(cands,
                               key=lambda v: g.logpdf(v) - b.logpdf(v))
                    config[key] = best
                else:
                    cands = [g.sample(self._rng)
                             for _ in range(self.n_candidates)]
                    best = max(cands,
                               key=lambda z: g.logpdf(z) - b.logpdf(z))
                    config[key] = _from_internal(domain, best)
        self._live[trial_id] = config
        return dict(config)

    def on_trial_complete(self, trial_id: str, result: dict | None = None,
                          error: bool = False):
        config = self._live.pop(trial_id, None)
        if config is None or error or not result:
            return
        if self.metric not in result:
            return
        v = float(result[self.metric])
        self._observed.append(
            (config, v if self.mode != "min" else -v))


# The reference exposes the TPE model through its BOHB integration
# (suggest/bohb.py TuneBOHB); same algorithm, so same name here.
TuneBOHB = TPESearcher
