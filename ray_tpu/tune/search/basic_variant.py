"""BasicVariantGenerator — grid × random search (reference:
python/ray/tune/suggest/basic_variant.py + suggest/variant_generator.py).

Resolution order matches the reference: grid_search entries form the cross
product; Domain objects are sampled per variant; sample_from Functions
resolve last against the materialized spec.
"""

from __future__ import annotations

import itertools
import random
from typing import Any

from ray_tpu.tune import sample as s
from ray_tpu.tune.search.searcher import Searcher


def _walk(config: dict, path=()):
    for key, value in config.items():
        p = path + (key,)
        if isinstance(value, dict) and not s.is_grid(value):
            yield from _walk(value, p)
        else:
            yield p, value


def _set(config: dict, path: tuple, value):
    node = config
    for key in path[:-1]:
        node = node[key]
    node[path[-1]] = value


def _deepcopy_spec(config):
    if isinstance(config, dict):
        return {k: _deepcopy_spec(v) for k, v in config.items()}
    if isinstance(config, list):
        return [_deepcopy_spec(v) for v in config]
    return config


def generate_variants(config: dict, rng: random.Random):
    """Yield concrete config dicts: cross-product of grids, then sampling."""
    grid_paths = [(p, v["grid_search"]) for p, v in _walk(config)
                  if s.is_grid(v)]
    grids = [vals for _, vals in grid_paths]
    for combo in itertools.product(*grids) if grids else [()]:
        spec = _deepcopy_spec(config)
        for (path, _), value in zip(grid_paths, combo):
            _set(spec, path, value)
        # sample plain domains
        deferred = []
        for path, value in list(_walk(spec)):
            if isinstance(value, s.Function):
                deferred.append((path, value))
            elif isinstance(value, s.Domain):
                _set(spec, path, value.sample(rng))
        for path, fn in deferred:
            _set(spec, path, fn.fn(spec))
        yield spec


class BasicVariantGenerator(Searcher):
    def __init__(self, config: dict | None = None, num_samples: int = 1,
                 seed: int | None = None):
        super().__init__()
        self._config = config or {}
        self._num_samples = num_samples
        self._seed = seed
        self._rng = random.Random(seed)
        self._iter = None
        self._consumed = 0
        self._finished = False

    def set_search_properties(self, metric, mode, config):
        super().set_search_properties(metric, mode, config)
        if config:
            self._config = config
        return True

    def _variants(self):
        for _ in range(self._num_samples):
            yield from generate_variants(self._config, self._rng)

    def suggest(self, trial_id):
        if self._iter is None:
            self._iter = self._variants()
        try:
            out = next(self._iter)
            self._consumed += 1
            return out
        except StopIteration:
            self._finished = True
            return None

    def is_finished(self):
        return self._finished

    # -- persistence (experiment resume): the live generator can't
    # pickle; persist the recipe + position, fast-forward on restore ----

    def get_state(self) -> dict:
        return {"config": self._config, "num_samples": self._num_samples,
                "seed": self._seed, "consumed": self._consumed,
                "finished": self._finished,
                "metric": self.metric, "mode": self.mode}

    def set_state(self, state: dict):
        self._config = state["config"]
        self._num_samples = state["num_samples"]
        self._seed = state["seed"]
        self.metric = state["metric"]
        self.mode = state["mode"]
        self._finished = state["finished"]
        self._rng = random.Random(self._seed)
        self._iter = self._variants()
        self._consumed = 0
        for _ in range(state["consumed"]):  # deterministic fast-forward
            self.suggest("__restore__")
