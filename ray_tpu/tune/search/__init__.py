"""Searchers (reference: python/ray/tune/suggest/) — Searcher protocol +
BasicVariantGenerator (grid × random sampling, suggest/basic_variant.py)."""

from ray_tpu.tune.search.basic_variant import BasicVariantGenerator
from ray_tpu.tune.search.searcher import (ConcurrencyLimiter, Repeater,
                                          SampleBudget, Searcher)
from ray_tpu.tune.search.tpe import TPESearcher, TuneBOHB

__all__ = ["BasicVariantGenerator", "ConcurrencyLimiter", "Repeater",
           "SampleBudget", "Searcher", "TPESearcher", "TuneBOHB"]
