"""Searchers (reference: python/ray/tune/suggest/) — Searcher protocol +
BasicVariantGenerator (grid × random sampling, suggest/basic_variant.py)."""

from ray_tpu.tune.search.basic_variant import BasicVariantGenerator
from ray_tpu.tune.search.searcher import ConcurrencyLimiter, Repeater, Searcher

__all__ = ["BasicVariantGenerator", "ConcurrencyLimiter", "Repeater",
           "Searcher"]
