"""Searcher protocol + wrappers (reference: python/ray/tune/suggest/
suggestion.py Searcher, suggest/repeater.py, suggest/concurrency_limiter)."""

from __future__ import annotations

from typing import Any


class Searcher:
    """suggest(trial_id) -> config | None (None = exhausted for now);
    on_trial_complete(trial_id, result) feeds the optimizer."""

    def __init__(self, metric: str | None = None, mode: str | None = None):
        self.metric = metric
        self.mode = mode

    def set_search_properties(self, metric: str | None, mode: str | None,
                              config: dict) -> bool:
        if self.metric is None:
            self.metric = metric
        if self.mode is None:
            self.mode = mode
        return True

    def suggest(self, trial_id: str) -> dict | None:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: dict):
        pass

    def on_trial_complete(self, trial_id: str, result: dict | None = None,
                          error: bool = False):
        pass

    def is_finished(self) -> bool:
        return False

    # -- persistence (reference: suggest/suggestion.py Searcher.save/
    # restore — experiment-level resume snapshots searcher state) -------

    def get_state(self) -> dict:
        """Default: the full __dict__ (fine for searchers whose state is
        plain data — TPE, median, etc.). Searchers holding live
        iterators/handles override."""
        return dict(self.__dict__)

    def set_state(self, state: dict):
        self.__dict__.update(state)


class _WrapperStateMixin:
    """get/set_state for searchers wrapping an inner searcher."""

    def get_state(self) -> dict:
        state = {k: v for k, v in self.__dict__.items()
                 if k != "searcher"}
        state["__inner__"] = self.searcher.get_state()
        return state

    def set_state(self, state: dict):
        inner = state.pop("__inner__", None)
        self.__dict__.update(state)
        if inner is not None:
            self.searcher.set_state(inner)


class SampleBudget(_WrapperStateMixin, Searcher):
    """Caps total suggestions at num_samples — gives model-based
    searchers (which never self-exhaust) the reference's
    tune.run(num_samples=N) stopping semantics (reference:
    suggest/search_generator.py SearchGenerator counts its trials)."""

    def __init__(self, searcher: Searcher, num_samples: int):
        super().__init__(searcher.metric, searcher.mode)
        self.searcher = searcher
        self.num_samples = num_samples
        self._suggested = 0

    def set_search_properties(self, metric, mode, config):
        ok = self.searcher.set_search_properties(metric, mode, config)
        self.metric = self.searcher.metric
        self.mode = self.searcher.mode
        return ok

    def suggest(self, trial_id):
        if self._suggested >= self.num_samples:
            return None
        config = self.searcher.suggest(trial_id)
        if config is not None:
            self._suggested += 1
        return config

    def on_trial_result(self, trial_id, result):
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False):
        self.searcher.on_trial_complete(trial_id, result, error)

    def is_finished(self):
        return (self._suggested >= self.num_samples
                or self.searcher.is_finished())


class ConcurrencyLimiter(_WrapperStateMixin, Searcher):
    """Caps concurrent unfinished suggestions (reference:
    suggest/suggestion.py ConcurrencyLimiter)."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        super().__init__(searcher.metric, searcher.mode)
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set[str] = set()

    def set_search_properties(self, metric, mode, config):
        return self.searcher.set_search_properties(metric, mode, config)

    def suggest(self, trial_id):
        if len(self._live) >= self.max_concurrent:
            return None
        config = self.searcher.suggest(trial_id)
        if config is not None:
            self._live.add(trial_id)
        return config

    def on_trial_result(self, trial_id, result):
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)

    def is_finished(self):
        return self.searcher.is_finished()


class Repeater(_WrapperStateMixin, Searcher):
    """Repeats each suggestion N times and reports the averaged metric to
    the wrapped searcher (reference: suggest/repeater.py)."""

    def __init__(self, searcher: Searcher, repeat: int):
        super().__init__(searcher.metric, searcher.mode)
        self.searcher = searcher
        self.repeat = repeat
        self._groups: dict[str, dict[str, Any]] = {}
        self._trial_group: dict[str, str] = {}
        self._pending: list[tuple[str, dict]] = []

    def set_search_properties(self, metric, mode, config):
        return self.searcher.set_search_properties(metric, mode, config)

    def suggest(self, trial_id):
        if not self._pending:
            base = self.searcher.suggest(trial_id)
            if base is None:
                return None
            group_id = trial_id
            self._groups[group_id] = {"config": base, "results": [],
                                      "outstanding": self.repeat}
            self._pending = [(group_id, base)] * self.repeat
        group_id, config = self._pending.pop(0)
        self._trial_group[trial_id] = group_id
        return dict(config)

    def on_trial_complete(self, trial_id, result=None, error=False):
        group_id = self._trial_group.pop(trial_id, None)
        if group_id is None:
            return
        group = self._groups[group_id]
        group["outstanding"] -= 1
        if result and self.searcher.metric in result:
            group["results"].append(result[self.searcher.metric])
        if group["outstanding"] == 0:
            vals = group["results"]
            avg = sum(vals) / len(vals) if vals else None
            final = dict(result or {})
            if avg is not None:
                final[self.searcher.metric] = avg
            self.searcher.on_trial_complete(group_id, final, error)
            del self._groups[group_id]

    def is_finished(self):
        return not self._pending and self.searcher.is_finished()
