"""Checkpoint/result syncing (reference: python/ray/tune/syncer.py
SyncConfig/Syncer + sync_client.py CommandBasedClient).

Mirrors each trial's logdir to an upload location so experiment state
survives the head node. Two modes:
- upload_dir on a mounted filesystem → built-in mirror copy (no deps)
- sync_template e.g. "rsync -a {source} {target}" → run the command
  (the reference's command-based sync client)
"""

from __future__ import annotations

import os
import shlex
import shutil
import subprocess
import time


class SyncConfig:
    def __init__(self, upload_dir: str | None = None,
                 sync_template: str | None = None,
                 sync_period: float = 300.0):
        self.upload_dir = upload_dir
        self.sync_template = sync_template
        self.sync_period = sync_period


class Syncer:
    def __init__(self, config: SyncConfig):
        self.config = config
        self._last_sync: dict[str, float] = {}

    def _target_for(self, logdir: str) -> str:
        return os.path.join(self.config.upload_dir,
                            os.path.basename(logdir.rstrip("/")))

    def sync_up(self, logdir: str, force: bool = False) -> bool:
        """Mirror `logdir` to the upload location. Rate-limited by
        sync_period unless force."""
        if not self.config.upload_dir or not os.path.isdir(logdir):
            return False
        now = time.monotonic()
        last = self._last_sync.get(logdir)
        if (not force and last is not None
                and now - last < self.config.sync_period):
            return False
        self._last_sync[logdir] = now
        target = self._target_for(logdir)
        if self.config.sync_template:
            cmd = self.config.sync_template.format(
                source=shlex.quote(logdir), target=shlex.quote(target))
            proc = subprocess.run(cmd, shell=True, capture_output=True)
            return proc.returncode == 0
        os.makedirs(target, exist_ok=True)
        shutil.copytree(logdir, target, dirs_exist_ok=True)
        return True

    def sync_down(self, logdir: str) -> bool:
        """Restore a trial logdir from the upload location (head-node
        recovery path)."""
        if not self.config.upload_dir:
            return False
        source = self._target_for(logdir)
        if not os.path.isdir(source):
            return False
        os.makedirs(logdir, exist_ok=True)
        shutil.copytree(source, logdir, dirs_exist_ok=True)
        return True
