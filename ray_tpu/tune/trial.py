"""Trial — one hyperparameter configuration's lifecycle state
(reference: python/ray/tune/trial.py)."""

from __future__ import annotations

import itertools

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"

_counter = itertools.count()


class Trial:
    def __init__(self, config: dict, trial_id: str | None = None,
                 experiment_tag: str = ""):
        self.trial_id = trial_id or f"trial_{next(_counter):05d}"
        self.config = config
        self.experiment_tag = experiment_tag
        self.status = PENDING
        self.last_result: dict = {}
        self.results: list[dict] = []
        self.checkpoint: bytes | None = None
        self.last_checkpoint_iter = -1
        self.error: str | None = None
        self.actor = None          # handle while RUNNING/PAUSED-with-actor
        self.inflight = None       # pending train.remote() ref
        self.pg = None             # PlacementGroup when PG-backed

    @property
    def iteration(self) -> int:
        return self.last_result.get("training_iteration", 0)

    def metric(self, name: str, default=None):
        return self.last_result.get(name, default)

    def __repr__(self):
        return (f"Trial({self.trial_id}, {self.status}, "
                f"it={self.iteration})")
