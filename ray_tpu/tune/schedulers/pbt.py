"""Population Based Training (reference: python/ray/tune/schedulers/pbt.py
PopulationBasedTraining — at each perturbation_interval, bottom-quantile
trials exploit (clone weights+config of) a top-quantile trial, then explore
(perturb hyperparameters ×1.2/×0.8 or resample)."""

from __future__ import annotations

import random

from ray_tpu.tune import sample as s
from ray_tpu.tune.schedulers.scheduler import TrialScheduler


class PopulationBasedTraining(TrialScheduler):
    def __init__(self, metric: str | None = None, mode: str = "max",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: dict | None = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: int | None = None):
        self._metric = metric
        self._mode = mode
        self._interval = perturbation_interval
        self._mutations = hyperparam_mutations or {}
        self._quantile = quantile_fraction
        self._resample_prob = resample_probability
        self._rng = random.Random(seed)
        self._last_perturb: dict[str, int] = {}
        # trial_id -> latest signed score
        self._scores: dict[str, float] = {}
        self.perturbations = 0  # exposed for tests/analysis

    def set_search_properties(self, metric, mode):
        if self._metric is None:
            self._metric = metric
        if mode:
            self._mode = mode
        return True

    def _signed(self, result):
        if self._metric not in result:
            return None
        v = float(result[self._metric])
        return v if self._mode == "max" else -v

    def _quantiles(self):
        ranked = sorted(self._scores, key=self._scores.get)
        k = max(1, int(len(ranked) * self._quantile))
        if len(ranked) < 2 * k:
            return [], []
        return ranked[:k], ranked[-k:]

    def _explore(self, config: dict) -> dict:
        new = dict(config)
        for key, spec in self._mutations.items():
            if self._rng.random() < self._resample_prob:
                if isinstance(spec, s.Domain):
                    new[key] = spec.sample(self._rng)
                elif isinstance(spec, (list, tuple)):
                    new[key] = self._rng.choice(list(spec))
                elif callable(spec):
                    new[key] = spec()
            elif isinstance(new.get(key), (int, float)):
                factor = 1.2 if self._rng.random() > 0.5 else 0.8
                new[key] = type(new[key])(new[key] * factor)
            elif isinstance(spec, (list, tuple)) and new.get(key) in spec:
                idx = list(spec).index(new[key])
                shift = self._rng.choice([-1, 1])
                new[key] = list(spec)[max(0, min(len(spec) - 1, idx + shift))]
        return new

    def on_trial_result(self, runner, trial, result):
        value = self._signed(result)
        if value is None:
            return self.CONTINUE
        self._scores[trial.trial_id] = value
        it = result.get("training_iteration", 0)
        last = self._last_perturb.get(trial.trial_id, 0)
        if it - last < self._interval:
            return self.CONTINUE
        # Quantiles are only meaningful once every *live* trial has
        # reported — otherwise early reporters exploit each other.
        # Terminated/errored trials (whose scores were dropped) must not
        # gate the rest of the population forever.
        live = {t.trial_id for t in runner.trials
                if t.status in ("PENDING", "RUNNING", "PAUSED")}
        if not live <= set(self._scores):
            return self.CONTINUE
        self._last_perturb[trial.trial_id] = it
        bottom, top = self._quantiles()
        if trial.trial_id not in bottom:
            return self.CONTINUE
        donor_id = self._rng.choice(top)
        donor = next(t for t in runner.trials if t.trial_id == donor_id)
        if (donor.actor is not None
                and donor.last_checkpoint_iter != donor.iteration):
            # Exploit-time checkpoint (reference pbt.py saves the donor on
            # demand) — don't depend on the runner's checkpoint_freq knob.
            try:
                import ray_tpu

                donor.checkpoint = ray_tpu.get(donor.actor.save.remote(),
                                               timeout=60)
                donor.last_checkpoint_iter = donor.iteration
            except Exception:
                pass
        if donor.checkpoint is None:
            return self.CONTINUE
        # exploit + explore: the runner restarts the trial from the donor's
        # checkpoint with the mutated config.
        trial.config = self._explore(donor.config)
        trial.checkpoint = donor.checkpoint
        self.perturbations += 1
        # After the restart the trial resumes from the donor's checkpoint,
        # so its training_iteration counter becomes the donor's. Record
        # _last_perturb on that counter — not the pre-restore one — or the
        # interval would be measured across two different counters.
        self._last_perturb[trial.trial_id] = (
            donor.last_checkpoint_iter
            if donor.last_checkpoint_iter >= 0 else it)
        return "PERTURB"  # runner treats as restart-with-new-config

    def on_trial_complete(self, runner, trial, result):
        self._scores.pop(trial.trial_id, None)

    def on_trial_error(self, runner, trial):
        # Never let a dead trial linger in the ranking as a donor.
        self._scores.pop(trial.trial_id, None)
