"""Median stopping rule (reference: python/ray/tune/schedulers/
median_stopping_rule.py): stop a trial whose best result so far is worse
than the median of other trials' running averages at the same iteration."""

from __future__ import annotations

import statistics
from collections import defaultdict

from ray_tpu.tune.schedulers.scheduler import TrialScheduler


class MedianStoppingRule(TrialScheduler):
    def __init__(self, metric: str | None = None, mode: str = "max",
                 grace_period: int = 1, min_samples_required: int = 3):
        self._metric = metric
        self._mode = mode
        self._grace = grace_period
        self._min_samples = min_samples_required
        # trial_id -> list of signed metric values per result
        self._history: dict[str, list[float]] = defaultdict(list)

    def set_search_properties(self, metric, mode):
        if self._metric is None:
            self._metric = metric
        if mode:
            self._mode = mode
        return True

    def _signed(self, result):
        if self._metric not in result:
            return None
        v = float(result[self._metric])
        return v if self._mode == "max" else -v

    def on_trial_result(self, runner, trial, result):
        value = self._signed(result)
        if value is None:
            return self.CONTINUE
        history = self._history[trial.trial_id]
        history.append(value)
        it = len(history)
        if it < self._grace:
            return self.CONTINUE
        # median of other trials' running means at this step count
        means = [
            statistics.fmean(h[:it])
            for tid, h in self._history.items()
            if tid != trial.trial_id and len(h) >= it
        ]
        if len(means) < self._min_samples:
            return self.CONTINUE
        if max(history) < statistics.median(means):
            return self.STOP
        return self.CONTINUE
