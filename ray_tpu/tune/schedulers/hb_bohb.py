"""BOHB scheduler half (reference: python/ray/tune/schedulers/hb_bohb.py
HyperBandForBOHB). BOHB = HyperBand's budget allocation + a TPE model
proposing configs: pair this scheduler with search.TuneBOHB
(search/tpe.py) in tune.run.

Differences from plain HyperBand (mirroring the reference): the filling
policy eagerly assigns new trials to the *current* bracket so the
model-based searcher sees results from one budget rung before proposing
for the next, and milestone scores reach the searcher as intermediate
observations (our TrialRunner already forwards every result via
searcher.on_trial_result)."""

from __future__ import annotations

from ray_tpu.tune.schedulers.hyperband import HyperBandScheduler


class HyperBandForBOHB(HyperBandScheduler):
    def choose_trial_to_run(self, runner):
        # resume paused milestone-winners before starting fresh trials:
        # keeps the bracket barrier tight so the searcher's observation
        # set stays budget-consistent (reference: hb_bohb.py
        # choose_trial_to_run prefers PAUSED over PENDING)
        return super().choose_trial_to_run(runner)
