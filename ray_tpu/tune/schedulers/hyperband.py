"""Synchronous HyperBand (reference: python/ray/tune/schedulers/
hyperband.py HyperBandScheduler; Li et al. 2016).

Trials fill brackets; each bracket successively halves at milestones
r, r*eta, r*eta^2, ... ≤ max_t: when every live trial in a bracket has
reached the current milestone (trials PAUSE as they arrive), the bottom
(1 - 1/eta) are stopped and the top 1/eta resume. Unlike ASHA (asha.py),
halving is a barrier — no promotion on stale comparisons."""

from __future__ import annotations

import math

from ray_tpu.tune.schedulers.scheduler import TrialScheduler


class _Bracket:
    def __init__(self, initial_t: int, max_t: int, eta: float, size: int):
        self.milestone = initial_t
        self.max_t = max_t
        self.eta = eta
        self.capacity = size
        self.trial_ids: list[str] = []
        self.paused_scores: dict[str, float] = {}
        self.dropped: set[str] = set()

    @property
    def full(self) -> bool:
        return len(self.trial_ids) >= self.capacity

    def live_ids(self) -> set[str]:
        return set(self.trial_ids) - self.dropped

    def ready_to_halve(self) -> bool:
        live = self.live_ids()
        return bool(live) and live <= set(self.paused_scores)

    def halve(self) -> tuple[set[str], set[str]]:
        """-> (resume_ids, stop_ids); advances the milestone."""
        live = sorted(self.live_ids(), key=self.paused_scores.get,
                      reverse=True)
        keep = max(1, int(len(live) / self.eta))
        resume, stop = set(live[:keep]), set(live[keep:])
        self.dropped |= stop
        self.paused_scores = {}
        self.milestone = int(self.milestone * self.eta)
        return resume, stop


class HyperBandScheduler(TrialScheduler):
    def __init__(self, metric: str | None = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 max_t: int = 81, reduction_factor: float = 3.0):
        self._metric = metric
        self._mode = mode
        self._time_attr = time_attr
        self._max_t = max_t
        self._eta = reduction_factor
        self._brackets: list[_Bracket] = []
        self._trial_bracket: dict[str, _Bracket] = {}
        self._s_next = self._s_max = int(
            math.log(max_t) / math.log(reduction_factor))
        self._resumable: set[str] = set()

    def set_search_properties(self, metric, mode):
        if self._metric is None:
            self._metric = metric
        if mode:
            self._mode = mode
        return True

    def _signed(self, result):
        if self._metric not in result:
            return None
        v = float(result[self._metric])
        return v if self._mode == "max" else -v

    def _new_bracket(self) -> _Bracket:
        s = self._s_next
        self._s_next = self._s_next - 1 if self._s_next > 0 else self._s_max
        n = int(math.ceil((self._s_max + 1) / (s + 1) * self._eta ** s))
        r = max(1, int(self._max_t * self._eta ** (-s)))
        return _Bracket(initial_t=r, max_t=self._max_t, eta=self._eta,
                        size=n)

    def on_trial_add(self, runner, trial):
        if not self._brackets or self._brackets[-1].full:
            self._brackets.append(self._new_bracket())
        bracket = self._brackets[-1]
        bracket.trial_ids.append(trial.trial_id)
        self._trial_bracket[trial.trial_id] = bracket

    def on_trial_result(self, runner, trial, result):
        bracket = self._trial_bracket.get(trial.trial_id)
        if bracket is None:
            return self.CONTINUE
        t = result.get(self._time_attr, 0)
        if t >= bracket.max_t:
            return self.STOP
        if t < bracket.milestone:
            return self.CONTINUE
        value = self._signed(result)
        if value is None:
            return self.CONTINUE
        bracket.paused_scores[trial.trial_id] = value
        if bracket.ready_to_halve():
            resume, stop = bracket.halve()
            resume.discard(trial.trial_id)  # this one continues inline
            self._resumable |= resume
            for other in runner.trials:
                if other.trial_id in stop and other.status in (
                        "RUNNING", "PAUSED", "PENDING"):
                    if other is not trial:
                        runner._stop_trial(other, "TERMINATED")
            if trial.trial_id in stop:
                return self.STOP
            return self.CONTINUE
        return self.PAUSE

    def on_trial_complete(self, runner, trial, result):
        self._cleanup(trial)

    def on_trial_error(self, runner, trial):
        # A dead trial must not block its bracket's barrier forever.
        bracket = self._trial_bracket.get(trial.trial_id)
        if bracket is not None:
            bracket.dropped.add(trial.trial_id)
        self._cleanup(trial)

    def _cleanup(self, trial):
        bracket = self._trial_bracket.pop(trial.trial_id, None)
        if bracket is not None:
            bracket.paused_scores.pop(trial.trial_id, None)
            bracket.dropped.add(trial.trial_id)
        self._resumable.discard(trial.trial_id)

    def choose_trial_to_run(self, runner):
        from ray_tpu.tune.trial import PAUSED, PENDING

        for trial in runner.trials:
            if trial.status == PAUSED and trial.trial_id in self._resumable:
                self._resumable.discard(trial.trial_id)
                return trial
        for trial in runner.trials:
            if trial.status == PENDING:
                return trial
        return None
