"""ASHA — Asynchronous Successive Halving (reference:
python/ray/tune/schedulers/async_hyperband.py AsyncHyperBandScheduler:
brackets of rungs at r, r*η, r*η², ...; a trial reaching a rung continues
only if its metric is in the top 1/η of completions at that rung)."""

from __future__ import annotations

from ray_tpu.tune.schedulers.scheduler import TrialScheduler


class _Bracket:
    def __init__(self, min_t: int, max_t: int, reduction_factor: float,
                 stop_last_trials: bool = True):
        self.rf = reduction_factor
        self._rungs = []  # [(milestone, {trial_id: metric})], descending
        milestone = min_t
        while milestone < max_t:
            self._rungs.append((milestone, {}))
            milestone = int(milestone * reduction_factor)
        self._rungs.reverse()

    def on_result(self, trial_id: str, cur_iter: int, metric: float) -> bool:
        """True = continue, False = stop."""
        keep = True
        for milestone, recorded in self._rungs:
            if cur_iter < milestone or trial_id in recorded:
                continue
            recorded[trial_id] = metric
            vals = sorted(recorded.values(), reverse=True)
            cutoff_idx = max(0, int(len(vals) / self.rf) - 1)
            cutoff = vals[cutoff_idx]
            if metric < cutoff:
                keep = False
            break
        return keep


class ASHAScheduler(TrialScheduler):
    def __init__(self, metric: str | None = None, mode: str = "max",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 4, brackets: int = 1):
        self._metric = metric
        self._mode = mode
        self._max_t = max_t
        self._grace = grace_period
        self._rf = reduction_factor
        self._brackets = [
            _Bracket(grace_period * int(reduction_factor ** i), max_t,
                     reduction_factor)
            for i in range(brackets)
        ]
        self._trial_bracket: dict[str, _Bracket] = {}
        self._counter = 0

    def set_search_properties(self, metric, mode):
        if self._metric is None:
            self._metric = metric
        if mode:
            self._mode = mode
        return True

    def _signed(self, result: dict) -> float | None:
        if self._metric not in result:
            return None
        v = float(result[self._metric])
        return v if self._mode == "max" else -v

    def on_trial_add(self, runner, trial):
        bracket = self._brackets[self._counter % len(self._brackets)]
        self._counter += 1
        self._trial_bracket[trial.trial_id] = bracket

    def on_trial_result(self, runner, trial, result):
        value = self._signed(result)
        it = result.get("training_iteration", 0)
        if value is None:
            return self.CONTINUE
        if it >= self._max_t:
            return self.STOP
        bracket = self._trial_bracket[trial.trial_id]
        return self.CONTINUE if bracket.on_result(
            trial.trial_id, it, value) else self.STOP

    def on_trial_complete(self, runner, trial, result):
        value = self._signed(result or {})
        if value is None:
            return
        bracket = self._trial_bracket.get(trial.trial_id)
        if bracket is not None:
            bracket.on_result(trial.trial_id,
                              result.get("training_iteration", self._max_t),
                              value)


# Reference alias (async_hyperband.py exports both names).
AsyncHyperBandScheduler = ASHAScheduler
