"""Trial schedulers (reference: python/ray/tune/schedulers/)."""

from ray_tpu.tune.schedulers.asha import ASHAScheduler, AsyncHyperBandScheduler
from ray_tpu.tune.schedulers.hb_bohb import HyperBandForBOHB
from ray_tpu.tune.schedulers.hyperband import HyperBandScheduler
from ray_tpu.tune.schedulers.median_stopping import MedianStoppingRule
from ray_tpu.tune.schedulers.pb2 import PB2
from ray_tpu.tune.schedulers.pbt import PopulationBasedTraining
from ray_tpu.tune.schedulers.scheduler import FIFOScheduler, TrialScheduler

__all__ = [
    "ASHAScheduler",
    "AsyncHyperBandScheduler",
    "FIFOScheduler",
    "HyperBandForBOHB",
    "HyperBandScheduler",
    "MedianStoppingRule",
    "PB2",
    "PopulationBasedTraining",
    "TrialScheduler",
]
