"""PB2 — Population Based Bandits (reference: python/ray/tune/schedulers/
pb2.py; Parker-Holder et al. 2020).

PBT's exploit step, but explore selects new hyperparameters by a
GP-bandit: fit a Gaussian process on (hyperparams, time) -> score-change
history and pick the UCB-maximizing point inside the search bounds.
The reference leans on GPy; here the GP is ~40 lines of numpy (RBF
kernel, jittered Cholesky), which is all PB2 needs."""

from __future__ import annotations

import numpy as np

from ray_tpu.tune.schedulers.pbt import PopulationBasedTraining


class _GP:
    """RBF-kernel GP regression with fixed hyperparameters."""

    def __init__(self, lengthscale: float = 0.3, signal: float = 1.0,
                 noise: float = 1e-2):
        self.ls = lengthscale
        self.sig = signal
        self.noise = noise

    def _k(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return self.sig * np.exp(-0.5 * d2 / self.ls ** 2)

    def fit(self, x: np.ndarray, y: np.ndarray):
        self.x = x
        k = self._k(x, x) + self.noise * np.eye(len(x))
        self.l_chol = np.linalg.cholesky(k)
        self.alpha = np.linalg.solve(
            self.l_chol.T, np.linalg.solve(self.l_chol, y))

    def predict(self, xq: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        ks = self._k(xq, self.x)
        mean = ks @ self.alpha
        v = np.linalg.solve(self.l_chol, ks.T)
        var = np.clip(self.sig - (v ** 2).sum(0), 1e-8, None)
        return mean, np.sqrt(var)


class PB2(PopulationBasedTraining):
    """hyperparam_bounds: {key: (low, high)} continuous ranges."""

    def __init__(self, metric: str | None = None, mode: str = "max",
                 perturbation_interval: int = 5,
                 hyperparam_bounds: dict | None = None,
                 quantile_fraction: float = 0.25,
                 log_scale: bool = True,
                 seed: int | None = None):
        if not hyperparam_bounds:
            raise ValueError("PB2 requires hyperparam_bounds")
        super().__init__(metric=metric, mode=mode,
                         perturbation_interval=perturbation_interval,
                         hyperparam_mutations={},
                         quantile_fraction=quantile_fraction,
                         seed=seed)
        self._bounds = {k: (float(lo), float(hi))
                        for k, (lo, hi) in hyperparam_bounds.items()}
        self._log = log_scale
        self._np_rng = np.random.RandomState(seed)
        # rows: (normalized hp vector, t, score before), score after
        self._history: list[tuple[np.ndarray, float, float]] = []
        self._prev_score: dict[str, tuple[float, dict]] = {}

    # -- data collection -------------------------------------------------

    def _vec(self, config: dict) -> np.ndarray:
        out = []
        for k, (lo, hi) in self._bounds.items():
            v = float(config.get(k, lo))
            if self._log and lo > 0:
                out.append((np.log(v) - np.log(lo))
                           / max(1e-12, np.log(hi) - np.log(lo)))
            else:
                out.append((v - lo) / max(1e-12, hi - lo))
        return np.clip(np.array(out), 0.0, 1.0)

    def _unvec(self, z: np.ndarray) -> dict:
        out = {}
        for zi, (k, (lo, hi)) in zip(z, self._bounds.items()):
            if self._log and lo > 0:
                out[k] = float(np.exp(
                    np.log(lo) + zi * (np.log(hi) - np.log(lo))))
            else:
                out[k] = float(lo + zi * (hi - lo))
        return out

    def on_trial_result(self, runner, trial, result):
        value = self._signed(result)
        if value is not None:
            prev = self._prev_score.get(trial.trial_id)
            if prev is not None:
                prev_val, prev_cfg = prev
                self._history.append(
                    (self._vec(prev_cfg), value - prev_val, 0.0))
            self._prev_score[trial.trial_id] = (value, dict(trial.config))
        return super().on_trial_result(runner, trial, result)

    # -- GP-bandit explore (the PB2 difference) --------------------------

    def _explore(self, config: dict) -> dict:
        new = dict(config)
        n_dims = len(self._bounds)
        cands = self._np_rng.random_sample((64, n_dims))
        if len(self._history) >= 4:
            x = np.stack([h[0] for h in self._history[-100:]])
            y = np.array([h[1] for h in self._history[-100:]])
            std = y.std()
            y = (y - y.mean()) / (std + 1e-8)
            gp = _GP()
            try:
                gp.fit(x, y)
                mean, sd = gp.predict(cands)
                best = cands[int(np.argmax(mean + 1.0 * sd))]  # UCB, k=1
            except np.linalg.LinAlgError:
                best = cands[0]
        else:
            best = cands[0]
        new.update(self._unvec(best))
        return new
