"""TrialScheduler protocol (reference: python/ray/tune/schedulers/
trial_scheduler.py) — decisions the runner acts on after each result."""

from __future__ import annotations

CONTINUE = "CONTINUE"
PAUSE = "PAUSE"
STOP = "STOP"


class TrialScheduler:
    CONTINUE = CONTINUE
    PAUSE = PAUSE
    STOP = STOP

    def set_search_properties(self, metric: str | None,
                              mode: str | None) -> bool:
        return True

    def on_trial_add(self, runner, trial):
        pass

    def on_trial_result(self, runner, trial, result: dict) -> str:
        return CONTINUE

    def on_trial_complete(self, runner, trial, result: dict):
        pass

    def on_trial_error(self, runner, trial):
        pass

    def choose_trial_to_run(self, runner):
        """Pick the next PENDING/PAUSED trial to (re)start, or None."""
        from ray_tpu.tune.trial import PAUSED, PENDING

        for trial in runner.trials:
            if trial.status == PENDING:
                return trial
        for trial in runner.trials:
            if trial.status == PAUSED:
                return trial
        return None


class FIFOScheduler(TrialScheduler):
    """Run every trial to completion in submission order (reference:
    trial_scheduler.py FIFOScheduler)."""
