"""TrialRunner — the tune event loop (reference: python/ray/tune/
trial_runner.py:145, step :456; executor: ray_trial_executor.py:138 —
trials run as remote actors; results fetched with ray_tpu.wait)."""

from __future__ import annotations

import logging
import time

import cloudpickle

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu.tune.schedulers.scheduler import FIFOScheduler
from ray_tpu.tune.trial import (
    ERROR, PAUSED, PENDING, RUNNING, TERMINATED, Trial,
)

logger = logging.getLogger("ray_tpu.tune")


class _TrainableActor:
    """The remote shell holding one Trainable instance (reference:
    ray_trial_executor.py:496 start_trial)."""

    def __init__(self, trainable_cls_pickled: bytes, config: dict):
        cls = cloudpickle.loads(trainable_cls_pickled)
        self._trainable = cls(config)

    def train(self):
        return self._trainable.train()

    def save(self):
        return self._trainable.save()

    def restore(self, blob: bytes):
        self._trainable.restore(blob)
        return True

    def reset(self, new_config: dict) -> bool:
        ok = self._trainable.reset_config(new_config)
        if ok:
            self._trainable.config = new_config
        return bool(ok)

    def stop(self):
        try:
            self._trainable.stop()
        finally:
            ray_tpu.exit_actor()


class TrialRunner:
    def __init__(self, trainable_cls, *, search_alg, scheduler=None,
                 metric: str | None = None, mode: str = "max",
                 stop: dict | None = None,
                 max_concurrent_trials: int = 0,
                 resources_per_trial=None,
                 checkpoint_freq: int = 0,
                 max_failures: int = 0,
                 local_dir: str | None = None,
                 loggers=None,
                 progress_reporter=None,
                 sync_config=None):
        from ray_tpu.tune.placement_groups import PlacementGroupFactory

        self._trainable_cls = trainable_cls
        self._pickled_cls = cloudpickle.dumps(trainable_cls)
        self._search = search_alg
        self._scheduler = scheduler or FIFOScheduler()
        self._metric = metric
        self._mode = mode
        self._stop = stop or {}
        self._max_concurrent = max_concurrent_trials
        # dict resources, or a PlacementGroupFactory (reference:
        # tune/utils/placement_groups.py) reserving a group per trial.
        self._pg_factory = (resources_per_trial
                            if isinstance(resources_per_trial,
                                          PlacementGroupFactory) else None)
        if self._pg_factory is not None:
            self._resources = dict(self._pg_factory.head_bundle)
        else:
            self._resources = dict(resources_per_trial or {"CPU": 1})
        self._checkpoint_freq = checkpoint_freq
        self._max_failures = max_failures
        self._failures: dict[str, int] = {}
        self._local_dir = local_dir
        self._logger_classes = loggers
        self._loggers: dict[str, object] = {}
        self._reporter = progress_reporter
        self._syncer = None
        if sync_config is not None and sync_config.upload_dir:
            from ray_tpu.tune.syncer import Syncer

            self._syncer = Syncer(sync_config)
        self.trials: list[Trial] = []
        self._search.set_search_properties(metric, mode, None)
        self._scheduler.set_search_properties(metric, mode)

    def _logger_for(self, trial: Trial):
        if self._local_dir is None and self._logger_classes is None:
            return None
        lg = self._loggers.get(trial.trial_id)
        if lg is None:
            import os

            from ray_tpu.tune.logger import DEFAULT_LOGGERS, UnifiedLogger

            base = self._local_dir or "/tmp/ray_tpu_results"
            lg = UnifiedLogger(
                os.path.join(base, trial.trial_id), trial.config,
                loggers=self._logger_classes or DEFAULT_LOGGERS)
            self._loggers[trial.trial_id] = lg
        return lg

    # -- trial lifecycle -------------------------------------------------

    def _next_trial(self) -> Trial | None:
        trial_id = f"trial_{len(self.trials):05d}"
        config = self._search.suggest(trial_id)
        if config is None:
            return None
        trial = Trial(config, trial_id=trial_id)
        self.trials.append(trial)
        self._scheduler.on_trial_add(self, trial)
        return trial

    def _start_trial(self, trial: Trial):
        actor_cls = ray_tpu.remote(resources=dict(self._resources))(
            _TrainableActor)
        if self._pg_factory is not None:
            trial.pg = self._pg_factory.create()
            trial.actor = actor_cls.options(
                placement_group=trial.pg,
                placement_group_bundle_index=0).remote(
                self._pickled_cls, dict(trial.config))
        else:
            trial.actor = actor_cls.remote(self._pickled_cls,
                                           dict(trial.config))
        if trial.checkpoint is not None:
            trial.actor.restore.remote(trial.checkpoint)
        trial.status = RUNNING
        trial.inflight = trial.actor.train.remote()

    def _stop_trial(self, trial: Trial, status: str):
        trial.status = status
        trial.inflight = None
        if trial.actor is not None:
            try:
                trial.actor.stop.remote()
            except Exception:
                pass
            trial.actor = None
        pg = getattr(trial, "pg", None)
        if pg is not None:
            from ray_tpu.util.placement_group import remove_placement_group

            try:
                remove_placement_group(pg)
            except Exception:
                pass
            trial.pg = None
        if status in (TERMINATED, ERROR):
            lg = self._loggers.pop(trial.trial_id, None)
            if lg is not None:
                lg.close()

    def _pause_trial(self, trial: Trial):
        if trial.last_checkpoint_iter != trial.iteration:
            try:
                trial.checkpoint = ray_tpu.get(trial.actor.save.remote(),
                                               timeout=60)
                trial.last_checkpoint_iter = trial.iteration
            except Exception:
                pass
        self._stop_trial(trial, PAUSED)

    def _running(self) -> list[Trial]:
        return [t for t in self.trials if t.status == RUNNING]

    def _live_slots(self) -> int:
        if self._max_concurrent:
            return self._max_concurrent - len(self._running())
        cpus = ray_tpu.cluster_resources().get("CPU", 1)
        need = self._resources.get("CPU", 1) or 1
        return max(1, int(cpus // need)) - len(self._running())

    # -- event loop ------------------------------------------------------

    def is_finished(self) -> bool:
        active = any(t.status in (PENDING, RUNNING, PAUSED)
                     for t in self.trials)
        return not active and self._search.is_finished()

    def step(self):
        # 1. launch new/paused work while slots are free (resource view
        # fetched once per step, not per launch)
        slots = self._live_slots()
        while slots > 0:
            trial = self._scheduler.choose_trial_to_run(self)
            if trial is None:
                trial = self._next_trial()
                if trial is None:
                    break
            try:
                self._start_trial(trial)
            except Exception as e:
                # e.g. the trial's placement group can't be reserved right
                # now: count it as a trial failure, keep the experiment
                # (and its other trials) alive.
                self._failures[trial.trial_id] = (
                    self._failures.get(trial.trial_id, 0) + 1)
                if self._failures[trial.trial_id] > self._max_failures:
                    trial.error = f"start failed: {e}"
                    self._stop_trial(trial, ERROR)
                    self._scheduler.on_trial_error(self, trial)
                    self._search.on_trial_complete(trial.trial_id, None,
                                                   error=True)
                else:
                    logger.warning("trial %s failed to start (%s); "
                                   "will retry", trial.trial_id, e)
                    self._stop_trial(trial, PENDING)
                break
            slots -= 1
        running = self._running()
        if not running:
            return
        # 2. wait for any result
        refs = [t.inflight for t in running]
        ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=1.0)
        for ref in ready:
            trial = next(t for t in running if t.inflight == ref)
            self._handle_result(trial, ref)

    def _handle_result(self, trial: Trial, ref):
        try:
            result = ray_tpu.get(ref, timeout=60)
        except (exc.TaskError, exc.ActorDiedError, exc.WorkerCrashedError,
                exc.GetTimeoutError, exc.ObjectLostError) as e:
            self._failures[trial.trial_id] = (
                self._failures.get(trial.trial_id, 0) + 1)
            if self._failures[trial.trial_id] <= self._max_failures:
                logger.warning("trial %s failed (%s); restarting",
                               trial.trial_id, e)
                self._stop_trial(trial, PENDING)
            else:
                trial.error = str(e)
                self._stop_trial(trial, ERROR)
                self._scheduler.on_trial_error(self, trial)
                self._search.on_trial_complete(trial.trial_id, None,
                                               error=True)
            return
        trial.last_result = result
        trial.results.append(result)
        lg = self._logger_for(trial)
        if lg is not None:
            lg.on_result(result)
        if self._syncer is not None and self._local_dir:
            import os

            self._syncer.sync_up(
                os.path.join(self._local_dir, trial.trial_id))
        self._search.on_trial_result(trial.trial_id, result)
        if (self._checkpoint_freq
                and trial.iteration % self._checkpoint_freq == 0):
            try:
                trial.checkpoint = ray_tpu.get(trial.actor.save.remote(),
                                               timeout=60)
                trial.last_checkpoint_iter = trial.iteration
            except Exception:
                pass
        if result.get("done") or self._should_stop(result):
            self._complete_trial(trial, result)
            return
        decision = self._scheduler.on_trial_result(self, trial, result)
        if decision == self._scheduler.STOP:
            self._complete_trial(trial, result)
        elif decision == self._scheduler.PAUSE:
            self._pause_trial(trial)
        elif decision == "PERTURB":
            # PBT exploit/explore: prefer in-place reset_config (no actor
            # restart); fall back to restarting from the donor checkpoint
            # the scheduler stashed on the trial.
            reused = False
            try:
                reused = ray_tpu.get(
                    trial.actor.reset.remote(dict(trial.config)), timeout=60)
                if reused and trial.checkpoint is not None:
                    ray_tpu.get(trial.actor.restore.remote(trial.checkpoint),
                                timeout=60)
            except Exception:
                reused = False
            if reused:
                trial.inflight = trial.actor.train.remote()
            else:
                self._stop_trial(trial, PENDING)
        else:
            trial.inflight = trial.actor.train.remote()

    def _should_stop(self, result: dict) -> bool:
        return any(result.get(k, float("-inf")) >= v
                   for k, v in self._stop.items())

    def _complete_trial(self, trial: Trial, result: dict):
        self._scheduler.on_trial_complete(self, trial, result)
        self._search.on_trial_complete(trial.trial_id, result)
        self._stop_trial(trial, TERMINATED)
        if self._syncer is not None and self._local_dir:
            import os

            self._syncer.sync_up(
                os.path.join(self._local_dir, trial.trial_id), force=True)

    # -- experiment-level checkpoint/resume ------------------------------
    # (reference: trial_runner.py checkpoint() + tune.run(resume=True))

    def _experiment_state_path(self) -> str | None:
        if not self._local_dir:
            return None
        import os

        return os.path.join(self._local_dir, "experiment_state.pkl")

    def _experiment_fingerprint(self) -> tuple:
        return tuple((t.trial_id, t.status, t.iteration,
                      id(t.checkpoint)) for t in self.trials)

    # reference: trial_runner checkpoints at most every
    # TUNE_GLOBAL_CHECKPOINT_S (10s) — checkpoints can be large
    _save_period_s = 10.0

    def save_experiment_state(self, force: bool = False):
        """Snapshot every trial's config/status/last checkpoint AND the
        searcher's own state so a killed driver can resume the sweep.
        Skipped when nothing changed, rate-limited to _save_period_s,
        and NEVER allowed to kill the sweep (persistence is a
        side-channel; serialization failures log once and disable it)."""
        path = self._experiment_state_path()
        if path is None or getattr(self, "_save_disabled", False):
            return
        fp = self._experiment_fingerprint()
        if fp == getattr(self, "_last_saved_fp", None):
            return
        now = time.monotonic()
        if (not force and now - getattr(self, "_last_save_t", 0.0)
                < self._save_period_s):
            return
        import os

        try:
            state = {
                "trials": [{
                    "trial_id": t.trial_id,
                    "config": t.config,
                    "status": t.status,
                    "last_result": t.last_result,
                    "checkpoint": t.checkpoint,
                    "error": t.error,
                } for t in self.trials],
                "searcher": self._search.get_state(),
            }
            os.makedirs(self._local_dir, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                cloudpickle.dump(state, f)
            os.replace(tmp, path)
            self._last_saved_fp = fp
            self._last_save_t = now
        except Exception as e:
            self._save_disabled = True
            logger.warning(
                "experiment-state persistence disabled: %s (resume will "
                "not be available for this run)", e)

    def restore_experiment_state(self) -> bool:
        """Load a prior run's state: finished trials keep their results,
        interrupted ones re-queue from their last checkpoint, and the
        searcher resumes exactly where it stopped (its own persisted
        state — no replay; reference: Searcher.save/restore). Returns
        False when no usable state file exists."""
        import os

        path = self._experiment_state_path()
        if path is None or not os.path.exists(path):
            return False
        try:
            with open(path, "rb") as f:
                state = cloudpickle.load(f)
        except Exception as e:
            # an EXISTING state file that won't load must not be
            # silently clobbered by the next save — surface it
            raise RuntimeError(
                f"resume=True but {path} failed to load ({e}); move or "
                f"delete it to start fresh") from e
        self._search.set_state(state["searcher"])
        for rec in state["trials"]:
            trial = Trial(rec["config"], trial_id=rec["trial_id"])
            trial.last_result = rec["last_result"]
            trial.checkpoint = rec["checkpoint"]
            trial.error = rec["error"]
            if rec["status"] in (TERMINATED, ERROR):
                trial.status = rec["status"]
                # distinguishes prior-run failures from this run's
                # (tune.run's raise_on_failed_trial ignores restored)
                trial.restored = True
            else:
                trial.status = PENDING  # interrupted: restart from ckpt
                # the searcher still counts it live; completion arrives
                # when the resumed trial finishes this run
            self.trials.append(trial)
            self._scheduler.on_trial_add(self, trial)
            if trial.status == TERMINATED and trial.last_result:
                # rebuild what scheduler state we can (rung records etc.);
                # mid-rung pauses/brackets are NOT reconstructed — a
                # resumed ASHA/PBT sweep schedules fresh from here
                self._scheduler.on_trial_complete(self, trial,
                                                  trial.last_result)
        return True

    def run(self):
        while not self.is_finished():
            self.step()
            self.save_experiment_state()
            if self._reporter is not None and self._reporter.should_report():
                self._reporter.report(self.trials)
        # final sweep: make sure nothing is left running
        for trial in self.trials:
            if trial.status in (RUNNING, PAUSED, PENDING):
                self._stop_trial(trial, TERMINATED)
        self.save_experiment_state(force=True)
        for lg in self._loggers.values():
            lg.close()
        self._loggers.clear()
        if self._reporter is not None:
            self._reporter.report(self.trials, done=True)
        time.sleep(0.05)
