"""Search-space primitives (reference: python/ray/tune/sample.py —
uniform/loguniform/choice/randint/qrandint/grid_search plus .sample()).

A config dict may contain Domain objects and {"grid_search": [...]} markers;
the basic-variant searcher resolves them into concrete configs.
"""

from __future__ import annotations

import random
from typing import Any, Sequence


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, lower: float, upper: float):
        self.lower, self.upper = lower, upper

    def sample(self, rng):
        return rng.uniform(self.lower, self.upper)


class LogUniform(Domain):
    def __init__(self, lower: float, upper: float, base: float = 10):
        import math

        if lower <= 0:
            raise ValueError("loguniform requires lower > 0")
        self.lower, self.upper, self.base = lower, upper, base
        self._log = (math.log(lower, base), math.log(upper, base))

    def sample(self, rng):
        return self.base ** rng.uniform(*self._log)


class Randint(Domain):
    """Uniform integer in [lower, upper) (reference semantics)."""

    def __init__(self, lower: int, upper: int):
        self.lower, self.upper = lower, upper

    def sample(self, rng):
        return rng.randrange(self.lower, self.upper)


class QRandint(Domain):
    def __init__(self, lower: int, upper: int, q: int = 1):
        self.lower, self.upper, self.q = lower, upper, q

    def sample(self, rng):
        v = round(rng.randrange(self.lower, self.upper + 1) / self.q) * self.q
        lo = -(-self.lower // self.q) * self.q   # ceil to a q multiple
        hi = (self.upper // self.q) * self.q
        return max(lo, min(hi, v))


class Choice(Domain):
    def __init__(self, categories: Sequence):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Normal(Domain):
    def __init__(self, mean: float = 0.0, sd: float = 1.0):
        self.mean, self.sd = mean, sd

    def sample(self, rng):
        return rng.gauss(self.mean, self.sd)


def uniform(lower: float, upper: float) -> Uniform:
    return Uniform(lower, upper)


def loguniform(lower: float, upper: float, base: float = 10) -> LogUniform:
    return LogUniform(lower, upper, base)


def randint(lower: int, upper: int) -> Randint:
    return Randint(lower, upper)


def qrandint(lower: int, upper: int, q: int = 1) -> QRandint:
    return QRandint(lower, upper, q)


def choice(categories: Sequence) -> Choice:
    return Choice(categories)


def randn(mean: float = 0.0, sd: float = 1.0) -> Normal:
    return Normal(mean, sd)


def sample_from(fn) -> "Function":
    return Function(fn)


class Function(Domain):
    """Lazy config-dependent sample (reference: tune.sample_from)."""

    def __init__(self, fn):
        self.fn = fn

    def sample(self, rng):
        raise TypeError("Function domains resolve against a spec")


def grid_search(values: Sequence) -> dict:
    return {"grid_search": list(values)}


def is_grid(value) -> bool:
    return isinstance(value, dict) and "grid_search" in value
