"""ray_tpu.tune — hyperparameter sweep orchestration (the Tune equivalent;
reference: python/ray/tune/)."""

from ray_tpu.tune.sample import (
    choice,
    grid_search,
    loguniform,
    qrandint,
    randint,
    randn,
    sample_from,
    uniform,
)
from ray_tpu.tune.trainable import Trainable, report
from ray_tpu.tune.tune import ExperimentAnalysis, run

__all__ = [
    "ExperimentAnalysis",
    "Trainable",
    "choice",
    "grid_search",
    "loguniform",
    "qrandint",
    "randint",
    "randn",
    "report",
    "run",
    "sample_from",
    "uniform",
]
