"""ray_tpu.tune — hyperparameter sweep orchestration (the Tune equivalent;
reference: python/ray/tune/)."""

from ray_tpu.tune.sample import (
    choice,
    grid_search,
    loguniform,
    qrandint,
    randint,
    randn,
    sample_from,
    uniform,
)
from ray_tpu.tune.logger import CSVLogger, JSONLogger, UnifiedLogger
from ray_tpu.tune.placement_groups import PlacementGroupFactory
from ray_tpu.tune.progress_reporter import CLIReporter
from ray_tpu.tune.syncer import SyncConfig, Syncer
from ray_tpu.tune.trainable import Trainable, report
from ray_tpu.tune.tune import ExperimentAnalysis, run, with_parameters

__all__ = [
    "CLIReporter",
    "SyncConfig",
    "Syncer",
    "CSVLogger",
    "ExperimentAnalysis",
    "JSONLogger",
    "PlacementGroupFactory",
    "Trainable",
    "UnifiedLogger",
    "choice",
    "grid_search",
    "loguniform",
    "qrandint",
    "randint",
    "randn",
    "report",
    "run",
    "sample_from",
    "uniform",
    "with_parameters",
]
