"""Trainable — the unit of execution Tune schedules (reference:
python/ray/tune/trainable.py:32 — setup/step/save_checkpoint/
load_checkpoint lifecycle; function API wrapper: function_runner.py).
"""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
import time


class Trainable:
    """Class API: subclass, implement setup/step/save_checkpoint/
    load_checkpoint. One instance per trial, living in an actor."""

    def __init__(self, config: dict | None = None):
        self.config = config or {}
        self._iteration = 0
        self._time_total = 0.0
        self.setup(self.config)

    # -- user surface ---------------------------------------------------

    def setup(self, config: dict):
        pass

    def step(self) -> dict:
        raise NotImplementedError

    def save_checkpoint(self, checkpoint_dir: str) -> str | dict | None:
        return None

    def load_checkpoint(self, checkpoint) -> None:
        pass

    def cleanup(self):
        pass

    def reset_config(self, new_config: dict) -> bool:
        """Reuse this instance for a new config (PBT exploit without actor
        restart). Return True if handled."""
        return False

    # -- framework surface ----------------------------------------------

    @property
    def iteration(self) -> int:
        return self._iteration

    def train(self) -> dict:
        t0 = time.perf_counter()
        result = self.step() or {}
        self._iteration += 1
        self._time_total += time.perf_counter() - t0
        result.setdefault("training_iteration", self._iteration)
        result.setdefault("time_total_s", self._time_total)
        result.setdefault("done", False)
        return result

    def save(self, checkpoint_dir: str | None = None) -> bytes:
        """Serialize a checkpoint to bytes (the object plane carries it;
        reference saves to disk + syncer — here checkpoints are plain
        values so multi-node restore needs no shared filesystem)."""
        own_tmp = checkpoint_dir is None
        tmp = checkpoint_dir or tempfile.mkdtemp(prefix="tune_ckpt_")
        try:
            data = self.save_checkpoint(tmp)
            if isinstance(data, str):
                # user wrote files under tmp and returned the path
                payload = {}
                base = data if os.path.isdir(data) else os.path.dirname(data)
                for root, _, files in os.walk(base):
                    for f in files:
                        p = os.path.join(root, f)
                        with open(p, "rb") as fh:
                            payload[os.path.relpath(p, base)] = fh.read()
                blob = {"kind": "dir", "files": payload}
            else:
                blob = {"kind": "obj", "data": data}
        finally:
            if own_tmp:
                shutil.rmtree(tmp, ignore_errors=True)
        # Framework counters ride along so a resumed trial keeps its
        # training_iteration (schedulers key rungs/intervals off it).
        blob["iteration"] = self._iteration
        blob["time_total"] = self._time_total
        return pickle.dumps(blob)

    def restore(self, blob: bytes):
        state = pickle.loads(blob)
        self._iteration = state.get("iteration", self._iteration)
        self._time_total = state.get("time_total", self._time_total)
        if state["kind"] == "dir":
            tmp = tempfile.mkdtemp(prefix="tune_restore_")
            try:
                for rel, content in state["files"].items():
                    p = os.path.join(tmp, rel)
                    os.makedirs(os.path.dirname(p), exist_ok=True)
                    with open(p, "wb") as fh:
                        fh.write(content)
                self.load_checkpoint(tmp)
            finally:
                shutil.rmtree(tmp, ignore_errors=True)
        else:
            self.load_checkpoint(state["data"])

    def stop(self):
        self.cleanup()


class FunctionTrainable(Trainable):
    """Wraps `def train_fn(config)` generators / tune.report style functions
    (reference: function_runner.py). The function either:
      - yields result dicts (preferred, resumable step-by-step), or
      - calls tune.report(**metrics) (run to completion on first step).
    """

    _fn = None  # set by make_function_trainable

    def setup(self, config):
        self._gen = None
        self._last: dict = {}
        self._done = False

    def _ensure_gen(self):
        if self._gen is None:
            import inspect

            out = type(self)._fn(self.config)
            if inspect.isgenerator(out):
                self._gen = out
            else:
                # plain function: ran to completion; collect reports
                self._gen = iter(_reported_results())
                self._done = True

    def step(self):
        self._ensure_gen()
        try:
            self._last = dict(next(self._gen))
            return dict(self._last)
        except StopIteration:
            # keep the final metrics visible on the terminating result
            return {**self._last, "done": True}


_REPORT_BUFFER: list[dict] = []


def report(**metrics):
    """tune.report for plain-function trainables."""
    _REPORT_BUFFER.append(dict(metrics))


def _reported_results():
    out, _REPORT_BUFFER[:] = list(_REPORT_BUFFER), []
    return out


def make_function_trainable(fn) -> type:
    return type(f"func_{getattr(fn, '__name__', 'trainable')}",
                (FunctionTrainable,), {"_fn": staticmethod(fn)})
