"""CLIReporter — periodic trial table on the console (reference:
python/ray/tune/progress_reporter.py CLIReporter)."""

from __future__ import annotations

import sys
import time


class CLIReporter:
    def __init__(self, metric_columns: list[str] | None = None,
                 max_report_frequency: float = 5.0, out=None):
        self.metric_columns = metric_columns or []
        self._freq = max_report_frequency
        self._last = 0.0
        self._out = out or sys.stderr

    def should_report(self, done: bool = False) -> bool:
        if done or time.monotonic() - self._last >= self._freq:
            self._last = time.monotonic()
            return True
        return False

    def report(self, trials, done: bool = False):
        counts: dict[str, int] = {}
        for t in trials:
            counts[t.status] = counts.get(t.status, 0) + 1
        summary = ", ".join(f"{k}:{v}" for k, v in sorted(counts.items()))
        print(f"== tune status: {len(trials)} trials ({summary})",
              file=self._out)
        cols = ["trial", "status", "iter"] + self.metric_columns
        print("  " + "  ".join(f"{c:>14}" for c in cols), file=self._out)
        for t in trials[:20]:
            vals = [t.trial_id[-8:], t.status, str(t.iteration)]
            vals += [f"{t.last_result.get(m, ''):.4g}"
                     if isinstance(t.last_result.get(m), (int, float))
                     else str(t.last_result.get(m, ""))
                     for m in self.metric_columns]
            print("  " + "  ".join(f"{v:>14}" for v in vals),
                  file=self._out)
        if len(trials) > 20:
            print(f"  ... and {len(trials) - 20} more", file=self._out)
