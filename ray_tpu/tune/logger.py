"""Trial result loggers (reference: python/ray/tune/logger.py CSVLogger,
JsonLogger, UnifiedLogger): every reported result lands in the trial's
directory under local_dir as progress.csv + result.json lines, plus
params.json once."""

from __future__ import annotations

import csv
import json
import os


class Logger:
    def __init__(self, trial_dir: str, config: dict):
        self.trial_dir = trial_dir
        self.config = config
        os.makedirs(trial_dir, exist_ok=True)

    def on_result(self, result: dict):
        raise NotImplementedError

    def close(self):
        pass


def _scalars(result: dict) -> dict:
    return {k: v for k, v in result.items()
            if isinstance(v, (int, float, str, bool)) or v is None}


class CSVLogger(Logger):
    """reference: logger.py CSVLogger — progress.csv, header from the
    first result."""

    def __init__(self, trial_dir: str, config: dict):
        super().__init__(trial_dir, config)
        self._file = open(os.path.join(trial_dir, "progress.csv"), "w",
                          newline="")
        self._writer = None

    def on_result(self, result: dict):
        row = _scalars(result)
        if self._writer is None:
            self._writer = csv.DictWriter(self._file,
                                          fieldnames=sorted(row))
            self._writer.writeheader()
        self._writer.writerow({k: row.get(k) for k in self._writer.fieldnames})
        self._file.flush()

    def close(self):
        self._file.close()


class JSONLogger(Logger):
    """reference: logger.py JsonLogger — result.json (one JSON per line)
    + params.json."""

    def __init__(self, trial_dir: str, config: dict):
        super().__init__(trial_dir, config)
        with open(os.path.join(trial_dir, "params.json"), "w") as f:
            json.dump(_scalars(config), f)
        self._file = open(os.path.join(trial_dir, "result.json"), "w")

    def on_result(self, result: dict):
        self._file.write(json.dumps(_scalars(result)) + "\n")
        self._file.flush()

    def close(self):
        self._file.close()


DEFAULT_LOGGERS = (CSVLogger, JSONLogger)


class UnifiedLogger(Logger):
    def __init__(self, trial_dir: str, config: dict,
                 loggers=DEFAULT_LOGGERS):
        super().__init__(trial_dir, config)
        self._loggers = [cls(trial_dir, config) for cls in loggers]

    def on_result(self, result: dict):
        for lg in self._loggers:
            lg.on_result(result)

    def close(self):
        for lg in self._loggers:
            lg.close()
