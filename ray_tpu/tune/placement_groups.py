"""PG-backed trial resources (reference: python/ray/tune/utils/
placement_groups.py PlacementGroupFactory): a trial declares bundles +
strategy; the runner reserves a placement group per trial, starts the
trainable actor inside bundle 0, and returns the group when the trial
stops."""

from __future__ import annotations


class PlacementGroupFactory:
    def __init__(self, bundles: list[dict], strategy: str = "PACK"):
        if not bundles:
            raise ValueError("need at least one bundle")
        self.bundles = [dict(b) for b in bundles]
        self.strategy = strategy

    @property
    def head_bundle(self) -> dict:
        return dict(self.bundles[0])

    def create(self, timeout: float = 60.0):
        from ray_tpu.util.placement_group import placement_group

        pg = placement_group(self.bundles, strategy=self.strategy)
        if not pg.wait(timeout):
            from ray_tpu.util.placement_group import remove_placement_group

            remove_placement_group(pg)
            raise TimeoutError(
                f"placement group {self.bundles} not ready in {timeout}s")
        return pg

    def __repr__(self):
        return (f"PlacementGroupFactory({self.bundles}, "
                f"strategy={self.strategy!r})")
