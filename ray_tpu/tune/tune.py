"""tune.run — experiment entry point + ExperimentAnalysis (reference:
python/ray/tune/tune.py:71 run; analysis.py ExperimentAnalysis)."""

from __future__ import annotations

import inspect

from ray_tpu.tune.search.basic_variant import BasicVariantGenerator
from ray_tpu.tune.trainable import Trainable, make_function_trainable
from ray_tpu.tune.trial import TERMINATED
from ray_tpu.tune.trial_runner import TrialRunner


class ExperimentAnalysis:
    def __init__(self, trials, metric: str | None, mode: str):
        self.trials = trials
        self._metric = metric
        self._mode = mode

    def _score(self, trial) -> float | None:
        if self._metric is None or self._metric not in trial.last_result:
            return None
        v = float(trial.last_result[self._metric])
        return v if self._mode == "max" else -v

    @property
    def best_trial(self):
        scored = [(self._score(t), t) for t in self.trials]
        scored = [(s, t) for s, t in scored if s is not None]
        if not scored:
            return None
        return max(scored, key=lambda p: p[0])[1]

    @property
    def best_config(self) -> dict | None:
        best = self.best_trial
        return best.config if best else None

    @property
    def best_result(self) -> dict | None:
        best = self.best_trial
        return best.last_result if best else None

    @property
    def best_checkpoint(self):
        best = self.best_trial
        return best.checkpoint if best else None

    def results_df(self):
        """Rows of (trial_id, config, last metrics) — pandas if available."""
        rows = [
            {"trial_id": t.trial_id, "status": t.status,
             **{f"config/{k}": v for k, v in t.config.items()},
             **t.last_result}
            for t in self.trials
        ]
        try:
            import pandas as pd

            return pd.DataFrame(rows)
        except ImportError:
            return rows

    def dataframe(self):
        return self.results_df()


def with_parameters(trainable, **params):
    """Bind large/unpicklable-by-value objects to a trainable via the
    object store (reference: tune/utils/trainable.py with_parameters):
    each trial's actor gets them from plasma instead of shipping a copy
    inside every trial config."""
    import functools

    import ray_tpu

    refs = {k: ray_tpu.put(v) for k, v in params.items()}

    if inspect.isclass(trainable) and issubclass(trainable, Trainable):
        class _WithParams(trainable):
            def setup(self, config):
                import ray_tpu as _ray

                resolved = {k: _ray.get(r, timeout=120)
                            for k, r in refs.items()}
                super().setup({**config, **resolved})

        _WithParams.__name__ = f"{trainable.__name__}WithParams"
        return _WithParams

    @functools.wraps(trainable)
    def _fn(config):
        import ray_tpu as _ray

        resolved = {k: _ray.get(r, timeout=120) for k, r in refs.items()}
        return trainable({**config, **resolved})

    return _fn


def run(run_or_experiment, *, config: dict | None = None,
        num_samples: int = 1, metric: str | None = None, mode: str = "max",
        search_alg=None, scheduler=None, stop: dict | None = None,
        resources_per_trial=None,
        max_concurrent_trials: int = 0, checkpoint_freq: int = 0,
        max_failures: int = 0, verbose: int = 1,
        local_dir: str | None = None, loggers=None,
        progress_reporter=None, sync_config=None, resume: bool = False,
        raise_on_failed_trial: bool = True) -> ExperimentAnalysis:
    """Run a hyperparameter sweep (reference: tune/tune.py:71).

    `run_or_experiment`: Trainable subclass or `def fn(config)` (generator
    yielding result dicts, or using tune.report)."""
    if mode not in ("min", "max"):
        raise ValueError("mode must be 'min' or 'max'")
    if inspect.isclass(run_or_experiment) and issubclass(
            run_or_experiment, Trainable):
        trainable_cls = run_or_experiment
    elif callable(run_or_experiment):
        trainable_cls = make_function_trainable(run_or_experiment)
    else:
        raise TypeError(f"not a trainable: {run_or_experiment!r}")

    if search_alg is None:
        search = BasicVariantGenerator(config or {},
                                       num_samples=num_samples)
    else:
        from ray_tpu.tune.search.searcher import SampleBudget

        search = search_alg
        # feed the config's Domain leaves to model-based searchers and
        # cap them at num_samples (they never self-exhaust)
        search.set_search_properties(metric, mode, config or {})
        if num_samples:
            search = SampleBudget(search, num_samples)
    runner = TrialRunner(
        trainable_cls,
        search_alg=search,
        scheduler=scheduler,
        metric=metric,
        mode=mode,
        stop=stop,
        max_concurrent_trials=max_concurrent_trials,
        resources_per_trial=resources_per_trial,
        checkpoint_freq=checkpoint_freq,
        max_failures=max_failures,
        local_dir=local_dir,
        loggers=loggers,
        progress_reporter=progress_reporter,
        sync_config=sync_config,
    )
    if resume:
        if not local_dir:
            raise ValueError("resume=True needs local_dir (the experiment "
                             "state lives there)")
        restored = runner.restore_experiment_state()
        if not restored:
            import logging

            logging.getLogger("ray_tpu.tune").warning(
                "resume=True but no experiment state under %s; starting "
                "fresh", local_dir)
    runner.run()
    errored = [t for t in runner.trials if t.status == "ERROR"
               and not getattr(t, "restored", False)]
    if errored and raise_on_failed_trial:
        raise RuntimeError(
            f"{len(errored)} trial(s) errored; first: "
            f"{errored[0].trial_id}: {errored[0].error}")
    return ExperimentAnalysis(runner.trials, metric, mode)
