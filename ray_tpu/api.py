"""Public API (reference: python/ray/worker.py — init :490, get :1369,
put :1446, wait :1475, remote :1741, kill :1597, cancel :1625,
get_actor :1576)."""

from __future__ import annotations

import inspect
from typing import Any, Sequence

from ray_tpu import exceptions as exc
from ray_tpu._private import global_state
from ray_tpu._private.config import Config, set_config
from ray_tpu._private.core_worker import DRIVER, CoreWorker
from ray_tpu._private.ids import ActorID
from ray_tpu._private.node import Node
from ray_tpu.actor import ActorClass, ActorHandle
from ray_tpu.object_ref import ObjectRef
from ray_tpu.remote_function import RemoteFunction

_global_node: Node | None = None


def init(address: str | None = None, *, num_cpus: float | None = None,
         num_tpus: float | None = None, resources: dict | None = None,
         labels: dict | None = None, object_store_memory: int | None = None,
         _system_config: dict | None = None, ignore_reinit_error=False,
         **kwargs) -> dict:
    """Start (or connect to) a cluster and connect this process as driver.

    address=None starts a new local head node; address="<gcs host:port>"
    connects to an existing cluster (e.g. one made by cluster_utils.Cluster
    or `ray-tpu start`); address="auto" finds one via RAY_TPU_ADDRESS.
    """
    global _global_node
    if global_state.get_core_worker() is not None:
        if ignore_reinit_error:
            return connection_info()
        raise RuntimeError("ray_tpu.init() called twice")

    overrides = dict(_system_config or {})
    if object_store_memory is not None:
        overrides["object_store_memory"] = object_store_memory
    config = Config.load(overrides)
    set_config(config)

    if address == "auto":
        import os

        address = os.environ.get("RAY_TPU_ADDRESS")
        if not address:
            raise ConnectionError(
                "address='auto' but RAY_TPU_ADDRESS is not set")

    if address is None:
        if num_tpus is None:
            num_tpus = _detect_tpu_chips()
        _global_node = Node(config=config, num_cpus=num_cpus,
                            num_tpus=num_tpus, resources=resources,
                            labels=labels)
        raylet_address = _global_node.raylet_address
        gcs_address = _global_node.gcs_address
        session_dir = _global_node.session_dir
        store_root = _global_node.store_root
    else:
        # Connect as a driver to an existing cluster: ask the GCS for a node
        # on this host (round-1: pick the first).
        gcs_address = address
        import asyncio

        from ray_tpu._private import rpc as _rpc

        async def _find():
            conn = await _rpc.connect(gcs_address, name="probe")
            nodes = await conn.call("get_all_nodes", {})
            await conn.close()
            return nodes

        nodes = asyncio.run(_find())
        if not nodes:
            raise ConnectionError(f"no alive nodes in cluster at {address}")
        head = next((n for n in nodes if n.get("is_head")), nodes[0])
        raylet_address = head["address"]
        import os

        # Attach to the raylet's own session/store when it's on this host
        # (the `ray-tpu start` two-shell flow): shared-memory objects are
        # then zero-copy between driver and workers.
        async def _info():
            conn = await _rpc.connect(raylet_address, name="probe")
            info = await conn.call("cluster_info", {})
            await conn.close()
            return info

        try:
            info = asyncio.run(_info())
        except Exception:
            info = {}
        session_dir = kwargs.get("session_dir") or info.get("session_dir")
        store_root = kwargs.get("store_root") or info.get("store_root")
        if not (session_dir and os.path.isdir(session_dir)):
            session_dir = "/tmp/ray_tpu/attached"
        os.makedirs(session_dir, exist_ok=True)
        if not (store_root and os.path.isdir(store_root)):
            store_root = os.path.join(session_dir, "driver_store")

    CoreWorker(
        mode=DRIVER,
        raylet_address=raylet_address,
        gcs_address=gcs_address,
        session_dir=session_dir,
        store_root=store_root,
        config=config,
    )
    return connection_info()


def _detect_tpu_chips() -> float:
    """Detect TPU chips WITHOUT initializing jax (a backend claim in init
    would grab the chip for the driver and can block). Env-based only;
    pass num_tpus explicitly for precise control."""
    import os

    if os.environ.get("RAY_TPU_NUM_CHIPS"):
        return float(os.environ["RAY_TPU_NUM_CHIPS"])
    if os.environ.get("PALLAS_AXON_POOL_IPS") or os.environ.get("TPU_NAME"):
        return 1.0
    return 0.0


def connection_info() -> dict:
    cw = global_state.require_core_worker()
    return {
        "gcs_address": _global_node.gcs_address if _global_node else "",
        "raylet_address": cw.raylet.name if cw.raylet else "",
        "session_dir": cw.session_dir,
        "node_id": cw.node_id.hex() if cw.node_id else "",
    }


def is_initialized() -> bool:
    return global_state.get_core_worker() is not None


def shutdown():
    global _global_node, _doctor_metrics_cache
    # disarm the doctor loop FIRST: a surviving tick would spin against
    # the dead runtime and silently re-attach to any later init() with
    # this session's stale cache/dedup state
    stop_doctor()
    _doctor_metrics_cache = None
    from ray_tpu._private import debug_state as _ds

    _ds.reset_stall_dedup()
    from ray_tpu._private import sampling_profiler as _sprof

    _sprof.stop()
    cw = global_state.get_core_worker()
    if cw is not None:
        cw.shutdown()
    if _global_node is not None:
        _global_node.kill_all_processes()
        _global_node = None


def timeline(filename: str | None = None) -> list[dict]:
    """Chrome-trace timeline of recorded cluster profile events
    (reference: python/ray/state.py:946 timeline(); load the output in
    chrome://tracing or Perfetto). Spans are flushed from workers within
    ~2s of recording (sooner after task completion) — a timeline taken
    immediately after a very short run may lag a moment behind."""
    import json

    from ray_tpu._private.profiling import to_chrome_trace

    cw = global_state.require_core_worker()
    trace = to_chrome_trace(cw.get_profile_events())
    if filename:
        with open(filename, "w") as f:
            json.dump(trace, f)
    return trace


def cluster_events(severity: str | None = None) -> list[dict]:
    """Structured cluster events (node joins/removals, actor deaths,
    worker crashes — the RAY_EVENT analog; reference: src/ray/util/
    event.h + the dashboard event view)."""
    return global_state.require_core_worker().get_cluster_events(severity)


def cluster_metrics(history: int | None = None) -> dict:
    """Metric snapshots from the GCS and every raylet (reference:
    src/ray/stats/metric.h export surface).

    With `history=N`, returns the GCS metrics time-series instead:
    `{source: {metric: [[ts, value], ...]}}` with up to the last N
    timestamped samples per metric (N<=0 for the full retained ring).
    Sources are `<node>/raylet` (heartbeat-piggybacked) and
    `<node>/<mode>-<pid>` per worker/driver (pushed on the ~2s profile
    flush cadence); histograms appear as `.count`/`.sum`/`.p99` scalar
    series — the serve autoscaler's feed."""
    cw = global_state.require_core_worker()
    if history is not None:
        return cw.get_metrics_history(samples=history)
    return cw.get_cluster_metrics()


def cluster_state(component: str | None = None,
                  filters: dict | None = None, *,
                  include_workers: bool = True,
                  timeout: float = 5.0):
    """Live cluster-wide introspection snapshot (the flight recorder;
    debug_state.py): every process class — driver, GCS director +
    shards, each raylet and its workers (serve actors and collective
    groups included) — answers a cheap `debug_state()` of its in-flight
    work: per-task stage with age, lease tables, transfer streams/pins,
    collective op phases, rpc conn depth, event-loop lag.

    With `component` (one of serve|tasks|actors|objects|leases|
    transfers|collectives) returns flat rows across every process,
    sorted oldest first (`serve`: per-router queue depth vs admission
    bound, shed/admitted totals, replica-group/controller state);
    `filters={"field": substring}` narrows them. Unreachable
    components degrade to an {"error": ...} entry — asking a sick
    cluster what is wrong must never hang on the sick part."""
    from ray_tpu._private import debug_state

    cw = global_state.require_core_worker()
    snap = cw.get_cluster_state(include_workers=include_workers,
                                timeout=timeout)
    if component is None:
        return snap
    rows = debug_state.flatten(snap, component)
    for key, want in (filters or {}).items():
        rows = [r for r in rows if str(want) in str(r.get(key, ""))]
    return rows


_doctor_metrics_cache: tuple | None = None  # (monotonic_ts, metrics)


def doctor(*, floor_s: float | None = None,
           p99_factor: float | None = None,
           include_stacks: bool = True, emit_events: bool = True,
           timeout: float = 5.0, metrics_max_age_s: float = 10.0) -> dict:
    """The stall doctor: cross-references `cluster_state()` against the
    per-hop latency histograms the cluster already records — any
    in-flight item whose age exceeds max(floor, K×p99-of-its-stage) is
    flagged with its stage, age, trace id and owning process, and (with
    include_stacks) the all-thread stacks of that process. Findings are
    also emitted as deduped STALL_DETECTED warning events into the GCS
    events ring (`/api/events`, `ray-tpu events`) so dashboards surface
    stalls without polling. Knobs: floor_s (default 1s,
    RAY_TPU_DOCTOR_FLOOR_S) and p99_factor (default 3, RAY_TPU_DOCTOR_P99_K)."""
    from ray_tpu._private import debug_state

    global _doctor_metrics_cache
    import time as _time

    cw = global_state.require_core_worker()
    snap = cw.get_cluster_state(timeout=timeout)
    # The p99 thresholds drift on the histogram timescale, not per tick:
    # cache the metrics fan-out so the armed 1s doctor cadence pays ONE
    # cluster sweep per tick (state), not two (the ≤5% microbench gate).
    cache = _doctor_metrics_cache
    if (cache is not None
            and _time.monotonic() - cache[0] < metrics_max_age_s):
        metrics = cache[1]
    else:
        try:
            metrics = cw.get_cluster_metrics()
        except Exception:
            metrics = {}
        # this driver's OWN registry: the submit-side task histograms
        # (lease_wait/queue_wait/e2e) live here, not in any raylet fold
        from ray_tpu._private import stats as _stats

        metrics = dict(metrics)
        metrics["driver"] = _stats.snapshot()
        _doctor_metrics_cache = (_time.monotonic(), metrics)
    findings = debug_state.diagnose(snap, metrics, floor_s=floor_s,
                                    p99_factor=p99_factor)
    if include_stacks and findings:
        addr_of = _process_addresses(snap)
        stacks: dict[str, dict] = {}
        for f in findings:
            label = f["process"]
            if label in stacks or len(stacks) >= 4:
                continue
            try:
                if label == "driver":
                    stacks[label] = cw.get_debug_stacks()
                elif label == "gcs":
                    stacks[label] = cw._io.run(
                        cw.gcs.call("debug_stacks", {}), timeout=timeout)
                elif addr_of.get(label):
                    stacks[label] = cw.get_debug_stacks(addr_of[label])
            except Exception as e:
                stacks[label] = {"error": f"{type(e).__name__}: {e}"}
        for f in findings:
            if f["process"] in stacks:
                f["stacks"] = stacks[f["process"]]
    if emit_events:
        for f in debug_state.novel_findings(findings):
            event = debug_state.make_stall_event(
                {k: v for k, v in f.items() if k != "stacks"})
            try:
                cw._io.run(cw.gcs.notify("report_event", event),
                           timeout=2.0)
            except Exception:
                pass
    return {"findings": findings,
            "collected_at": snap.get("collected_at"),
            "processes": sum(
                1 for _ in debug_state_iter_processes(snap))}


def debug_state_iter_processes(snap):
    from ray_tpu._private import debug_state

    return debug_state.iter_processes(snap)


def _process_addresses(snap: dict) -> dict[str, str]:
    """process label (as in doctor findings) -> rpc address."""
    from ray_tpu._private import debug_state

    out = {}
    for label, proc in debug_state.iter_processes(snap):
        addr = proc.get("address")
        if addr:
            out[label] = addr
    return out


_doctor_loop = None


def start_doctor(interval: float = 1.0, **knobs) -> None:
    """Arm a background doctor tick in this driver: every `interval`
    seconds, collect cluster_state + diagnose + emit stall events (the
    cadence the microbench regression gate runs at). Idempotent;
    stop_doctor() disarms."""
    import threading

    global _doctor_loop
    if _doctor_loop is not None:
        return
    stop = threading.Event()

    def _loop():
        while not stop.wait(interval):
            try:
                doctor(include_stacks=False, **knobs)
            except Exception:
                pass

    t = threading.Thread(target=_loop, name="stall-doctor", daemon=True)
    t.start()
    _doctor_loop = (t, stop)


def stop_doctor() -> None:
    global _doctor_loop
    if _doctor_loop is not None:
        _doctor_loop[1].set()
        _doctor_loop = None


def debug_stacks(address: str | None = None) -> dict:
    """All-thread Python stacks of this driver, or of any live runtime
    process by rpc address (`sys._current_frames` over rpc — the
    `ray-tpu stack` surface)."""
    return global_state.require_core_worker().get_debug_stacks(address)


def trace_spans(trace_id: str | None = None) -> list[dict]:
    """Flat span rows from the GCS trace table (tracing.py), optionally
    filtered to one trace (hex trace id). Each row carries the emitting
    process (`component_type`/`component_id`/`node_id`) and the span's
    `tid`/`sid`/`psid` linkage in `extra_data`."""
    return global_state.require_core_worker().get_trace_spans(trace_id)


def profile(seconds: float | None = 2.0, component: str | None = None,
            out: str | None = None) -> dict:
    """Cluster-wide CPU flamegraph off the continuous profiling plane
    (sampling_profiler.py): every process class (driver, workers,
    raylets, GCS director + shards) runs an always-on ~67 Hz wall-clock
    sampler whose collapsed stacks flush to the GCS profile ring on the
    ~2 s profile cadence.

    With `seconds=N` collects a fresh window: waits N seconds (plus up
    to one flush cadence for the tail) and returns the sampler windows
    OVERLAPPING it — a ~2s flush window already open when collection
    starts is included whole, so a short collection may carry up to one
    cadence of immediately-preceding stacks. `seconds=None` returns
    everything the ring holds.
    `component` filters to one process class (driver|worker|raylet|
    gcs|gcs-shard); `out` also writes the collapsed text to a file.

    Returns {"collapsed": str, "components": [...], "samples": int,
    "batches": [...]} — `collapsed` is Brendan-Gregg collapsed-stack
    text (one `component;thread;frame;... count` line per stack; feed
    it to flamegraph.pl / speedscope), `batches` the raw ring rows
    (sampling_profiler.samples_to_chrome_trace renders them as merged
    Perfetto tracks)."""
    import time as _time

    from ray_tpu._private import sampling_profiler as _sprof

    cw = global_state.require_core_worker()
    if seconds is not None:
        since = _time.time()
        _time.sleep(max(0.0, float(seconds)))
        batches = _sprof.wait_for_coverage(
            lambda: cw.get_profile_samples(since=since,
                                           component=component),
            component)
    else:
        batches = cw.get_profile_samples(component=component)
    collapsed = _sprof.collapse_text(batches)
    if out:
        with open(out, "w") as f:
            f.write(collapsed + ("\n" if collapsed else ""))
    return {
        "collapsed": collapsed,
        "components": _sprof.components_of(batches),
        "samples": sum(b.get("samples", 0) for b in batches),
        "batches": batches,
    }


def set_profiling(hz: float) -> None:
    """Arm/re-rate the continuous profiler cluster-wide, live: every
    process's sampler thread flips to `hz` samples/s (0 stops it; the
    default is RAY_TPU_PROFILE_HZ, ~67). Rides the internal KV + pubsub
    plane exactly like failpoint arming and trace-sampling overrides,
    so running processes and any spawned later both honor it."""
    from ray_tpu._private import sampling_profiler as _sprof

    hz = min(_sprof.MAX_HZ, max(0.0, float(hz)))
    cw = global_state.require_core_worker()
    cw.kv_put(_sprof.KV_KEY, repr(hz).encode())
    _sprof.apply_kv_value(repr(hz))  # local apply; push also lands


def set_trace_sampling(rate: float) -> None:
    """Set the head-sampling rate for distributed tracing cluster-wide,
    live (0.0 disables new roots, 1.0 traces everything; default is
    `RAY_TPU_TRACE_SAMPLE`, ~1%). Rides the internal KV + pubsub plane,
    so every connected process — and any spawned later — picks it up."""
    from ray_tpu._private import tracing

    rate = min(1.0, max(0.0, float(rate)))
    cw = global_state.require_core_worker()
    cw.kv_put(tracing.KV_KEY, repr(rate).encode())
    tracing.set_sample_rate(rate)  # local apply; push also lands


def remote(*args, **kwargs):
    """@remote decorator for functions and classes, with or without options:

        @ray_tpu.remote
        def f(): ...

        @ray_tpu.remote(num_tpus=1, max_restarts=3)
        class A: ...
    """
    if len(args) == 1 and not kwargs and callable(args[0]):
        return _make_remote(args[0], {})
    if args:
        raise TypeError("@remote takes keyword options only")

    def decorator(obj):
        return _make_remote(obj, kwargs)

    return decorator


def _make_remote(obj, opts):
    if inspect.isclass(obj):
        allowed = {"num_cpus", "num_tpus", "resources", "max_restarts",
                   "max_concurrency", "accelerator_type"}
        bad = set(opts) - allowed
        if bad:
            raise ValueError(f"unsupported actor options: {bad}")
        return ActorClass(obj, **opts)
    allowed = {"num_cpus", "num_tpus", "resources", "num_returns",
               "max_retries", "accelerator_type"}
    bad = set(opts) - allowed
    if bad:
        raise ValueError(f"unsupported task options: {bad}")
    return RemoteFunction(obj, **opts)


def put(value: Any) -> ObjectRef:
    return global_state.require_core_worker().put(value)


def get(refs, timeout: float | None = None):
    cw = global_state.require_core_worker()
    if isinstance(refs, ObjectRef):
        return cw.get([refs], timeout)[0]
    if not isinstance(refs, (list, tuple)):
        raise TypeError("get() expects an ObjectRef or a list of ObjectRefs")
    for r in refs:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"get() got a non-ObjectRef element: {type(r)}")
    return cw.get(list(refs), timeout)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: float | None = None, fetch_local: bool = True):
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    refs = list(refs)
    if len(set(refs)) != len(refs):
        raise ValueError("wait() got duplicate ObjectRefs")
    if num_returns > len(refs):
        raise ValueError("num_returns exceeds the number of refs")
    cw = global_state.require_core_worker()
    return cw.wait(refs, num_returns=num_returns, timeout=timeout,
                   fetch_local=fetch_local)


def kill(actor: ActorHandle, *, no_restart: bool = True):
    if not isinstance(actor, ActorHandle):
        raise TypeError("kill() expects an ActorHandle; use cancel() for tasks")
    cw = global_state.require_core_worker()
    cw.kill_actor(actor._actor_id.binary(), no_restart=no_restart)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True):
    cw = global_state.require_core_worker()
    cw.cancel_task(ref, force=force, recursive=recursive)


def get_actor(name: str, namespace: str = "") -> ActorHandle:
    cw = global_state.require_core_worker()
    info = cw.get_named_actor(name, namespace)
    if info is None or info["state"] == "DEAD":
        raise ValueError(f"Failed to look up actor {name!r}")
    from ray_tpu._private.ids import ActorID as _ActorID

    # class fn_id is unknown to late-bound getters; methods resolve by name
    # at call time, so a nil cls id is fine.
    return ActorHandle(_ActorID(info["actor_id"]), b"\x00" * 16,
                       info.get("class_name", "Actor"))


def nodes() -> list[dict]:
    cw = global_state.require_core_worker()
    info = cw.cluster_info()
    return [
        {
            "NodeID": n["node_id"].hex(),
            "Alive": True,
            "Address": n["address"],
            "Resources": {k: v / 10000 for k, v in n["resources"].items()},
            "IsHead": n.get("is_head", False),
            "Labels": n.get("labels", {}),
            "TpuSlice": n.get("tpu_slice"),
        }
        for n in info["nodes"]
    ]


def cluster_resources() -> dict:
    out: dict[str, float] = {}
    for node in nodes():
        for k, v in node["Resources"].items():
            out[k] = out.get(k, 0) + v
    return out


def available_resources() -> dict:
    cw = global_state.require_core_worker()
    info = cw.cluster_info()
    return {k: v / 10000 for k, v in info["available"].items()}
