"""ray_tpu — a TPU-native distributed computing framework.

Task/actor/object-store runtime with the capabilities of the Ray v1.2-era
core (reference: /root/reference, photoszzt/ray), redesigned TPU-first:
XLA-collective data plane over ICI, jit/pjit compute, slice-aware
scheduling, and JAX-native ML libraries (train/tune/rllib/serve) on top.
"""

from ray_tpu._version import __version__
from ray_tpu import exceptions
from ray_tpu.actor import ActorClass, ActorHandle, exit_actor
from ray_tpu.api import (
    available_resources,
    cancel,
    cluster_events,
    cluster_metrics,
    cluster_resources,
    cluster_state,
    debug_stacks,
    doctor,
    get,
    get_actor,
    init,
    is_initialized,
    kill,
    nodes,
    profile,
    put,
    remote,
    set_profiling,
    set_trace_sampling,
    shutdown,
    start_doctor,
    stop_doctor,
    timeline,
    trace_spans,
    wait,
)
from ray_tpu.object_ref import ObjectRef

__all__ = [
    "ActorClass",
    "ActorHandle",
    "ObjectRef",
    "__version__",
    "available_resources",
    "cancel",
    "cluster_events",
    "cluster_metrics",
    "cluster_resources",
    "cluster_state",
    "debug_stacks",
    "doctor",
    "exceptions",
    "exit_actor",
    "get",
    "get_actor",
    "init",
    "is_initialized",
    "kill",
    "nodes",
    "profile",
    "put",
    "remote",
    "set_profiling",
    "set_trace_sampling",
    "shutdown",
    "start_doctor",
    "stop_doctor",
    "timeline",
    "trace_spans",
    "wait",
]
