"""asyncio integration (reference: python/ray/experimental/async_api.py
as_future — await ObjectRefs from asyncio event loops).

ObjectRefs are natively awaitable here (object_ref.py __await__), so this
module is the explicit-conversion surface for code that wants
concurrent.futures / asyncio.Future objects instead of `await ref`."""

from __future__ import annotations

import asyncio


def as_future(object_ref) -> asyncio.Future:
    """Wrap an ObjectRef into an asyncio.Future on the running loop."""
    return asyncio.ensure_future(_await_ref(object_ref))


async def _await_ref(object_ref):
    return await object_ref


def as_concurrent_future(object_ref):
    """concurrent.futures.Future resolving to the object (thread-safe;
    no running asyncio loop required)."""
    return object_ref.future()
