"""Distributed block arrays (reference: python/ray/experimental/array/
distributed/core.py DistArray + remote/core.py): an array decomposed into
object-store blocks, with remote blockwise constructors and ops, so
arrays larger than one node's memory live across the cluster.

Original design notes vs the reference: blocks are addressed by a dict
keyed on grid index (sparse-friendly) rather than a dense object ndarray,
ops submit one task per OUTPUT block (dot accumulates its k-chain inside
a single task to avoid a tree of tiny objects), and the surface sticks to
what the rest of this framework needs: zeros/ones/from_numpy/assemble,
elementwise add/sub/mul, transpose, dot, and a block-map escape hatch.
"""

from __future__ import annotations

import itertools

import numpy as np

import ray_tpu

BLOCK_SIZE = 256  # rows/cols per block (2-D); tuned for object overhead


def _grid(shape, block):
    return tuple(-(-s // block) for s in shape)


def _block_bounds(idx, shape, block):
    lo = [i * block for i in idx]
    hi = [min((i + 1) * block, s) for i, s in zip(idx, shape)]
    return lo, hi


@ray_tpu.remote
def _fill_block(shape, value, dtype):
    return np.full(shape, value, dtype)


@ray_tpu.remote
def _ew(op, a, b):
    return getattr(np, op)(a, b)


@ray_tpu.remote
def _dot_chain(k, *blocks):
    # blocks = a_0..a_{k-1}, b_0..b_{k-1} as top-level args (refs nested
    # in containers are not resolved at submit time)
    a_blocks, b_blocks = blocks[:k], blocks[k:]
    out = a_blocks[0] @ b_blocks[0]
    for a, b in zip(a_blocks[1:], b_blocks[1:]):
        out = out + a @ b
    return out


@ray_tpu.remote
def _transpose_block(a):
    return np.ascontiguousarray(a.T)


class DistArray:
    """Block-decomposed distributed array. `blocks` maps grid index ->
    ObjectRef of that block's numpy array."""

    def __init__(self, shape, blocks: dict | None = None,
                 block_size: int = BLOCK_SIZE, dtype=np.float64):
        self.shape = tuple(int(s) for s in shape)
        self.ndim = len(self.shape)
        self.block_size = int(block_size)
        self.dtype = np.dtype(dtype)
        self.grid = _grid(self.shape, self.block_size)
        self.blocks = blocks if blocks is not None else {}

    def _indices(self):
        return itertools.product(*[range(g) for g in self.grid])

    def _block_shape(self, idx):
        lo, hi = _block_bounds(idx, self.shape, self.block_size)
        return tuple(h - l for l, h in zip(lo, hi))

    # -- materialization -------------------------------------------------

    def assemble(self) -> np.ndarray:
        """Gather every block into one local ndarray (reference:
        DistArray.assemble). One batched get — not a round-trip per
        block."""
        indices = list(self._indices())
        values = ray_tpu.get([self.blocks[idx] for idx in indices])
        out = np.zeros(self.shape, self.dtype)
        for idx, val in zip(indices, values):
            lo, hi = _block_bounds(idx, self.shape, self.block_size)
            out[tuple(slice(l, h) for l, h in zip(lo, hi))] = val
        return out

    def __repr__(self):
        return (f"DistArray(shape={self.shape}, grid={self.grid}, "
                f"block={self.block_size})")


def _filled(shape, value, dtype, block_size) -> DistArray:
    arr = DistArray(shape, block_size=block_size, dtype=dtype)
    for idx in arr._indices():
        arr.blocks[idx] = _fill_block.remote(
            arr._block_shape(idx), value, np.dtype(dtype).str)
    return arr


def zeros(shape, dtype=np.float64, block_size=BLOCK_SIZE) -> DistArray:
    return _filled(shape, 0, dtype, block_size)


def ones(shape, dtype=np.float64, block_size=BLOCK_SIZE) -> DistArray:
    return _filled(shape, 1, dtype, block_size)


def from_numpy(a: np.ndarray, block_size=BLOCK_SIZE) -> DistArray:
    out = DistArray(a.shape, block_size=block_size, dtype=a.dtype)
    for idx in out._indices():
        lo, hi = _block_bounds(idx, a.shape, block_size)
        sl = tuple(slice(l, h) for l, h in zip(lo, hi))
        out.blocks[idx] = ray_tpu.put(np.ascontiguousarray(a[sl]))
    return out


def _elementwise(op, x: DistArray, y: DistArray) -> DistArray:
    if x.shape != y.shape or x.block_size != y.block_size:
        raise ValueError(
            f"shape/block mismatch: {x.shape}/{x.block_size} vs "
            f"{y.shape}/{y.block_size}")
    out = DistArray(x.shape, block_size=x.block_size,
                    dtype=np.result_type(x.dtype, y.dtype))
    for idx in x._indices():
        out.blocks[idx] = _ew.remote(op, x.blocks[idx], y.blocks[idx])
    return out


def add(x, y):
    return _elementwise("add", x, y)


def subtract(x, y):
    return _elementwise("subtract", x, y)


def multiply(x, y):
    return _elementwise("multiply", x, y)


def transpose(x: DistArray) -> DistArray:
    if x.ndim != 2:
        raise ValueError("transpose supports 2-D DistArrays")
    out = DistArray((x.shape[1], x.shape[0]), block_size=x.block_size,
                    dtype=x.dtype)
    for (i, j) in x._indices():
        out.blocks[(j, i)] = _transpose_block.remote(x.blocks[(i, j)])
    return out


def dot(x: DistArray, y: DistArray) -> DistArray:
    """Blockwise matmul: one task per OUTPUT block accumulates its whole
    k-chain (reference: distributed/core.py dot uses per-k tasks + sum;
    chaining in-task avoids the intermediate-object tree)."""
    if x.ndim != 2 or y.ndim != 2 or x.shape[1] != y.shape[0]:
        raise ValueError(f"dot shape mismatch: {x.shape} @ {y.shape}")
    if x.block_size != y.block_size:
        raise ValueError("dot needs matching block sizes")
    out = DistArray((x.shape[0], y.shape[1]), block_size=x.block_size,
                    dtype=np.result_type(x.dtype, y.dtype))
    k_blocks = x.grid[1]
    for (i, j) in out._indices():
        if k_blocks == 0:  # zero inner dim: matmul result is zeros
            out.blocks[(i, j)] = _fill_block.remote(
                out._block_shape((i, j)), 0, out.dtype.str)
            continue
        a_chain = [x.blocks[(i, k)] for k in range(k_blocks)]
        b_chain = [y.blocks[(k, j)] for k in range(k_blocks)]
        out.blocks[(i, j)] = _dot_chain.remote(
            k_blocks, *a_chain, *b_chain)
    return out
