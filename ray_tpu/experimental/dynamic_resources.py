"""Dynamic custom resources (reference:
python/ray/experimental/dynamic_resources.py set_resource — resize a
node's custom resource capacity at runtime; deletion via capacity 0)."""

from __future__ import annotations

from ray_tpu._private import global_state


def set_resource(resource_name: str, capacity: float,
                 node_id: bytes | str | None = None):
    """Set `resource_name`'s total capacity on a node (default: the
    caller's node). capacity=0 removes the resource. Newly freed
    capacity immediately unblocks queued tasks."""
    if resource_name in ("CPU", "TPU", "GPU", "memory"):
        raise ValueError(
            f"cannot dynamically update built-in resource "
            f"{resource_name!r} (reference imposes the same limit)")
    if capacity < 0:
        raise ValueError("capacity must be >= 0")
    cw = global_state.require_core_worker()
    if isinstance(node_id, str):
        node_id = bytes.fromhex(node_id)
    if node_id is None and cw.node_id is not None:
        node_id = cw.node_id.binary()
    return cw.set_resource(resource_name, float(capacity), node_id)
