"""Distributed shuffle (reference: python/ray/experimental/shuffle.py —
the two-phase map/reduce shuffle used as a data-plane stress workload).

Phase 1: map tasks partition their input block by key-hash and `put` one
object per reducer. Phase 2: reduce tasks fetch their partition from
every mapper and merge. All transport rides the object store (zero-copy
numpy on shared memory locally, chunked pulls across nodes)."""

from __future__ import annotations

from typing import Callable, Sequence

import ray_tpu


def _stable_key(record) -> int:
    """Cross-process-stable default key: builtin hash() is per-process
    randomized for strings, and mappers run in separate worker
    processes (same rationale as streaming.py _stable_hash)."""
    import pickle
    import zlib

    if isinstance(record, int):
        return record & 0x7FFFFFFF
    return zlib.crc32(pickle.dumps(record, protocol=4))


def simple_shuffle(input_blocks: Sequence,
                   num_reducers: int,
                   key_fn: Callable | None = None,
                   reduce_fn: Callable | None = None,
                   partition_resources: dict | None = None) -> list:
    """Shuffle rows from `input_blocks` (each a list of records) into
    `num_reducers` output blocks grouped by key_fn(record) % num_reducers.
    reduce_fn(list_of_partitions) -> merged block (default: concat).
    Returns the reduced blocks (materialized on the driver)."""

    if key_fn is None:
        key_fn = _stable_key
    resources = partition_resources or {"CPU": 1}

    @ray_tpu.remote(resources=resources, num_returns=num_reducers)
    def mapper(block):
        parts = [[] for _ in range(num_reducers)]
        for rec in block:
            parts[key_fn(rec) % num_reducers].append(rec)
        if num_reducers == 1:
            return parts[0]
        return tuple(parts)

    @ray_tpu.remote(resources=resources)
    def reducer(*partitions):
        if reduce_fn is not None:
            return reduce_fn(list(partitions))
        out = []
        for p in partitions:
            out.extend(p)
        return out

    map_out = [mapper.remote(block) for block in input_blocks]
    if num_reducers == 1:
        map_refs = [[ref] for ref in map_out]
    else:
        map_refs = map_out  # list of tuples of refs
    reduce_refs = [
        reducer.remote(*[refs[r] for refs in map_refs])
        for r in range(num_reducers)
    ]
    return ray_tpu.get(reduce_refs, timeout=600)
