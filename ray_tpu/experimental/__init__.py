"""ray_tpu.experimental (reference: python/ray/experimental/ —
internal_kv, async_api, dynamic_resources, shuffle)."""

from ray_tpu.experimental.async_api import as_concurrent_future, as_future
from ray_tpu.experimental.dynamic_resources import set_resource
from ray_tpu.experimental.shuffle import simple_shuffle

__all__ = ["as_concurrent_future", "as_future", "set_resource",
           "simple_shuffle"]
