"""GCS-backed internal KV (reference:
python/ray/experimental/internal_kv.py) — used by libraries (collective
rendezvous, tune, serve) for small control-plane state."""

from __future__ import annotations

from ray_tpu._private import global_state


def _kv_put(key: str, value: bytes, overwrite: bool = True) -> bool:
    return global_state.require_core_worker().kv_put(key, value, overwrite)


def _kv_get(key: str) -> bytes | None:
    return global_state.require_core_worker().kv_get(key)


def _kv_del(key: str) -> bool:
    return global_state.require_core_worker().kv_del(key)


def _kv_exists(key: str) -> bool:
    return global_state.require_core_worker().kv_exists(key)


def _kv_list(prefix: str) -> list[str]:
    return global_state.require_core_worker().kv_keys(prefix)
