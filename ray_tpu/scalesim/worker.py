"""Scale-sim worker process: drives a subset of the spoofed raylets.

Spawned by harness.run_scalesim, one per `client_procs`. Protocol:

1. read the shared config JSON (plane addresses, schedule, seeds);
2. connect every assigned SimRaylet to every plane and seed its hosted
   object locations, then touch `<out>.ready`;
3. poll for the go file, read the shared wall-clock T0;
4. follow the timetable: slice i covers
   [T0 + i*(window_s+gap_s), +window_s] — sleep to each slice start,
   drive the slice's (arm, kind) with this worker's clients until the
   slice deadline, record the completed-op count;
5. write counts + every acked KV write to `<out>` and exit 0.

Worker processes exist so the measured bottleneck is the CONTROL PLANE:
a single driving process is GIL-bound and caps both arms at the
harness's own speed; several of them generate enough concurrent demand
to saturate the single-director arm's one event loop while the sharded
arm keeps scaling across its processes."""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import time

from ray_tpu._private.config import Config
from ray_tpu.scalesim.harness import SimRaylet


async def _run(cfg: dict, indices: list[int], out: str) -> None:
    window_s = cfg["window_s"]
    gap_s = cfg["gap_s"]
    # clients per (plane, sim-raylet index)
    clients: dict[str, list[SimRaylet]] = {}
    for label, plane in cfg["planes"].items():
        config = Config.load({"gcs_shards": plane["shards"]})
        cs = [SimRaylet(i, cfg["seed"], cfg["raylets"], cfg["pool_size"])
              for i in indices]
        await asyncio.gather(*(c.connect(plane["gcs_address"], config,
                                         uds_dir=plane.get("uds_dir"))
                               for c in cs))
        await asyncio.gather(*(c.seed_locations() for c in cs))
        clients[label] = cs

    with open(out + ".ready.tmp", "w") as f:
        f.write("ready")
    os.rename(out + ".ready.tmp", out + ".ready")

    while not os.path.exists(cfg["go_path"]):
        await asyncio.sleep(0.02)
    with open(cfg["go_path"]) as f:
        t0 = float(f.read().strip())

    counts = []  # [arm, kind, window, n]
    for sl in cfg["schedule"]:
        start = t0 + sl["index"] * (window_s + gap_s)
        stop = start + window_s
        await asyncio.sleep(max(0.0, start - time.time()))
        cs = clients[sl["arm"]]
        kind = sl["kind"]
        streams = int(cfg.get("streams", 8))
        budget = int(window_s * 4000) + 64  # far beyond one slice
        if kind == "ops":
            work = [(c.issue_op, c.gen_ops(budget)) for c in cs]
        else:
            work = [(c.issue_decision, c.gen_decisions(budget))
                    for c in cs]
        slice_counts = [0] * len(work)

        async def drive(i, issue, items, offset):
            # `streams` concurrent op streams per sim raylet: a real
            # raylet has many control ops in flight at once (seal
            # registrations spawn a task per object, lease and pull
            # lookups overlap) — a depth-1 client measures its own
            # RTT, not the plane's capacity
            n = 0
            while time.time() < stop:
                await issue(items[(offset + n * streams) % len(items)])
                n += 1
            slice_counts[i] += n

        t_start = time.time()
        await asyncio.gather(*(
            drive(i, issue, items, k)
            for i, (issue, items) in enumerate(work)
            for k in range(streams)))
        # drain: pipelined notify()s issued this slice must be fully
        # dispatched server-side before they count (and before the next
        # slice starts measuring a different arm); the drain time stays
        # in this slice's denominator so backlog can't inflate the rate
        await asyncio.gather(*(c.gcs.barrier() for c in cs))
        counts.append([sl["arm"], kind, sl["window"], sum(slice_counts),
                       time.time() - t_start])

    # only the verify arm's acks count (same keys get independently
    # written on every plane; verification reads one plane)
    acked = {k: v.hex()
             for c in clients.get(cfg.get("verify_arm", ""), ())
             for k, v in c.acked_kv.items()}
    for cs in clients.values():
        for c in cs:
            await c.close()

    with open(out + ".tmp", "w") as f:
        json.dump({"counts": counts, "acked": acked}, f)
    os.rename(out + ".tmp", out)


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", required=True)
    parser.add_argument("--out", required=True)
    parser.add_argument("--clients", required=True,
                        help="comma-separated sim-raylet indices")
    args = parser.parse_args()
    with open(args.config) as f:
        cfg = json.load(f)
    indices = [int(x) for x in args.clients.split(",")]
    asyncio.run(_run(cfg, indices, args.out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
