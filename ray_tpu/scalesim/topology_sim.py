"""Scale-sim topology arm: ICI_RING vs PACK against a REAL GCS.

16 spoofed raylets register synthetic 4x4-torus TopologyCoords (the
node-index -> coord mapping is seeded-SHUFFLED, like real fleets where
allocation order has nothing to do with rack adjacency) into two live
directors — one per arm, so each arm's `gcs.placement_score_s`
histogram is its own. Unlike harness.py's table-op raylets, these
answer the 2PC (`prepare_bundle`/`commit_bundle`/...) over the duplex
registration connection and heartbeat their availability, so the
director runs the REAL placement path end to end.

Paired interleaved windows (the MICROBENCH discipline): each window
fills the fleet with `fleet // bundles` gangs in BOTH arms
(alternating), records per-gang ring circumference, simulated
spillback-chain hops, and client-observed placement latency, then
releases everything and verifies no raylet kept a bundle hold.

Measures (per arm):
- mean_ring_circumference — torus wire distance around consecutive
  bundle ranks incl. the wrap (ICI_RING target: == bundles, a perfect
  ring; PACK: whatever first-fit scatter produced);
- spillback_hops — greedy nearest-neighbor chain cost from a seeded
  origin node across the gang (what a lease forwarded along the
  PR 7 spillback chain pays in ICI hops);
- placement latency — client create->CREATED wall time, plus the
  director's own `gcs.placement_score_s` p99 (the <=5% A/B gate).
"""

from __future__ import annotations

import asyncio
import json
import random
import time

from ray_tpu._private import rpc
from ray_tpu._private import stats as _stats
from ray_tpu._private import topology as _topo
from ray_tpu._private.common import ResourceSet
from ray_tpu.scalesim.harness import ControlPlane

def _torus_for(n: int) -> tuple[int, int]:
    """Near-square 2D torus with exactly `n` positions, so every spoofed
    raylet gets a DISTINCT coord whatever --raylets says (16 -> 4x4; a
    prime count degenerates to a 1xN ring)."""
    a = int(n ** 0.5)
    while a > 1 and n % a:
        a -= 1
    return (a, n // a)


class TopoSimRaylet:
    """One spoofed raylet that really participates in placement: it
    registers (with a TopologyCoord), heartbeats availability, and
    serves the 2PC bundle handlers. Holds are tracked so the harness
    can prove none leak."""

    def __init__(self, idx: int, node_id: bytes, coord: _topo.TopologyCoord,
                 cpus: float = 1.0):
        self.idx = idx
        self.node_id = node_id
        self.coord = coord
        self.total = ResourceSet({"CPU": cpus})
        self.available = self.total.copy()
        self.holds: dict[tuple[bytes, int], dict] = {}
        self.conn: rpc.ReconnectingConnection | None = None
        self._beat_task: asyncio.Task | None = None

    def _handlers(self):
        return {
            "prepare_bundle": self.h_prepare,
            "commit_bundle": self.h_commit,
            "cancel_bundle": self.h_release,
            "return_bundle": self.h_release,
            "ping": lambda conn, d: "pong",
        }

    async def h_prepare(self, conn, d):
        need = ResourceSet.from_raw(d["resources"])
        if not need.is_subset_of(self.available):
            return False
        self.available.subtract(need)
        self.holds[(d["pg_id"], d["bundle_index"])] = {
            "need": need, "state": "PREPARED"}
        return True

    async def h_commit(self, conn, d):
        hold = self.holds.get((d["pg_id"], d["bundle_index"]))
        if hold is not None:
            hold["state"] = "COMMITTED"
        return True

    async def h_release(self, conn, d):
        hold = self.holds.pop((d["pg_id"], d["bundle_index"]), None)
        if hold is not None:
            self.available.add(hold["need"])
        return True

    async def connect(self, gcs_address: str):
        self.conn = rpc.ReconnectingConnection(
            gcs_address, handlers=self._handlers(),
            name=f"toposim{self.idx}", retry_timeout=30.0)
        conn = await self.conn.ensure_connected()
        await conn.call("register_node", {
            "node_id": self.node_id,
            "address": f"sim://{self.idx}",
            "resources": self.total.raw(),
            "available": self.available.raw(),
            "hostname": f"sim{self.idx}",
            "topology": self.coord.to_dict(),
        })
        self._beat_task = asyncio.create_task(self._beat_loop())

    async def _beat_loop(self):
        # fast availability beats so the director's view tracks the
        # 2PC holds within one create->create gap
        while True:
            await asyncio.sleep(0.05)
            try:
                await self.conn.call("heartbeat", {
                    "node_id": self.node_id,
                    "available": self.available.raw()})
            except Exception:
                await asyncio.sleep(0.2)

    async def close(self):
        if self._beat_task is not None:
            self._beat_task.cancel()
        if self.conn is not None:
            await self.conn.close()


def _sim_spillback_hops(members: list[_topo.TopologyCoord],
                        origin: _topo.TopologyCoord) -> float:
    """Greedy nearest-neighbor chain from `origin` visiting every gang
    member — the ICI hop cost a lease forwarded along the spillback
    chain pays when each raylet picks its topologically nearest next
    holder (raylet._topo_prefer)."""
    hops = 0.0
    at = origin
    left = list(members)
    while left:
        nxt = min(left, key=lambda c: _topo.torus_hops(
            at.coords, c.coords, at.dims))
        hops += _topo.torus_hops(at.coords, nxt.coords, at.dims)
        left.remove(nxt)
        at = nxt
    return hops


async def _run_arm_window(gcs, raylets, strategy: str, bundles: int,
                          gangs: int, rng: random.Random) -> list[dict]:
    """Fill the fleet with `gangs` gangs under `strategy`, measure each,
    then release everything and wait for the availability view to
    settle. Returns one record per gang."""
    fleet_cpus = sum(r.total.get("CPU") for r in raylets)

    async def wait_available(expect: float, timeout: float = 20.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            avail = await gcs.call("get_available_resources", {})
            total = sum(ResourceSet.from_raw(raw).get("CPU")
                        for raw in avail.values())
            if abs(total - expect) < 1e-6:
                return
            await asyncio.sleep(0.02)
        raise TimeoutError(
            f"director availability never reached {expect} CPUs")

    out = []
    created: list[bytes] = []
    coords_by_node = {r.node_id: r.coord for r in raylets}
    try:
        for g in range(gangs):
            await wait_available(fleet_cpus - g * bundles)
            pg_id = rng.randbytes(16)
            spec = [{"resources": ResourceSet({"CPU": 1.0}).raw()}
                    for _ in range(bundles)]
            t0 = time.perf_counter()
            reply = await gcs.call("create_placement_group", {
                "pg_id": pg_id, "bundles": spec, "strategy": strategy})
            state = reply["state"]
            deadline = time.monotonic() + 20.0
            while state != "CREATED":
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"{strategy} gang {g} stuck in {state}")
                await asyncio.sleep(0.02)
                rec = await gcs.call("get_placement_group",
                                     {"pg_id": pg_id})
                state = rec["state"] if rec else "REMOVED"
            latency = time.perf_counter() - t0
            created.append(pg_id)
            rec = await gcs.call("get_placement_group", {"pg_id": pg_id})
            members = [coords_by_node[b["node_id"]] for b in rec["bundles"]]
            origin = coords_by_node[rng.choice(raylets).node_id]
            out.append({
                "strategy": strategy,
                "ring_circumference": _topo.ring_circumference(members),
                "spillback_hops": _sim_spillback_hops(members, origin),
                "latency_s": latency,
                # PACK-downgrade marker: only meaningful for ICI_RING
                # requests (PACK never carries a plan by design)
                "fallback": (strategy == "ICI_RING"
                             and "topology_plan" not in rec),
            })
    finally:
        for pg_id in created:
            await gcs.call("remove_placement_group", {"pg_id": pg_id})
        await wait_available(fleet_cpus)
    return out


async def _run(plane_by_arm: dict, raylets_by_arm: dict, windows: int,
               bundles: int, seed: int) -> dict:
    rng = random.Random(seed)
    conns = {}
    for arm, plane in plane_by_arm.items():
        for r in raylets_by_arm[arm]:
            await r.connect(plane.gcs_address)
        conns[arm] = await rpc.connect(plane.gcs_address,
                                       name=f"toposim-driver-{arm}")
    records: dict[str, list[dict]] = {arm: [] for arm in plane_by_arm}
    gangs = len(next(iter(raylets_by_arm.values()))) // bundles
    try:
        warm_counts = {}
        for arm in plane_by_arm:
            # warmup gang per arm (not recorded): absorbs first-call
            # costs (import, cache build) so the p99 A/B compares
            # steady-state scoring, not process cold-start; the
            # director-side histogram delta below excludes it the same
            # way
            strategy = "ICI_RING" if arm == "ici_ring" else "PACK"
            await _run_arm_window(conns[arm], raylets_by_arm[arm],
                                  strategy, bundles, 1, rng)
            snap = await conns[arm].call("get_metrics", {})
            m = snap.get("gcs.placement_score_s") or {}
            warm_counts[arm] = list(m.get("counts") or [])
        for w in range(windows):
            # paired interleaved: every window runs both arms once,
            # alternating which goes first so box-load swings wash out
            order = list(plane_by_arm)
            if w % 2:
                order.reverse()
            for arm in order:
                strategy = "ICI_RING" if arm == "ici_ring" else "PACK"
                records[arm].extend(await _run_arm_window(
                    conns[arm], raylets_by_arm[arm], strategy,
                    bundles, gangs, rng))
        # director-side scoring histogram, per arm — warmup excluded by
        # per-bucket count delta (cumulative counts, so subtraction is
        # exact)
        score = {}
        for arm, conn in conns.items():
            snap = await conn.call("get_metrics", {})
            m = snap.get("gcs.placement_score_s") or {}
            counts = list(m.get("counts") or [])
            warm = warm_counts.get(arm) or [0] * len(counts)
            delta = [c - w for c, w in zip(counts, warm)]
            dm = {"counts": delta, "count": sum(delta),
                  "boundaries": m.get("boundaries") or []}
            score[arm] = {
                "count": dm["count"],
                "p99_s": _stats.percentile(dm, 0.99),
            }
    finally:
        for conn in conns.values():
            await conn.close()
        for rs in raylets_by_arm.values():
            for r in rs:
                await r.close()
    leaked = {arm: sum(len(r.holds) for r in rs)
              for arm, rs in raylets_by_arm.items()}
    return {"records": records, "score": score, "leaked_holds": leaked}


def run_topology_sim(raylets: int = 16, windows: int = 3,
                     bundles: int = 4, seed: int = 0,
                     out: str | None = None,
                     keep_dirs: bool = False) -> dict:
    """Run the topology arm. Returns per-arm medians/means plus the
    counter-verified geometry: every ICI_RING gang's ring circumference
    (target: == bundles, the perfect ring) vs the PACK control's, the
    simulated spillback-chain hops, and placement latency (client wall
    + director `gcs.placement_score_s` p99)."""
    rng = random.Random(seed)
    n = raylets
    torus = _torus_for(n)
    coords = [_topo.TopologyCoord(
        slice_id="sim-slice", coords=_topo._coords_of_index(i, torus),
        dims=torus, host_id=f"simhost{i:02d}")
        for i in range(n)]
    rng.shuffle(coords)  # allocation order != rack adjacency

    planes = {"ici_ring": ControlPlane(1, label="topo-ici"),
              "pack": ControlPlane(1, label="topo-pack")}
    raylets_by_arm = {
        arm: [TopoSimRaylet(i, bytes([arm_i, i]) * 8, coords[i])
              for i in range(n)]
        for arm_i, arm in enumerate(planes)
    }
    try:
        raw = asyncio.run(_run(planes, raylets_by_arm, windows,
                               bundles, seed))
    finally:
        for plane in planes.values():
            plane.close(remove_dir=not keep_dirs)

    def _mean(xs):
        return round(sum(xs) / max(len(xs), 1), 3)

    result: dict = {"raylets": n, "windows": windows, "bundles": bundles,
                    "seed": seed, "torus": list(torus), "arms": {}}
    for arm, recs in raw["records"].items():
        circ = [r["ring_circumference"] for r in recs]
        result["arms"][arm] = {
            "gangs": len(recs),
            "mean_ring_circumference": _mean(circ),
            "max_ring_circumference": max(circ, default=0.0),
            "mean_spillback_hops": _mean(
                [r["spillback_hops"] for r in recs]),
            "placement_latency_ms": {
                "mean": _mean([r["latency_s"] * 1e3 for r in recs]),
                "max": round(max((r["latency_s"] for r in recs),
                                 default=0.0) * 1e3, 3)},
            "score_p99_s": raw["score"][arm]["p99_s"],
            "score_count": raw["score"][arm]["count"],
            "fallbacks": sum(1 for r in recs if r["fallback"]),
        }
        result["arms"][arm]["leaked_holds"] = raw["leaked_holds"][arm]
    a, b = result["arms"]["ici_ring"], result["arms"]["pack"]
    result["circumference_ratio"] = round(
        b["mean_ring_circumference"]
        / max(a["mean_ring_circumference"], 1e-9), 2)
    result["spillback_hops_ratio"] = round(
        b["mean_spillback_hops"] / max(a["mean_spillback_hops"], 1e-9), 2)
    result["score_p99_ratio"] = round(
        a["score_p99_s"] / max(b["score_p99_s"], 1e-9), 3)
    if out:
        with open(out, "w") as f:
            json.dump(result, f, indent=1)
    return result
