"""`python -m ray_tpu.scalesim` — same surface as `ray-tpu scalesim`."""

import sys

from ray_tpu.scripts.cli import main

if __name__ == "__main__":
    sys.exit(main(["scalesim", *sys.argv[1:]]))
