from ray_tpu.scalesim.harness import ControlPlane, run_scalesim

__all__ = ["ControlPlane", "run_scalesim"]
