from ray_tpu.scalesim.elastic_sim import run_elastic_sim
from ray_tpu.scalesim.harness import ControlPlane, run_scalesim
from ray_tpu.scalesim.topology_sim import run_topology_sim

__all__ = ["ControlPlane", "run_elastic_sim", "run_scalesim",
           "run_topology_sim"]
