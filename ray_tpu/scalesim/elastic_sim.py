"""Scale-sim elastic arm: drain-aware vs static vs kill-based scale-down.

Ramps a synthetic demand series up and down against three fleets, each
with its own REAL director (harness.ControlPlane), and scores
node-hours x SLO violations per policy:

- ``static``  — never scales; capacity is always max (the no-autoscaler
  control: zero violations, maximum node-hours).
- ``drain``   — follows demand; scale-down goes through the elastic
  membership plane (``drain_node`` -> raylet migrates its object
  locations to a survivor -> ``node_drained`` -> DRAINED), so departed
  nodes' objects stay resolvable.
- ``kill``    — follows demand; scale-down abruptly closes the raylet's
  registration conn (the crash path: ``_remove_node`` reclaims its
  object locations exactly like a node loss).

Each spoofed raylet registers a handful of synthetic object locations
at join. The SLO ledger counts (a) objects from departed nodes that no
longer resolve in the GCS directory — the bytes a real fleet would
re-derive through lineage — and (b) capacity shortfall vs the demand
series. Score = node_hours * (1 + violations); lower is better. The
drain arm should match kill on node-hours and static on violations —
that inequality pair IS the planned-vs-crash A/B (the kill arm staying
green on everything else is the PR 4/7 safety-net control)."""

from __future__ import annotations

import asyncio
import json
import time

from ray_tpu._private import rpc
from ray_tpu._private.common import ResourceSet
from ray_tpu.scalesim.harness import ControlPlane

_OBJ_SIZE = 1024


class ElasticSimRaylet:
    """Spoofed raylet with the elastic-membership surface: registers,
    heartbeats, serves the 2PC bundle handlers AND the ``drain`` RPC —
    draining re-registers its object locations on a survivor before
    reporting ``node_drained``, exactly the real migration contract
    (directory-confirmed copy before the node's own entries drop)."""

    def __init__(self, idx: int, node_id: bytes, objects: int = 4):
        self.idx = idx
        self.node_id = node_id
        self.total = ResourceSet({"CPU": 1.0})
        self.available = self.total.copy()
        self.oids = [node_id[:8] + bytes([idx % 256, k]) * 4
                     for k in range(objects)]
        self.conn: rpc.ReconnectingConnection | None = None
        self.migrate_target: bytes | None = None  # set before drain
        self._beat_task: asyncio.Task | None = None
        self._draining = False
        self.drained = asyncio.Event()

    def _handlers(self):
        return {
            "drain": self.h_drain,
            "prepare_bundle": self.h_prepare,
            "commit_bundle": lambda conn, d: True,
            "cancel_bundle": self.h_release,
            "return_bundle": self.h_release,
            "ping": lambda conn, d: "pong",
        }

    async def h_prepare(self, conn, d):
        need = ResourceSet.from_raw(d["resources"])
        if self._draining or not need.is_subset_of(self.available):
            return False
        self.available.subtract(need)
        return True

    async def h_release(self, conn, d):
        return True

    async def h_drain(self, conn, d):
        if not self._draining:
            self._draining = True
            asyncio.create_task(self._do_drain())
        return {"state": "DRAINING"}

    async def _do_drain(self):
        conn = await self.conn.ensure_connected()
        migrated = 0
        if self.migrate_target is not None:
            for oid in self.oids:
                await conn.call("add_object_location", {
                    "object_id": oid, "node_id": self.migrate_target,
                    "size": _OBJ_SIZE})
                migrated += 1
        await conn.call("node_drained", {
            "node_id": self.node_id, "migrated": migrated,
            "leftovers": len(self.oids) - migrated})
        await self.close()
        self.drained.set()

    async def connect(self, gcs_address: str):
        self.conn = rpc.ReconnectingConnection(
            gcs_address, handlers=self._handlers(),
            name=f"elastic{self.idx}", retry_timeout=30.0)
        conn = await self.conn.ensure_connected()
        await conn.call("register_node", {
            "node_id": self.node_id,
            "address": f"sim://{self.idx}",
            "resources": self.total.raw(),
            "available": self.available.raw(),
            "hostname": f"sim{self.idx}",
        })
        for oid in self.oids:
            await conn.call("add_object_location", {
                "object_id": oid, "node_id": self.node_id,
                "size": _OBJ_SIZE})
        self._beat_task = asyncio.create_task(self._beat_loop())

    async def _beat_loop(self):
        while True:
            await asyncio.sleep(0.05)
            try:
                await self.conn.call("heartbeat", {
                    "node_id": self.node_id,
                    "available": self.available.raw()})
            except Exception:
                await asyncio.sleep(0.2)

    async def close(self):
        if self._beat_task is not None:
            self._beat_task.cancel()
        if self.conn is not None:
            await self.conn.close()


def _demand_series(max_nodes: int, windows: int) -> list[int]:
    """Triangle ramp max -> min -> max across the window budget (the
    autoscale shape that exercises both directions every run)."""
    lo = max(1, max_nodes // 4)
    series = []
    half = max(1, windows // 2)
    for w in range(windows):
        frac = (half - w) / half if w <= half else (w - half) / half
        series.append(max(lo, round(lo + (max_nodes - lo) * abs(frac))))
    return series


async def _run_arm(policy: str, plane: ControlPlane, max_nodes: int,
                   windows: int, objects_per_node: int) -> dict:
    gcs = await rpc.connect(plane.gcs_address, name=f"elastic-{policy}")
    fleet: list[ElasticSimRaylet] = []
    next_idx = 0
    departed_oids: list[bytes] = []
    node_hours = 0
    shortfall = 0
    recovery_s: list[float] = []
    demand = _demand_series(max_nodes, windows)

    async def spawn():
        nonlocal next_idx
        r = ElasticSimRaylet(next_idx,
                             bytes([next_idx % 251 + 1]) * 16,
                             objects=objects_per_node)
        next_idx += 1
        await r.connect(plane.gcs_address)
        fleet.append(r)

    async def wait_departed(node_id: bytes, timeout: float = 10.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            nodes = await gcs.call("get_all_nodes", {})
            if all(n["node_id"] != node_id for n in nodes):
                return
            await asyncio.sleep(0.02)
        raise TimeoutError(f"node never left the table ({policy})")

    async def scale_down(r: ElasticSimRaylet):
        departed_oids.extend(r.oids)
        t0 = time.monotonic()
        if policy == "drain":
            survivors = [s for s in fleet if s is not r]
            r.migrate_target = survivors[0].node_id if survivors else None
            reply = await gcs.call("drain_node", {"node_id": r.node_id})
            assert reply["state"] == "DRAINING", reply
            await asyncio.wait_for(r.drained.wait(), timeout=10.0)
        else:  # kill: abrupt conn close -> the GCS crash path
            await r.close()
        await wait_departed(r.node_id)
        recovery_s.append(time.monotonic() - t0)
        fleet.remove(r)

    try:
        for _ in range(max_nodes):
            await spawn()
        for want in demand:
            if policy != "static":
                while len(fleet) > want:
                    await scale_down(fleet[-1])
                while len(fleet) < want:
                    await spawn()
            node_hours += len(fleet)
            shortfall += max(0, want - len(fleet))
        lost = 0
        for oid in departed_oids:
            locs = await gcs.call("get_object_locations",
                                  {"object_id": oid})
            if not locs:
                lost += 1
    finally:
        for r in list(fleet):
            await r.close()
        await gcs.close()
    violations = lost + shortfall
    return {
        "policy": policy,
        "demand": demand,
        "node_hours": node_hours,
        "objects_departed": len(departed_oids),
        "objects_lost": lost,
        "bytes_rederived": lost * _OBJ_SIZE,
        "capacity_shortfall": shortfall,
        "slo_violations": violations,
        "score": node_hours * (1 + violations),
        "mean_recovery_ms": round(
            sum(recovery_s) / max(len(recovery_s), 1) * 1e3, 2),
        "departures": len(recovery_s),
    }


def run_elastic_sim(raylets: int = 6, windows: int = 6,
                    objects_per_node: int = 4,
                    out: str | None = None,
                    keep_dirs: bool = False) -> dict:
    """Run all three policies, each against its own live director.
    Returns per-arm ledgers plus the drain-vs-kill A/B (recovery time
    and bytes re-derived) and the drain-vs-static node-hour saving."""
    arms: dict[str, dict] = {}
    for policy in ("static", "drain", "kill"):
        plane = ControlPlane(1, label=f"elastic-{policy}")
        try:
            arms[policy] = asyncio.run(_run_arm(
                policy, plane, raylets, windows, objects_per_node))
        finally:
            plane.close(remove_dir=not keep_dirs)
    result = {
        "raylets": raylets, "windows": windows,
        "objects_per_node": objects_per_node,
        "arms": arms,
        # planned-vs-crash A/B: drain must match kill on node-hours and
        # static on losses; kill's losses are the lineage re-derive bill
        "node_hours_saved_vs_static": (
            arms["static"]["node_hours"] - arms["drain"]["node_hours"]),
        "bytes_saved_vs_kill": (
            arms["kill"]["bytes_rederived"]
            - arms["drain"]["bytes_rederived"]),
        "score_ratio_kill_over_drain": round(
            arms["kill"]["score"] / max(arms["drain"]["score"], 1e-9), 3),
        "score_ratio_static_over_drain": round(
            arms["static"]["score"] / max(arms["drain"]["score"], 1e-9), 3),
    }
    if out:
        with open(out, "w") as f:
            json.dump(result, f, indent=1)
    return result
