"""Scale-sim: many spoofed raylets against a REAL control plane on one box.

Control-plane scalability can't be measured honestly on a small box by
spawning a real cluster — worker processes eat the budget before the GCS
is ever the bottleneck. This harness spawns only the control plane
itself (director + store shards, the same processes a real cluster
runs), then drives it from spoofed raylets spread over `client_procs`
worker PROCESSES: each sim raylet owns a director connection plus the
shard-routing client (gcs/client.py), a partition of synthetic object
ids it "hosts", and a seeded, PRE-GENERATED op stream shaped like the
real steady state (object-directory add/remove/batched lookups + KV —
the PR 5/6 hot ops). Multiple client processes matter: a single driving
process is itself GIL-bound and would measure the harness, not the
plane; with several, the single-director arm saturates its one event
loop (one core, ever) while the sharded arm's N processes keep scaling —
which is precisely the claim under test.

Two rate metrics, per-second over paired interleaved windows (the
MICROBENCH discipline — both arms live simultaneously, every window runs
each arm once on a shared wall-clock timetable, median over windows):

- **gcs ops**: the mixed table-op stream, summed across sim raylets;
- **scheduler decisions**: one decision = the owner-side locality pick a
  raylet/driver makes per task burst — a batched location lookup over
  the task's args, argmax-bytes node choice, then registering the result
  object's location (2 table round trips of real scheduler shape).

Plus the **director-bypass** counter-check: per-arm server CPU sampled
from /proc (director + every shard) and normalized per issued op. The
sharded arm must drive its steady-state stream AROUND the director
(`director_cpu_us_per_op` collapsing toward 0, `director_bypass_ratio`
« 1) — that is the property that removes the single-process ceiling.
NOTE the wall-clock aggregate rates only exceed the legacy arm when the
box has >= shards+2 cores: on smaller boxes every process timeshares the
same cores and the sharded plane's extra per-tick syscalls (4 sockets
where the legacy arm coalesces onto 1) dominate the measurement — the
rates stay honest, the bypass ratio carries the scaling claim.

Fault story (the chaos-sweep analogs, runnable without a cluster):

- `kill_shard=True` SIGKILLs a seeded store shard MID-window and
  restarts it on its fixed port against its journal; every acked KV
  write is verified readable afterwards (zero lost acked ops — clients
  ride rpc.ReconnectingConnection retry, exactly like real processes);
- at teardown the same shard is quiesced, snapshotted (canonical bytes),
  killed, restarted, and snapshotted again — journal replay must restore
  the tables BIT-IDENTICAL (`replay_identical`).
"""

from __future__ import annotations

import asyncio
import json
import os
import random
import shutil
import statistics
import subprocess
import sys
import time

from ray_tpu._private import rpc
from ray_tpu._private.config import Config
from ray_tpu._private.node import (
    new_session_dir,
    start_gcs,
    start_gcs_shard,
    start_gcs_shards,
)
from ray_tpu.gcs.client import GcsClient

OP_BATCH_LOOKUP = 16  # oids per batched directory lookup
DECISION_ARGS = 3     # plasma args per simulated task's locality pick
                      # (a real lease request carries 1-4, PR 5)


class ControlPlane:
    """One live control plane (director + `shards` store shards) in its
    own session dir. shards=1 spawns NO shard processes — the legacy
    single-GCS layout, byte-identical to today's clusters."""

    def __init__(self, shards: int, label: str = "plane"):
        self.label = label
        self.shards = shards
        self.config = Config.load({"gcs_shards": shards})
        self.session_dir = new_session_dir()
        self.shard_procs, self.shard_addresses = start_gcs_shards(
            self.session_dir, self.config)
        self.gcs_svc, self.gcs_address = start_gcs(
            self.session_dir, self.config,
            shard_addresses=self.shard_addresses)

    def cpu_seconds(self) -> dict[str, float]:
        """Cumulative CPU (utime+stime) per control-plane process, from
        /proc — the director-bypass counter-check: in the sharded arm the
        director must burn ~no CPU per steady-state op."""
        out = {}
        ticks = os.sysconf("SC_CLK_TCK")
        procs = [("director", self.gcs_svc)] + [
            (f"shard{i}", svc) for i, svc in enumerate(self.shard_procs)]
        for name, svc in procs:
            try:
                with open(f"/proc/{svc.proc.pid}/stat") as f:
                    parts = f.read().rsplit(") ", 1)[1].split()
                out[name] = (int(parts[11]) + int(parts[12])) / ticks
            except (OSError, IndexError, ValueError):
                out[name] = 0.0
        return out

    def kill_shard(self, index: int):
        self.shard_procs[index].kill()

    def restart_shard(self, index: int):
        old = self.shard_procs[index]
        svc, _addr = start_gcs_shard(self.session_dir, self.config, index,
                                     port=old.shard_port)
        self.shard_procs[index] = svc

    def kill_director(self):
        self.gcs_svc.kill()

    def restart_director(self):
        port = int(self.gcs_address.rsplit(":", 1)[1])
        self.gcs_svc, _addr = start_gcs(
            self.session_dir, self.config, port=port,
            shard_addresses=self.shard_addresses)

    def close(self, remove_dir: bool = True):
        for svc in [self.gcs_svc, *self.shard_procs]:
            try:
                svc.kill()
            except Exception:
                pass
        if remove_dir:
            shutil.rmtree(self.session_dir, ignore_errors=True)


def sim_node_ids(raylets: int) -> list[bytes]:
    return [bytes([i % 256, i // 256]) * 8 for i in range(raylets)]


def sim_pool(seed: int, idx: int, pool_size: int) -> list[bytes]:
    """Client idx's hosted object ids — derived purely from (seed, idx)
    so every worker process recomputes every client's pool with no IPC."""
    rng = random.Random(seed * 7919 + idx)
    return [rng.randbytes(16) for _ in range(pool_size)]


class SimRaylet:
    """One spoofed raylet: a director connection + shard-routing client,
    a pool of object ids it hosts, and a seeded op stream."""

    def __init__(self, idx: int, seed: int, raylets: int, pool_size: int):
        self.idx = idx
        self.rng = random.Random(seed * 104729 + idx)
        self.node_ids = sim_node_ids(raylets)
        self.node_id = self.node_ids[idx % len(self.node_ids)]
        self.pool = sim_pool(seed, idx, pool_size)
        self.shared_pool = [oid for i in range(raylets)
                            for oid in sim_pool(seed, i, pool_size)]
        self.acked_kv: dict[str, bytes] = {}
        self._kv_seq = 0
        self.gcs: GcsClient | None = None

    async def connect(self, gcs_address: str, config: Config,
                      uds_dir: str | None = None):
        director = rpc.ReconnectingConnection(
            rpc.prefer_uds(gcs_address, uds_dir),
            name=f"sim{self.idx}", retry_timeout=30.0)
        self.gcs = GcsClient(director, config, uds_dir=uds_dir)
        await self.gcs.ensure_connected()

    async def seed_locations(self):
        for oid in self.pool:
            await self.gcs.call("add_object_location", {
                "object_id": oid, "node_id": self.node_id,
                "size": self.rng.randrange(1 << 10, 1 << 20)})

    async def close(self):
        if self.gcs is not None:
            await self.gcs.close()

    # -- the workloads -------------------------------------------------
    # Op payloads are pre-generated OUTSIDE the timed slice (issue_* just
    # pops and sends): the subject under test is the control plane, not
    # the harness's rng.

    def gen_ops(self, n: int) -> list[tuple[str, dict, str | None]]:
        """Pre-generate `n` steps of the steady-state table-op mix: the
        per-object seal/free directory stream every raylet emits (PR 5 —
        single-key adds/removes, the hottest op class by count), a
        single-key lookup tail, and KV traffic. Batched lookups are
        measured by the DECISION metric, not here. Each entry:
        (method, payload, acked_kv_key)."""
        ops = []
        for _ in range(n):
            r = self.rng.random()
            if r < 0.40:
                ops.append(("add_object_location", {
                    "object_id": self.rng.choice(self.pool),
                    "node_id": self.rng.choice(self.node_ids),
                    "size": self.rng.randrange(1 << 10, 1 << 20)}, None))
            elif r < 0.55:
                ops.append(("remove_object_location", {
                    "object_id": self.rng.choice(self.pool),
                    "node_id": self.rng.choice(self.node_ids)}, None))
            elif r < 0.70:
                ops.append(("get_object_locations", {
                    "object_id": self.rng.choice(self.shared_pool)},
                    None))
            elif r < 0.85:
                self._kv_seq += 1
                key = f"sim:{self.idx}:{self._kv_seq}"
                ops.append(("kv_put", {"key": key,
                                       "value": self.rng.randbytes(64)},
                            key))
            else:
                ops.append(("kv_get", {
                    "key": f"sim:{self.idx}:"
                           f"{self.rng.randrange(1, self._kv_seq + 2)}"},
                    None))
        return ops

    async def issue_op(self, op):
        method, payload, kv_key = op
        if method in ("add_object_location", "remove_object_location"):
            # Directory updates are PIPELINED in the real raylet
            # (raylet._register_location: best-effort, issued from a
            # spawned task per seal, errors swallowed) — model them as
            # notify()s; the 45% call mix paces them and the post-slice
            # barrier() proves the server drained every one.
            await self.gcs.notify(method, payload)
            return
        await self.gcs.call(method, payload)
        if kv_key is not None:
            # the call returned => the plane acked it: it must survive
            # any later shard kill (journal replay)
            self.acked_kv[kv_key] = payload["value"]

    def gen_decisions(self, n: int) -> list[list[bytes]]:
        return [[self.rng.choice(self.shared_pool)
                 for _ in range(DECISION_ARGS)] for _ in range(n)]

    async def issue_decision(self, args: list[bytes]):
        """One owner-side scheduling decision: locality-pick the node
        holding the most argument bytes (the PR 5 lease-targeting
        lookup), then register the result object's location there."""
        locs = await self.gcs.call("get_object_locations_batch",
                                   {"object_ids": args})
        by_node: dict[bytes, int] = {}
        for rec in (locs or {}).values():
            for nid in rec["nodes"]:
                by_node[nid] = by_node.get(nid, 0) + int(rec["size"])
        best = (max(by_node, key=by_node.get) if by_node
                else self.node_id)
        await self.gcs.call("add_object_location", {
            "object_id": args[0][::-1], "node_id": best,
            "size": 1 << 12})


def build_schedule(windows: int, arms: list[str]) -> list[dict]:
    """The shared wall-clock timetable every worker process follows:
    window w runs every (kind, arm) slice once, arms interleaved inside
    the window so box-load swings hit both equally."""
    slices = []
    for w in range(windows):
        for kind in ("ops", "decisions"):
            for arm in arms:
                slices.append({"index": len(slices), "window": w,
                               "kind": kind, "arm": arm})
    return slices


async def _shard_snapshot(address: str) -> dict:
    conn = await rpc.connect(address, name="scalesim-snap", timeout=10.0)
    try:
        return await conn.call("shard_snapshot", {}, timeout=10.0)
    finally:
        await conn.close()


def _stat(samples: list[float]) -> dict:
    return {"median": round(statistics.median(samples), 2),
            "samples": [round(s, 2) for s in samples]}


def run_scalesim(shards: int = 4, raylets: int = 16, windows: int = 5,
                 window_s: float = 1.0, seed: int = 0,
                 kill_shard: bool = False, legacy_arm: bool = True,
                 pool_size: int = 32, out: str | None = None,
                 keep_dirs: bool = False, client_procs: int = 3,
                 streams: int = 8, gap_s: float = 0.3) -> dict:
    """Run the scale-sim. Returns (and optionally writes) a result dict
    with per-arm `gcs_ops_per_s` / `decisions_per_s` medians over
    `windows` paired interleaved windows, speedups, and — with
    `kill_shard` — the zero-lost-acked-ops + bit-identical-replay
    verdicts for a seeded mid-window shard kill."""
    rng = random.Random(seed)
    planes = [ControlPlane(shards, label=f"shards{shards}")]
    if legacy_arm:
        planes.append(ControlPlane(1, label="shards1"))
    arm_labels = [p.label for p in planes]
    victim = rng.randrange(max(1, shards)) if shards > 1 else 0
    schedule = build_schedule(windows, arm_labels)
    persist = planes[0].config.gcs_persistence

    result: dict = {
        "shards": shards, "raylets": raylets, "windows": windows,
        "window_s": window_s, "seed": seed, "client_procs": client_procs,
        "arms": {}, "kill": None,
    }

    workdir = os.path.join(planes[0].session_dir, "scalesim")
    os.makedirs(workdir, exist_ok=True)
    go_path = os.path.join(workdir, "go")
    cfg = {
        "planes": {p.label: {"gcs_address": p.gcs_address,
                             "shards": p.shards,
                             "uds_dir": os.path.join(p.session_dir, "sock")}
                   for p in planes},
        "raylets": raylets, "pool_size": pool_size, "seed": seed,
        "schedule": schedule, "window_s": window_s, "gap_s": gap_s,
        "go_path": go_path, "verify_arm": arm_labels[0],
        "streams": streams,
    }
    cfg_path = os.path.join(workdir, "config.json")
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)

    # spread sim raylets over worker processes
    assign = [[] for _ in range(client_procs)]
    for i in range(raylets):
        assign[i % client_procs].append(i)

    procs = []
    out_paths = []
    try:
        for w, indices in enumerate(assign):
            if not indices:
                continue
            res_path = os.path.join(workdir, f"worker{w}.json")
            out_paths.append(res_path)
            log = open(os.path.join(workdir, f"worker{w}.log"), "w")
            procs.append((subprocess.Popen(
                [sys.executable, "-m", "ray_tpu.scalesim.worker",
                 "--config", cfg_path, "--out", res_path,
                 "--clients", ",".join(map(str, indices))],
                stdout=log, stderr=log,
                env={**os.environ,
                     "PYTHONPATH": os.pathsep.join(
                         [os.path.dirname(os.path.dirname(
                             os.path.dirname(os.path.abspath(__file__)))),
                          os.environ.get("PYTHONPATH", "")])}), log))

        # barrier: workers connect + seed their pools, then touch .ready
        deadline = time.monotonic() + 60
        for res_path in out_paths:
            while not os.path.exists(res_path + ".ready"):
                for p, _log in procs:
                    if p.poll() is not None:
                        raise RuntimeError(
                            f"scalesim worker died during setup "
                            f"(see {workdir})")
                if time.monotonic() > deadline:
                    raise TimeoutError("scalesim workers not ready in 60s")
                time.sleep(0.05)

        t0 = time.time() + 0.5
        cpu_before = {p.label: p.cpu_seconds() for p in planes}
        with open(go_path + ".tmp", "w") as f:
            f.write(str(t0))
        os.rename(go_path + ".tmp", go_path)

        kill_info = None
        if kill_shard and shards > 1 and persist:
            # SIGKILL the victim shard halfway through the middle
            # window's sharded ops slice, restart on its fixed port
            kill_slice = next(
                s for s in schedule
                if s["window"] == windows // 2 and s["kind"] == "ops"
                and s["arm"] == arm_labels[0])
            t_kill = (t0 + kill_slice["index"] * (window_s + gap_s)
                      + window_s / 2)
            time.sleep(max(0.0, t_kill - time.time()))
            tk = time.perf_counter()
            planes[0].kill_shard(victim)
            planes[0].restart_shard(victim)
            kill_info = {"victim_shard": victim,
                         "window": kill_slice["window"],
                         "restart_s": round(time.perf_counter() - tk, 3)}

        total_s = len(schedule) * (window_s + gap_s) + 30
        for p, log in procs:
            p.wait(timeout=max(60.0, t0 + total_s - time.time()))
            log.close()
            if p.returncode != 0:
                raise RuntimeError(
                    f"scalesim worker exited rc={p.returncode} "
                    f"(see {workdir})")

        cpu_after = {p.label: p.cpu_seconds() for p in planes}
        counts: dict[tuple, float] = {}
        elapsed: dict[tuple, float] = {}
        acked: dict[str, bytes] = {}
        for res_path in out_paths:
            with open(res_path) as f:
                rec = json.load(f)
            for arm, kind, w, n, dt in rec["counts"]:
                counts[(arm, kind, w)] = counts.get((arm, kind, w), 0) + n
                elapsed[(arm, kind, w)] = max(
                    elapsed.get((arm, kind, w), 0.0), dt)
            for k, v in rec["acked"].items():
                acked[k] = bytes.fromhex(v)

        async def _post():
            nonlocal kill_info
            if kill_info is not None:
                # zero lost acked ops: every kv write a worker got an
                # ack for must read back its value post-restart
                plane = planes[0]
                director = rpc.ReconnectingConnection(
                    plane.gcs_address, name="scalesim-verify")
                client = GcsClient(director, plane.config)
                checked = 0
                for key, value in acked.items():
                    got = await client.call("kv_get", {"key": key})
                    if got != value:
                        raise AssertionError(
                            f"acked op lost: kv[{key!r}] read back "
                            f"{'missing' if got is None else 'wrong'} "
                            f"after shard kill")
                    checked += 1
                kill_info["acked_ops_verified"] = checked
                kill_info["lost_ops"] = 0
                await client.close()
            # teardown replay check: quiesced canonical snapshot ->
            # kill -> journal-replay restart -> BIT-IDENTICAL snapshot
            # (meaningless without a journal: gcs_persistence=False
            # restarts a shard empty by design)
            if planes[0].shards > 1 and persist:
                addr = planes[0].shard_addresses[victim]
                before = await _shard_snapshot(addr)
                planes[0].kill_shard(victim)
                await asyncio.to_thread(planes[0].restart_shard, victim)
                after = await _shard_snapshot(addr)
                if kill_info is None:
                    kill_info = {"victim_shard": victim}
                kill_info["replay_identical"] = (
                    before["state"] == after["state"])
                if not kill_info["replay_identical"]:
                    raise AssertionError(
                        f"shard {victim} journal replay diverged from "
                        f"its pre-kill tables ({len(before['state'])} vs "
                        f"{len(after['state'])} canonical bytes)")

        asyncio.run(_post())

        def _rate(label, kind, w):
            return (counts.get((label, kind, w), 0)
                    / max(elapsed.get((label, kind, w), window_s),
                          window_s))

        for label in arm_labels:
            # director-bypass counter-check: CPU the plane's processes
            # burned across this arm's slices (they idle during the other
            # arm's), normalized per issued table op (a decision ≈ 2 ops:
            # one batched lookup + one location add). In the sharded arm
            # the steady-state stream must route AROUND the director —
            # its CPU/op collapses toward zero, which is the property
            # that removes the single-process ceiling (the wall-clock
            # aggregate only shows it with >= shards+2 cores; see
            # MICROBENCH control_plane notes).
            dcpu = {k: cpu_after[label][k] - cpu_before[label].get(k, 0.0)
                    for k in cpu_after[label]}
            n_ops = sum(counts.get((label, "ops", w), 0)
                        for w in range(windows))
            n_dec = sum(counts.get((label, "decisions", w), 0)
                        for w in range(windows))
            issued = max(n_ops + 2 * n_dec, 1)
            result["arms"][label] = {
                "gcs_ops_per_s": _stat(
                    [_rate(label, "ops", w) for w in range(windows)]),
                "decisions_per_s": _stat(
                    [_rate(label, "decisions", w)
                     for w in range(windows)]),
                "server_cpu_s": {k: round(v, 3) for k, v in dcpu.items()},
                "director_cpu_us_per_op": round(
                    dcpu.get("director", 0.0) / issued * 1e6, 2),
            }
        result["kill"] = kill_info
        result["cores"] = os.cpu_count()
        if legacy_arm:
            a = result["arms"][arm_labels[0]]
            b = result["arms"]["shards1"]
            result["director_bypass_ratio"] = round(
                a["director_cpu_us_per_op"]
                / max(b["director_cpu_us_per_op"], 1e-9), 4)
    finally:
        for p, _log in procs:
            if p.poll() is None:
                p.kill()
        for plane in planes:
            plane.close(remove_dir=not keep_dirs)

    if legacy_arm:
        a = result["arms"][arm_labels[0]]
        b = result["arms"]["shards1"]
        result["speedup_gcs_ops"] = round(
            a["gcs_ops_per_s"]["median"]
            / max(b["gcs_ops_per_s"]["median"], 1e-9), 2)
        result["speedup_decisions"] = round(
            a["decisions_per_s"]["median"]
            / max(b["decisions_per_s"]["median"], 1e-9), 2)
    if out:
        with open(out, "w") as f:
            json.dump(result, f, indent=1)
    return result
