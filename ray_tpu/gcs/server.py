"""GCS — global control store server (head-node control plane).

Capability parity with the reference's gcs_server process (reference:
src/ray/gcs/gcs_server/gcs_server.h:57): cluster membership + heartbeat
failure detection (GcsHeartbeatManager, gcs_heartbeat_manager.h:32), actor
lifecycle + restart (GcsActorManager, gcs_actor_manager.h:157), actor
scheduling (GcsActorScheduler, gcs_actor_scheduler.h:83), job registry,
KV store + pubsub (GcsPubSub over Redis in the reference — here an
in-process table + push channels over our RPC layer; no Redis process),
object location directory (GcsObjectManager), and placement groups
(GcsPlacementGroupManager, gcs_placement_group_manager.h:130).

State is write-through persisted via GcsStorage (WAL + snapshot under the
session dir — see storage.py; reference: gcs_table_storage.h:294 persists
to Redis): a restarted GCS reloads jobs/actors/named-actors/placement
groups/KV/node table, raylets and drivers redial and re-register
(rpc.ReconnectingConnection), and the cluster continues — the analog of
the reference's GCS fault-tolerance behavior
(python/ray/tests/test_gcs_fault_tolerance.py).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import random
import time

from ray_tpu._private import debug_state as _debug
from ray_tpu._private import failpoints as _fp
from ray_tpu._private import rpc
from ray_tpu._private import sampling_profiler as _sprof
from ray_tpu._private import stats as _stats
from ray_tpu._private import topology as _topo
from ray_tpu._private import tracing as _tracing
from ray_tpu._private.common import InsufficientResources, ResourceSet
from ray_tpu._private.config import Config, get_config, set_config

logger = logging.getLogger("ray_tpu.gcs")

M_TRACE_APPLY_FAILURES = _stats.Count(
    "gcs.trace_apply_failures_total",
    "profile/trace batches dropped by a failed trace-table apply")
M_TOPO_FALLBACKS = _stats.Count(
    "gcs.placement_topology_fallbacks_total",
    "ICI_RING placements that fell back to PACK (no candidate node had "
    "registered topology coords, or the scoring seam failed)")
M_PLACEMENT_SCORE_S = _stats.Histogram(
    "gcs.placement_score_s", _stats.LATENCY_BOUNDARIES_S,
    "one placement decision: strategy dispatch + candidate scoring in "
    "_place_bundles (every strategy — the PACK-vs-ICI_RING latency A/B "
    "reads this histogram per arm)")
M_PREEMPT_NOTICES = _stats.Count(
    "gcs.preemption_notices_total",
    "preemption notices received (node.preempt_notice failpoint or "
    "drain --preempt) — each starts a compressed drain; a notice on an "
    "already-draining node is counted but idempotent")
M_RING_REPLACEMENTS = _stats.Count(
    "gcs.ring_replacements_total",
    "ICI_RING placements scored around a torus hole (>=1 masked "
    "DRAINING or recently-departed coord) — gang re-placements after "
    "a drain/preemption")

# How long a departed node's torus coords stay visible as masked_coords
# in new ICI_RING plans (re-placements around the hole are recorded and
# counted within this window; a re-registration clears the hole early).
_DEPARTED_COORD_TTL_S = 300.0

# Actor states (reference: src/ray/protobuf/gcs.proto ActorTableData.ActorState)
DEPENDENCIES_UNREADY = "DEPENDENCIES_UNREADY"
PENDING_CREATION = "PENDING_CREATION"
ALIVE = "ALIVE"
RESTARTING = "RESTARTING"
DEAD = "DEAD"


class GcsServer:
    def __init__(self, config: Config, storage=None,
                 shard_addresses: list[str] | None = None):
        self.config = config
        self.storage = storage
        # Store-shard tier (gcs/shard.py): the director advertises the
        # addresses (get_shard_map) so clients key-route table ops
        # directly, and keeps its own connection per shard to push
        # actor/pg directory mirrors, node-death prunes, and live
        # failpoint arming. Empty = single-process layout (shards=1).
        self.shard_addresses = list(shard_addresses or [])
        self._shard_conns: list = [None] * len(self.shard_addresses)
        # sibling-UDS dir (run() fills it): local shard dials skip TCP
        self._uds_dir: str | None = None
        self.kv: dict[str, bytes] = {}
        self.subscriptions: dict[str, set[rpc.Connection]] = {}
        # node_id(bytes) -> node info dict
        self.nodes: dict[bytes, dict] = {}
        self.node_conns: dict[bytes, rpc.Connection] = {}
        self.last_heartbeat: dict[bytes, float] = {}
        self.available: dict[bytes, ResourceSet] = {}
        # actor_id -> record
        self.actors: dict[bytes, dict] = {}
        self.named_actors: dict[tuple[str, str], bytes] = {}
        self.jobs: dict[bytes, dict] = {}
        self.next_job = 1
        # object_id -> {"nodes": set of node_ids, "size": bytes} — the
        # object directory (reference: object_directory.h). Sizes feed
        # the raylets' locality-aware lease targeting; multiple nodes
        # feed multi-source striped pulls.
        self.object_locations: dict[bytes, dict] = {}
        self.placement_groups: dict[bytes, dict] = {}
        # ICI_RING scoring leaves the winning candidate's plan here for
        # _do_create_pg to stamp onto the CREATED record (single-threaded
        # asyncio: set synchronously in _place_bundles, read immediately
        # after it returns)
        self._last_topology_plan: dict | None = None
        # (coords, snake order) of coord-bearing nodes — rebuilt only
        # when membership changes, so per-decision scoring cost stays in
        # the PACK arm's latency bucket (the <=5% A/B gate)
        self._topo_cache: tuple[dict, list] | None = None
        # node8 -> (departed_ts, topology dict) for coord-bearing nodes
        # that drained or died: ICI_RING plans stamp these as
        # masked_coords so re-placement around the torus hole stays
        # visible in the placement record after the node is gone
        self._departed_coords: dict[str, tuple[float, dict]] = {}
        self.server = rpc.Server(self._handlers(), on_disconnect=self._on_disconnect,
                                 name="gcs")
        self._pending_actor_queue: list[bytes] = []
        self._pending_logged: set[bytes] = set()
        # Structured cluster events ring (reference: src/ray/util/event.h
        # EventManager; fed by every process via "report_event").
        import collections as _collections

        self.events: _collections.deque = _collections.deque(maxlen=1000)
        # Profile-event table (reference: the GCS profile table fed by
        # core_worker profiling.h batches), bounded ring.

        self.profile_events: _collections.deque = _collections.deque(
            maxlen=200_000)
        # Trace table: flat span rows (tracing.py spans carry a `tid`
        # trace id in extra_data) indexed out of the profile batches so
        # one request's cross-process tree is queryable by trace id.
        self.trace_spans: _collections.deque = _collections.deque(
            maxlen=50_000)
        # Continuous-profiling ring (sampling_profiler.py): collapsed-
        # stack sample batches from every process class, bounded —
        # director-memory-only like the other observability rings.
        self.profile_samples: _collections.deque = _collections.deque(
            maxlen=4000)
        # per-shard t_end of the last ingested profiler window (the
        # at-least-once ack _drain_shard_profiles carries)
        self._shard_profile_acks: dict[int, float] = {}
        # Metrics time series: source -> metric -> ring of [ts, value]
        # samples, fed by raylet heartbeat piggybacks and worker/driver
        # push_metrics notifies (~2s cadence; ~10 min of history).
        self.metrics_history: dict[str, dict] = {}
        self.metrics_history_samples = 300
        self.metrics_last_push: dict[str, float] = {}
        # histogram p99 exemplars (trace-id strings can't ride the
        # scalar rings): source -> hist name -> {"trace_id","value","ts"}
        self.metrics_exemplars: dict[str, dict] = {}
        # History epoch: metrics-history and trace rings are DIRECTOR
        # MEMORY ONLY by contract (ARCHITECTURE.md "State introspection"
        # — the lossy-restart contract): a restart resets them, and
        # consumers (`ray-tpu top`) detect the reset by this changing.
        self.started_at = time.time()
        if storage is not None:
            self._restore()

    # ---- persistence (reference: gcs_table_storage.h:294) ----
    def _restore(self):
        """Reload control state after a GCS restart. Raylets redial and
        re-register (restoring conns/heartbeats); actors that were mid-
        scheduling are re-queued; ALIVE actors keep running untouched."""
        st = self.storage
        self.kv = dict(st.table("kv"))
        if _fp.KV_KEY in self.kv:
            # armed failpoints survive a GCS restart with the KV
            _fp.apply_kv_value(self.kv[_fp.KV_KEY])
        if _tracing.KV_KEY in self.kv:
            # so does a live trace-sampling override
            _tracing.apply_kv_value(self.kv[_tracing.KV_KEY])
        if _sprof.KV_KEY in self.kv:
            # and a live profiling-rate override
            _sprof.apply_kv_value(self.kv[_sprof.KV_KEY])
        self.jobs = dict(st.table("jobs"))
        self.next_job = st.get("meta", "next_job", 1)
        now = time.monotonic()
        for node_id, info in st.table("nodes").items():
            self.nodes[node_id] = dict(info)
            # Full resources until the raylet's next heartbeat corrects it.
            self.available[node_id] = ResourceSet.from_raw(info["resources"])
            # Grace window: a raylet that outlived the GCS reconnects well
            # within the normal heartbeat timeout.
            self.last_heartbeat[node_id] = now
        for actor_id, rec in st.table("actors").items():
            rec = dict(rec)
            self.actors[actor_id] = rec
            if rec["state"] in (PENDING_CREATION, RESTARTING):
                self._pending_actor_queue.append(actor_id)
        for key, actor_id in st.table("named_actors").items():
            ns, _, name = key.partition("\x00")
            self.named_actors[(ns, name)] = actor_id
        for pg_id, rec in st.table("placement_groups").items():
            rec = dict(rec)
            rec.pop("creating", None)
            self.placement_groups[pg_id] = rec
        if self.nodes or self.actors:
            logger.info(
                "restored GCS state: %d nodes, %d actors, %d pgs, %d kv",
                len(self.nodes), len(self.actors),
                len(self.placement_groups), len(self.kv))

    def _persist(self, table: str, key, value, sync: bool = False):
        if _fp.ARMED:
            # table-apply seam: `raise` fails the mutating handler (the
            # caller sees RemoteError and retries idempotently), `delay`
            # widens the apply->publish window a GCS crash can land in
            _fp.fire_strict("gcs.table.apply")
        if self.storage is not None:
            self.storage.put(table, key, value, sync=sync)

    def _persist_del(self, table: str, key):
        if self.storage is not None:
            self.storage.delete(table, key)

    def _persist_actor(self, rec):
        # Everything in rec travelled over msgpack RPC, so it persists
        # as-is. Actor transitions fsync: losing one strands live handles.
        self._persist("actors", rec["actor_id"], rec, sync=True)

    def _persist_pg(self, rec):
        clean = {k: v for k, v in rec.items() if k != "creating"}
        self._persist("placement_groups", rec["pg_id"], clean, sync=True)

    def _handlers(self):
        return {
            "kv_put": self.h_kv_put,
            "kv_get": self.h_kv_get,
            "kv_del": self.h_kv_del,
            "kv_exists": self.h_kv_exists,
            "kv_keys": self.h_kv_keys,
            "subscribe": self.h_subscribe,
            "unsubscribe": self.h_unsubscribe,
            "publish": self.h_publish,
            "register_node": self.h_register_node,
            "heartbeat": self.h_heartbeat,
            "set_resource": self.h_set_resource,
            "get_all_nodes": self.h_get_all_nodes,
            "get_available_resources": self.h_get_available_resources,
            "drain_node": self.h_drain_node,
            "node_drained": self.h_node_drained,
            "register_job": self.h_register_job,
            "register_actor": self.h_register_actor,
            "get_actor": self.h_get_actor,
            "get_named_actor": self.h_get_named_actor,
            "list_actors": self.h_list_actors,
            "kill_actor": self.h_kill_actor,
            "actor_alive": self.h_actor_alive,
            "report_worker_failure": self.h_report_worker_failure,
            "add_object_location": self.h_add_object_location,
            "remove_object_location": self.h_remove_object_location,
            "get_object_locations": self.h_get_object_locations,
            "get_object_locations_batch": self.h_get_object_locations_batch,
            "create_placement_group": self.h_create_placement_group,
            "remove_placement_group": self.h_remove_placement_group,
            "get_placement_group": self.h_get_placement_group,
            "get_named_placement_group": self.h_get_named_placement_group,
            "list_placement_groups": self.h_list_placement_groups,
            "add_profile_events": self.h_add_profile_events,
            "get_profile_events": self.h_get_profile_events,
            "get_trace_spans": self.h_get_trace_spans,
            "add_profile_samples": self.h_add_profile_samples,
            "get_profile_samples": self.h_get_profile_samples,
            "push_metrics": self.h_push_metrics,
            "get_metrics_history": self.h_get_metrics_history,
            "report_event": self.h_report_event,
            "get_events": self.h_get_events,
            "get_metrics": self.h_get_metrics,
            "get_shard_map": self.h_get_shard_map,
            "debug_state": self.h_debug_state,
            "debug_stacks": lambda conn, data: _debug.collect_stacks(),
            "ping": lambda conn, data: "pong",
        }

    # ---- store-shard tier ----
    async def h_get_shard_map(self, conn, d):
        """Addresses of the store shards, in index order — the client-
        side routing table (gcs/client.py shard_for)."""
        return {"addresses": self.shard_addresses}

    async def _shard_conn(self, idx: int):
        conn = self._shard_conns[idx]
        if conn is None:
            async def _resync(c, idx=idx):
                await self._resync_shard(idx, c)

            conn = rpc.ReconnectingConnection(
                rpc.prefer_uds(self.shard_addresses[idx], self._uds_dir),
                name=f"gcs->shard{idx}", on_reconnect=_resync,
                retry_timeout=self.config.gcs_reconnect_timeout_s)
            self._shard_conns[idx] = conn
        return conn

    def _shard_index_for(self, key) -> int:
        from ray_tpu.gcs.client import shard_for

        return shard_for(key, len(self.shard_addresses))

    async def _resync_shard(self, idx: int, conn):
        """Re-push everything the director owns that this shard mirrors:
        actor/pg public records in its partition, plus live failpoint /
        trace-sampling specs. Runs at startup and after every shard
        reconnect, so a shard restarted WHILE a mirror push was lost
        still converges (its journal already replayed the rest)."""
        records = []
        for actor_id, rec in self.actors.items():
            if self._shard_index_for(actor_id) == idx:
                records.append(["actors", actor_id, self._actor_public(rec)])
        for pg_id, rec in self.placement_groups.items():
            if self._shard_index_for(pg_id) == idx:
                records.append(["pgs", pg_id, _pg_public(rec)])
        if records:
            await conn.call("mirror_apply", {"records": records})
        spec = self.kv.get(_fp.KV_KEY)
        if spec:
            await conn.notify("configure_failpoints", {"spec": spec})
        hz = self.kv.get(_sprof.KV_KEY)
        if hz:
            await conn.notify("configure_profiling", {"spec": hz})

    async def _mirror(self, table: str, key, value):
        """Push one actor/pg public record (value=None deletes) to the
        owning shard. Best-effort with a short bound: a shard mid-restart
        must not stall scheduling — the reconnect resync repairs it."""
        if not self.shard_addresses:
            return
        conn = await self._shard_conn(self._shard_index_for(key))
        try:
            await asyncio.wait_for(
                conn.call("mirror_apply",
                          {"records": [[table, key, value]]}),
                timeout=2.0)
        except Exception:
            logger.warning("mirror push to shard lost (%s); reconnect "
                           "resync will repair", table)

    async def _broadcast_shards(self, method: str, data):
        async def one(idx):
            try:
                conn = await self._shard_conn(idx)
                await asyncio.wait_for(conn.call(method, data), timeout=2.0)
            except Exception:
                logger.warning("shard %d broadcast %r failed", idx, method)

        # concurrent: callers like _remove_node gate failover on this —
        # serial 2s timeouts would stack per unreachable shard
        await asyncio.gather(*(one(i)
                               for i in range(len(self.shard_addresses))))

    # ---- kv ----
    async def h_kv_put(self, conn, d):
        key = d["key"]
        if not d.get("overwrite", True) and key in self.kv:
            return False
        self.kv[key] = d["value"]
        self._persist("kv", key, d["value"])
        if key == _fp.KV_KEY:
            # live fault-injection arming: apply here, broadcast to every
            # subscribed raylet/worker/driver (failpoints.arm_cluster),
            # and forward to the store shards (they don't subscribe)
            _fp.apply_kv_value(d["value"])
            await self.publish(_fp.CHANNEL, d["value"])
            if self.shard_addresses:
                await self._broadcast_shards(
                    "configure_failpoints", {"spec": d["value"]})
        elif key == _tracing.KV_KEY:
            # live trace-sampling override (ray_tpu.set_trace_sampling):
            # same apply-here + broadcast plane as the failpoints
            _tracing.apply_kv_value(d["value"])
            await self.publish(_tracing.CHANNEL, d["value"])
        elif key == _sprof.KV_KEY:
            # live profiler arming (ray_tpu.set_profiling): apply here,
            # broadcast to subscribers, forward to the store shards
            # (they don't subscribe to pubsub)
            _sprof.apply_kv_value(d["value"])
            await self.publish(_sprof.CHANNEL, d["value"])
            if self.shard_addresses:
                await self._broadcast_shards(
                    "configure_profiling", {"spec": d["value"]})
        return True

    async def h_kv_get(self, conn, d):
        return self.kv.get(d["key"])

    async def h_kv_del(self, conn, d):
        self._persist_del("kv", d["key"])
        return self.kv.pop(d["key"], None) is not None

    async def h_kv_exists(self, conn, d):
        return d["key"] in self.kv

    async def h_kv_keys(self, conn, d):
        prefix = d.get("prefix", "")
        return [k for k in self.kv if k.startswith(prefix)]

    # ---- pubsub ----
    async def h_subscribe(self, conn, d):
        self.subscriptions.setdefault(d["channel"], set()).add(conn)
        return True

    async def h_unsubscribe(self, conn, d):
        self.subscriptions.get(d["channel"], set()).discard(conn)
        return True

    async def h_publish(self, conn, d):
        await self.publish(d["channel"], d["data"])
        return True

    async def publish(self, channel: str, data):
        if _fp.ARMED and channel != _fp.CHANNEL:
            # publish seam: drop_conn DROPS this publish (subscribers
            # must survive a lost state push — e.g. the owner-side actor
            # poll backstop); never injected on the failpoints channel
            # itself, which must stay reliable to disarm a sweep
            if await _fp.fire_async("gcs.publish") == "drop_conn":
                logger.warning("gcs.publish failpoint dropped a publish "
                               "on %r", channel)
                return
        for conn in list(self.subscriptions.get(channel, ())):
            if conn.closed:
                self.subscriptions[channel].discard(conn)
                continue
            try:
                await conn.push(channel, data)
            except Exception:
                self.subscriptions[channel].discard(conn)

    # ---- nodes ----
    async def h_register_node(self, conn, d):
        node_id = d["node_id"]
        info = {
            "node_id": node_id,
            "address": d["address"],  # raylet rpc address
            "object_manager_address": d.get("object_manager_address", d["address"]),
            # bulk object data-plane listener (raylet/transfer.py); ""
            # when the node runs without one (peers fall back to the
            # legacy chunked rpc pull)
            "bulk_address": d.get("bulk_address", ""),
            "resources": d["resources"],  # raw quantized dict
            "hostname": d.get("hostname", ""),
            "is_head": d.get("is_head", False),
            "labels": d.get("labels", {}),
            # util/accelerators.TpuSliceDescriptor dict or None: this
            # host's ICI domain, consumed by _place_bundles
            "tpu_slice": d.get("tpu_slice"),
            # _private/topology.TopologyCoord dict or None: the node's
            # position in the torus (ICI_RING scoring, spillback
            # ordering, locality tie-breaks all read it)
            "topology": d.get("topology"),
            "state": "ALIVE",
            "start_time": time.time(),
        }
        rejoining = node_id in self.nodes  # redial after a GCS restart
        self.nodes[node_id] = info
        self._topo_cache = None
        # a re-registering node fills its own torus hole
        self._departed_coords.pop(node_id.hex()[:8], None)
        self.available[node_id] = ResourceSet.from_raw(
            d.get("available", d["resources"]))
        self.last_heartbeat[node_id] = time.monotonic()
        conn.context["node_id"] = node_id
        self.node_conns[node_id] = conn
        self._persist("nodes", node_id, info)
        if not rejoining:
            await self.publish("nodes",
                               {"event": "added", "node": _node_public(info)})
        logger.info("node %s: %s @ %s",
                    "re-registered" if rejoining else "registered",
                    node_id.hex()[:8], d["address"])
        if not rejoining:
            from ray_tpu._private.events import INFO

            self._event(INFO, "NODE_ADDED",
                        f"node {node_id.hex()[:8]} joined @ {d['address']}",
                        node_id=node_id.hex())
        await self._try_schedule_pending_actors()
        await self._retry_pending_pgs()
        return True

    async def h_set_resource(self, conn, d):
        """ray.experimental.set_resource: forward to the target raylet,
        then refresh this table's view (reference: gcs_resource_manager
        UpdateResources)."""
        node_id = d.get("node_id") or next(
            (nid for nid, info in self.nodes.items()
             if info["state"] == "ALIVE"), None)
        node_conn = self.node_conns.get(node_id)
        if node_conn is None or node_conn.closed:
            raise ValueError(f"no live raylet for node "
                             f"{node_id.hex()[:8] if node_id else None}")
        reply = await node_conn.call("set_resource", {
            "resource_name": d["resource_name"],
            "capacity": d["capacity"],
        })
        info = self.nodes.get(node_id)
        if info is not None:
            info["resources"] = reply["total"]
            self._persist("nodes", node_id, info)
            # let every raylet refresh its cluster view (spillback
            # scoring and api.nodes() read it)
            await self.publish("nodes", {"event": "updated",
                                         "node": _node_public(info)})
        self.available[node_id] = ResourceSet.from_raw(reply["available"])
        return True

    async def h_heartbeat(self, conn, d):
        if _fp.ARMED:
            # heartbeat seam: `raise` makes beats fail while the conn
            # stays up — the raylet's fail-stop window must catch it
            await _fp.fire_async_strict("gcs.heartbeat")
        node_id = d["node_id"]
        self.last_heartbeat[node_id] = time.monotonic()
        if "metrics" in d:
            # heartbeat-piggybacked raylet metric sample (the raylet
            # sends one every ~4th beat) — feed the time-series ring
            self._ingest_metrics(
                d.get("metrics_source")
                or f"{node_id.hex()[:8]}/raylet", d["metrics"])
        if "available" in d and node_id in self.nodes:
            self.available[node_id] = ResourceSet.from_raw(d["available"])
            if any(r["state"] == "PENDING"
                   for r in self.placement_groups.values()):
                await self._retry_pending_pgs()
            # resources freed elsewhere may unblock queued actors —
            # without this, a pending actor waits for a node REGISTRATION
            # that may never come (the deadlock: all slots busy at
            # creation time, freed later)
            if self._pending_actor_queue:
                await self._try_schedule_pending_actors()
        return True

    async def h_get_all_nodes(self, conn, d):
        return [_node_public(info) for info in self.nodes.values()]

    async def h_get_available_resources(self, conn, d):
        """Heartbeat-fresh per-node availability, used by raylets for
        load-aware spillback (reference: the scheduler's cluster resource
        view fed by resource usage broadcast, cluster_resource_scheduler.cc:217)."""
        return {node_id: avail.raw()
                for node_id, avail in self.available.items()
                # DRAINING nodes are leaving — spillback must not target
                # them, so they simply vanish from this view
                if self.nodes.get(node_id, {}).get("state") == "ALIVE"}

    async def h_drain_node(self, conn, d):
        """Start (or report) a graceful drain: ALIVE -> DRAINING here;
        the raylet then migrates its plasma objects to survivors,
        finishes in-flight leases (bounded by the deadline), checkpoints
        restartable actor state, calls node_drained and exits — so the
        node finalizes DRAINED, never tripping the crash path. `preempt`
        compresses the deadline (checkpoints first, objects best-effort)
        and counts a preemption notice. Idempotent: a second drain call
        or a notice on an already-draining node reports the in-progress
        state without restarting anything."""
        node_id = d["node_id"]
        info = self.nodes.get(node_id)
        preempt = bool(d.get("preempt"))
        if preempt:
            M_PREEMPT_NOTICES.inc()
        if info is None:
            return {"state": "UNKNOWN"}
        if info["state"] == "DRAINING":
            return {"state": "DRAINING",
                    "deadline_s": info.get("drain_deadline_s")}
        deadline_s = d.get("deadline_s")
        if deadline_s is None:
            deadline_s = (self.config.preempt_drain_deadline_s if preempt
                          else self.config.drain_deadline_s)
        info["state"] = "DRAINING"
        info["drain_deadline_s"] = float(deadline_s)
        info["drain_preempt"] = preempt
        info["drain_started"] = time.time()
        self._persist("nodes", node_id, info)
        from ray_tpu._private.events import WARNING

        self._event(WARNING, "NODE_DRAINING",
                    f"node {node_id.hex()[:8]} draining "
                    f"({'preempt' if preempt else 'planned'}, "
                    f"deadline {float(deadline_s):.1f}s)",
                    node_id=node_id.hex(), preempt=preempt)
        # "updated" (not "removed"): every raylet keeps the node in its
        # cluster view but reads state=DRAINING and stops targeting it
        # for spillback/locality; new placements mask its coords
        await self.publish("nodes", {"event": "updated",
                                     "node": _node_public(info)})
        node_conn = self.node_conns.get(node_id)
        if node_conn is not None and not node_conn.closed:
            try:
                await asyncio.wait_for(
                    node_conn.call("drain", {"deadline_s": deadline_s,
                                             "preempt": preempt}),
                    timeout=5.0)
            except Exception:
                logger.warning("drain RPC to %s failed; the heartbeat "
                               "checker will reap it past the deadline",
                               node_id.hex()[:8])
        return {"state": "DRAINING", "deadline_s": deadline_s}

    async def h_node_drained(self, conn, d):
        """The raylet finished draining and is about to exit."""
        await self._finish_drain(d["node_id"],
                                 migrated=int(d.get("migrated", 0)),
                                 leftovers=int(d.get("leftovers", 0)))
        return True

    def _remember_departed(self, node_id: bytes, topo: dict | None):
        if not topo:
            return
        now = time.time()
        self._departed_coords[node_id.hex()[:8]] = (now, dict(topo))
        for key in [k for k, (ts, _) in self._departed_coords.items()
                    if now - ts > _DEPARTED_COORD_TTL_S]:
            self._departed_coords.pop(key, None)

    async def _finish_drain(self, node_id: bytes, migrated: int = 0,
                            leftovers: int = 0):
        """Planned twin of _remove_node: the node leaves as DRAINED, so
        nothing trips the crash path — restartable actors relocate
        without burning a restart, and only this node's own directory
        entries drop (migrated copies on survivors keep every object
        resolvable)."""
        info = self.nodes.pop(node_id, None)
        self.available.pop(node_id, None)
        self._topo_cache = None
        self.last_heartbeat.pop(node_id, None)
        self.node_conns.pop(node_id, None)
        if info is None:
            return
        self._remember_departed(node_id, info.get("topology"))
        from ray_tpu._private.events import INFO

        self._event(INFO, "NODE_DRAINED",
                    f"node {node_id.hex()[:8]} drained "
                    f"({migrated} objects migrated, {leftovers} left)",
                    node_id=node_id.hex(), migrated=migrated)
        info["state"] = "DRAINED"
        self._persist_del("nodes", node_id)
        await self.publish("nodes", {"event": "removed",
                                     "node": _node_public(info),
                                     "reason": "drained"})
        if self.shard_addresses:
            await self._broadcast_shards("prune_node", {"node_id": node_id})
        # Planned relocation: restartable actors move to a survivor
        # without consuming a restart; pinned (max_restarts=0) ones die.
        for actor_id, rec in list(self.actors.items()):
            if rec.get("node_id") == node_id and rec["state"] in (ALIVE, PENDING_CREATION):
                await self._on_actor_interrupted(actor_id, "node drained",
                                                 planned=True)
        for oid, rec in list(self.object_locations.items()):
            rec["nodes"].discard(node_id)
            if not rec["nodes"]:
                # a leftover the drain could not migrate in time: same
                # typed-loss path as a crash, scoped to the leftovers
                del self.object_locations[oid]

    async def _remove_node(self, node_id: bytes, reason: str):
        info = self.nodes.pop(node_id, None)
        self.available.pop(node_id, None)
        self._topo_cache = None
        self.last_heartbeat.pop(node_id, None)
        self.node_conns.pop(node_id, None)
        if info is None:
            return
        self._remember_departed(node_id, info.get("topology"))
        from ray_tpu._private.events import ERROR

        self._event(ERROR, "NODE_REMOVED",
                    f"node {node_id.hex()[:8]} removed: {reason}",
                    node_id=node_id.hex(), reason=reason)
        info["state"] = "DEAD"
        self._persist_del("nodes", node_id)
        await self.publish("nodes", {"event": "removed",
                                     "node": _node_public(info),
                                     "reason": reason})
        if self.shard_addresses:
            # the object-directory partitions live on the shards: drop
            # every location entry naming the dead node
            await self._broadcast_shards("prune_node", {"node_id": node_id})
        # Fail or restart actors that lived on this node.
        for actor_id, rec in list(self.actors.items()):
            if rec.get("node_id") == node_id and rec["state"] in (ALIVE, PENDING_CREATION):
                await self._on_actor_interrupted(actor_id, f"node died ({reason})")
        for oid, rec in list(self.object_locations.items()):
            rec["nodes"].discard(node_id)
            if not rec["nodes"]:
                # no copy left anywhere: pulls waiting on this object
                # hit the empty-directory deadline and fail typed
                del self.object_locations[oid]

    async def heartbeat_checker(self):
        cfg = self.config
        timeout = cfg.heartbeat_interval_s * cfg.num_heartbeats_timeout
        while True:
            await asyncio.sleep(cfg.heartbeat_interval_s)
            now = time.monotonic()
            for node_id, last in list(self.last_heartbeat.items()):
                limit = timeout
                info = self.nodes.get(node_id)
                if info is not None and info.get("state") == "DRAINING":
                    # a draining raylet is busy migrating: give it its
                    # full drain budget + grace before the crash path
                    # takes over (it normally exits via node_drained
                    # well before this)
                    limit = max(timeout,
                                float(info.get("drain_deadline_s") or 0.0)
                                + cfg.drain_grace_s)
                if now - last > limit:
                    logger.warning("node %s missed heartbeats; declaring dead",
                                   node_id.hex()[:8])
                    await self._remove_node(node_id, reason="heartbeat timeout")

    # ---- jobs ----
    async def h_register_job(self, conn, d):
        # Idempotent by driver-supplied token: a replayed call (reply lost
        # across a GCS restart) returns the already-allocated job instead
        # of minting a ghost.
        token = d.get("token") or ""
        if token:
            for rec in self.jobs.values():
                if rec.get("token") == token:
                    return {"job_id": rec["job_id"]}
        job_id = self.next_job.to_bytes(4, "big")
        self.next_job += 1
        self.jobs[job_id] = {"job_id": job_id, "driver_addr": d.get("driver_addr", ""),
                             "start_time": time.time(), "state": "RUNNING",
                             "token": token}
        self._persist("meta", "next_job", self.next_job)
        self._persist("jobs", job_id, self.jobs[job_id])
        return {"job_id": job_id}

    # ---- actors ----
    async def h_register_actor(self, conn, d):
        """Register + schedule an actor creation.

        Protocol parity (reference: gcs_actor_manager.h:125-127): caller
        registers the actor; GCS owns scheduling + lifetime from then on.
        Returns once the actor is scheduled (ALIVE) or queued.
        """
        spec = d["spec"]
        actor_id = spec["actor_id"]
        # Idempotent: a client retrying across a GCS restart (or a lost
        # reply) must not double-register.
        existing_rec = self.actors.get(actor_id)
        if existing_rec is not None:
            return self._actor_public(existing_rec)
        name = spec["actor_creation"].get("name") or ""
        namespace = spec["actor_creation"].get("namespace") or "default"
        if name:
            key = (namespace, name)
            if key in self.named_actors:
                existing = self.named_actors[key]
                if self.actors.get(existing, {}).get("state") != DEAD:
                    raise ValueError(f"actor name {name!r} already taken")
            self.named_actors[key] = actor_id
            self._persist("named_actors", f"{namespace}\x00{name}", actor_id)
        rec = {
            "actor_id": actor_id,
            "spec": spec,
            "state": PENDING_CREATION,
            "address": "",
            "task_channel": "",
            "node_id": None,
            "worker_id": None,
            "name": name,
            "namespace": namespace,
            "num_restarts": 0,
            "max_restarts": spec["actor_creation"].get("max_restarts", 0),
            "death_cause": "",
        }
        self.actors[actor_id] = rec
        self._persist_actor(rec)
        await self._mirror("actors", actor_id, self._actor_public(rec))
        await self._schedule_actor(actor_id)
        return self._actor_public(rec)

    async def _schedule_actor(self, actor_id: bytes):
        rec = self.actors[actor_id]
        spec = rec["spec"]
        need = ResourceSet.from_raw(spec["resources"])
        # Random-among-feasible policy (reference:
        # gcs_actor_schedule_strategy.h:42 GcsRandomActorScheduleStrategy),
        # honoring placement-group bundle location when present.
        candidates = []
        if spec.get("pg_id") is not None:
            pg = self.placement_groups.get(spec["pg_id"])
            if pg and pg["state"] == "CREATED":
                idx = spec.get("bundle_index", -1)
                bundle_nodes = {b["node_id"] for i, b in enumerate(pg["bundles"])
                                if idx in (-1, i)}
                candidates = [n for n in bundle_nodes if n in self.nodes]
        if not candidates:
            candidates = [
                node_id for node_id, avail in self.available.items()
                if need.is_subset_of(avail)
            ]
        # Only ALIVE nodes with a live raylet connection are placeable.
        # A restored-from-storage node whose raylet hasn't redialed yet
        # is NOT dead (its actors are alive) — skip it and let the
        # heartbeat checker decide its fate, never _remove_node from
        # here. DRAINING nodes are leaving: never place new actors there.
        candidates = [
            n for n in candidates
            if (c := self.node_conns.get(n)) is not None and not c.closed
            and self.nodes.get(n, {}).get("state") == "ALIVE"
        ]
        if not candidates:
            if actor_id not in self._pending_actor_queue:
                self._pending_actor_queue.append(actor_id)
            # one-shot logging: the heartbeat-driven retry re-enters here
            # every interval for a stuck actor
            if actor_id not in self._pending_logged:
                self._pending_logged.add(actor_id)
                logger.info("actor %s pending: no feasible node",
                            actor_id.hex()[:8])
                # infeasible-anywhere warning (reference:
                # cluster_task_manager.cc logs infeasible tasks)
                totals = [ResourceSet.from_raw(n["resources"])
                          for n in self.nodes.values()]
                if not any(need.is_subset_of(t) for t in totals):
                    logger.warning(
                        "actor %s requires %s, which exceeds every "
                        "node's TOTAL capacity — it will never schedule "
                        "on the current cluster", actor_id.hex()[:8],
                        need.to_dict())
            return
        self._pending_logged.discard(actor_id)
        node_id = random.choice(candidates)
        conn = self.node_conns[node_id]
        rec["node_id"] = node_id
        try:
            reply = await conn.call("create_actor", {"spec": spec})
        except Exception as e:
            if isinstance(getattr(e, "exc", None), InsufficientResources):
                # The GCS's availability view was stale (lease grants race
                # the heartbeat): that is a scheduling miss, not an actor
                # failure — requeue, and correct the view so the next
                # pass picks another node (the true value arrives with
                # the node's next heartbeat).
                self.available[node_id] = ResourceSet()
                if actor_id not in self._pending_actor_queue:
                    self._pending_actor_queue.append(actor_id)
                logger.info("actor %s bounced off %s (stale availability);"
                            " requeued", actor_id.hex()[:8],
                            node_id.hex()[:8])
                return
            logger.warning("actor creation on %s failed: %s", node_id.hex()[:8], e)
            await self._on_actor_interrupted(actor_id, f"creation failed: {e}")
            return
        rec["state"] = ALIVE
        rec["address"] = reply["worker_address"]
        # same-node direct task channel of the hosting worker ("" when
        # unavailable; owners on other nodes can't reach it and fall
        # back to the rpc address)
        rec["task_channel"] = reply.get("task_channel") or ""
        rec["worker_id"] = reply["worker_id"]
        await self._publish_actor(rec)

    async def _on_actor_interrupted(self, actor_id: bytes, reason: str,
                                    planned: bool = False):
        rec = self.actors.get(actor_id)
        if rec is None or rec["state"] == DEAD:
            return
        restarts_left = (rec["max_restarts"] == -1
                         or rec["num_restarts"] < rec["max_restarts"])
        if planned:
            # drain relocation: moving a restartable actor is free (no
            # restart burned) — only actors pinned at max_restarts=0
            # cannot be relocated and die with the node
            restarts_left = rec["max_restarts"] != 0
        if restarts_left:
            if not planned:
                rec["num_restarts"] += 1
            # the new incarnation checks the KV for drained-away state
            # (actor_ckpt:<id>, written by the departing raylet) and
            # restores via __ray_restore__ before taking traffic
            rec["spec"]["restore"] = True
            rec["state"] = RESTARTING
            rec["address"] = ""
            await self._publish_actor(rec)
            await self._schedule_actor(actor_id)
        else:
            rec["state"] = DEAD
            rec["death_cause"] = reason
            rec["address"] = ""
            if self.kv.pop(f"actor_ckpt:{actor_id.hex()}", None) is not None:
                self._persist_del("kv", f"actor_ckpt:{actor_id.hex()}")
            await self._publish_actor(rec)

    async def _publish_actor(self, rec):
        # Every externally-visible actor transition goes through here, so
        # it is also the persistence + event point.
        if rec["state"] in (DEAD, RESTARTING):
            from ray_tpu._private.events import ERROR, WARNING

            self._event(
                ERROR if rec["state"] == DEAD else WARNING,
                "ACTOR_DEAD" if rec["state"] == DEAD else "ACTOR_RESTART",
                f"actor {rec['actor_id'].hex()[:8]} "
                f"({rec['spec']['name']}) -> {rec['state']}: "
                f"{rec.get('death_cause') or 'restarting'}",
                actor_id=rec["actor_id"].hex(),
                class_name=rec["spec"]["name"])
        self._persist_actor(rec)
        # mirror BEFORE the publish: a subscriber poked awake by the push
        # must read back at-least-as-fresh state from the owning shard
        await self._mirror("actors", rec["actor_id"], self._actor_public(rec))
        await self.publish(f"actor:{rec['actor_id'].hex()}", self._actor_public(rec))

    def _actor_public(self, rec):
        return {
            "actor_id": rec["actor_id"],
            "state": rec["state"],
            "address": rec["address"],
            "node_id": rec["node_id"],
            "name": rec["name"],
            "namespace": rec["namespace"],
            "num_restarts": rec["num_restarts"],
            "max_restarts": rec["max_restarts"],
            "death_cause": rec["death_cause"],
            "task_channel": (rec.get("task_channel", "")
                             if rec["state"] == ALIVE else ""),
            "class_name": rec["spec"]["name"],
        }

    async def h_get_actor(self, conn, d):
        rec = self.actors.get(d["actor_id"])
        return self._actor_public(rec) if rec else None

    async def h_get_named_actor(self, conn, d):
        key = (d.get("namespace") or "default", d["name"])
        actor_id = self.named_actors.get(key)
        if actor_id is None:
            return None
        return self._actor_public(self.actors[actor_id])

    async def h_list_actors(self, conn, d):
        return [self._actor_public(r) for r in self.actors.values()]

    async def h_actor_alive(self, conn, d):
        """Raylet reports a restarted/relocated actor is up (unused in the
        normal path — creation reply carries the address)."""
        rec = self.actors.get(d["actor_id"])
        if rec:
            rec["state"] = ALIVE
            rec["address"] = d["address"]
            await self._publish_actor(rec)
        return True

    async def h_kill_actor(self, conn, d):
        actor_id = d["actor_id"]
        rec = self.actors.get(actor_id)
        if rec is None:
            return False
        no_restart = d.get("no_restart", True)
        if no_restart:
            rec["max_restarts"] = rec["num_restarts"]
        node_conn = self.node_conns.get(rec.get("node_id"))
        if node_conn is not None and rec["state"] == ALIVE:
            try:
                await node_conn.call("kill_actor_worker",
                                     {"worker_id": rec["worker_id"],
                                      "actor_id": actor_id})
            except Exception:
                pass
        if no_restart:
            rec["state"] = DEAD
            rec["death_cause"] = "killed via kill()"
            rec["address"] = ""
            await self._publish_actor(rec)
        return True

    async def h_report_worker_failure(self, conn, d):
        """Raylet reports a dead worker, listing actors it hosted."""
        for actor_id in d.get("actor_ids", []):
            rec = self.actors.get(actor_id)
            if rec is not None and rec["state"] in (ALIVE, RESTARTING):
                if d.get("intended", False):
                    rec["state"] = DEAD
                    rec["death_cause"] = "actor exited"
                    rec["address"] = ""
                    await self._publish_actor(rec)
                else:
                    await self._on_actor_interrupted(actor_id, "worker died")
        return True

    async def _try_schedule_pending_actors(self):
        queue, self._pending_actor_queue = self._pending_actor_queue, []
        for actor_id in queue:
            if self.actors.get(actor_id, {}).get("state") != DEAD:
                await self._schedule_actor(actor_id)

    # ---- profiling / metrics ----
    def _event(self, severity: str, label: str, message: str, **fields):
        """GCS-originated structured event: file + own ring."""
        from ray_tpu._private import events

        self.events.append(
            events.report_event(severity, label, message, **fields))

    async def h_report_event(self, conn, d):
        self.events.append(d)
        return True

    async def h_get_events(self, conn, d):
        out = list(self.events)
        sev = d.get("severity")
        if sev:
            out = [e for e in out if e.get("severity") == sev]
        limit = d.get("limit")
        limit = 1000 if limit is None else int(limit)
        if limit <= 0:
            return []
        return out[-limit:]

    async def h_add_profile_events(self, conn, d):
        if _fp.ARMED:
            # trace-table apply seam: `raise` models a failed table
            # write — the batch is dropped HERE (counted, typed log)
            # while the sender's requeue path stays untouched
            try:
                await _fp.fire_async_strict("gcs.trace_table.apply")
            except _fp.FailpointError:
                M_TRACE_APPLY_FAILURES.inc()
                logger.warning("trace table apply failed (failpoint); "
                               "dropping batch of %d events",
                               len(d.get("events", ())))
                return False
        self.profile_events.append({
            "component_type": d["component_type"],
            "component_id": d["component_id"],
            "node_id": d.get("node_id"),
            "events": d["events"],
        })
        # index trace spans (events carrying a trace id) into the flat
        # trace table so get_trace_spans can filter by trace
        for ev in d["events"]:
            extra = ev.get("extra_data") or {}
            if "tid" in extra:
                self.trace_spans.append({
                    "component_type": d["component_type"],
                    "component_id": d["component_id"],
                    "node_id": d.get("node_id"),
                    "event_type": ev["event_type"],
                    "start_time": ev["start_time"],
                    "end_time": ev["end_time"],
                    "extra_data": extra,
                })
        return True

    async def h_get_profile_events(self, conn, d):
        return list(self.profile_events)

    async def h_get_trace_spans(self, conn, d):
        """Flat span rows from the trace table, optionally filtered to
        one trace (hex trace id)."""
        tid = d.get("trace_id")
        if isinstance(tid, bytes):
            tid = tid.decode()
        out = list(self.trace_spans)
        if tid:
            out = [s for s in out if s["extra_data"].get("tid") == tid]
        return out

    async def h_add_profile_samples(self, conn, d):
        """One collapsed-stack sample batch from any process's sampler
        (sampling_profiler.py) into the bounded profile ring."""
        if _fp.ARMED:
            # same seam class as the trace table: `raise` models a
            # failed ring apply — batch dropped here, typed; the
            # sender's bounded merge-back path stays untouched
            try:
                await _fp.fire_async_strict("gcs.profile_ring.apply")
            except _fp.FailpointError:
                M_TRACE_APPLY_FAILURES.inc()
                logger.warning("profile ring apply failed (failpoint); "
                               "dropping batch of %d stacks",
                               len(d.get("stacks", ())))
                return False
        if d.get("stacks"):
            self.profile_samples.append({
                k: d.get(k) for k in (
                    "component_type", "component_id", "node_id",
                    "t_start", "t_end", "hz", "samples", "stacks")})
        return True

    async def h_get_profile_samples(self, conn, d):
        """Profile-ring read: optionally filtered to one component class
        and/or to batches whose window ended at/after `since`."""
        component = d.get("component")
        since = d.get("since")
        out = []
        for b in self.profile_samples:
            if component and b.get("component_type") != component:
                continue
            if since is not None and (b.get("t_end") or 0) < float(since):
                continue
            out.append(b)
        return out

    def _ingest_own_profile(self):
        """The director IS the ring: its own sampler batches ingest
        directly (no RPC), on the heartbeat-checker cadence."""
        batch = _sprof.drain_batch("gcs")
        if batch is not None:
            self.profile_samples.append(batch)

    async def _drain_shard_profiles(self):
        """Pull the store shards' sampler windows into the ring (shards
        don't dial the director; the director polls them on the same
        cadence that mirrors flow). Each call acks the previously
        ingested window's t_end — a timed-out reply makes the shard
        merge that window back instead of losing it."""
        for idx in range(len(self.shard_addresses)):
            try:
                conn = await self._shard_conn(idx)
                batch = await asyncio.wait_for(
                    conn.call("drain_profile_samples",
                              {"ack": self._shard_profile_acks.get(idx)}),
                    timeout=2.0)
                if batch and batch.get("stacks"):
                    self.profile_samples.append(batch)
                    self._shard_profile_acks[idx] = batch.get("t_end")
            except Exception:
                pass  # delayed, not lost: the shard re-merges unacked

    async def _profile_ingest_loop(self):
        """~2s profile cadence for the control plane itself: fold the
        director's own sampler window (and the shards') into the ring."""
        while True:
            await asyncio.sleep(2.0)
            try:
                self._ingest_own_profile()
                if self.shard_addresses:
                    await self._drain_shard_profiles()
            except Exception:  # pragma: no cover - must never die
                logger.exception("profile ingest tick failed")

    def _ingest_metrics(self, source: str, snap: dict):
        """One timestamped sample per metric into the per-source ring.
        Histograms flatten to scalar series (.count/.sum/.p99) so the
        serving tier's autoscaler can read router p99 over time without
        re-deriving bucket math."""
        import collections as _collections

        ts = time.time()
        rings = self.metrics_history.setdefault(source, {})

        def put(name, value):
            ring = rings.get(name)
            if ring is None:
                ring = rings[name] = _collections.deque(
                    maxlen=self.metrics_history_samples)
            ring.append([ts, float(value)])

        for name, m in snap.items():
            try:
                kind = m.get("type")
                if kind == "histogram":
                    put(name + ".count", m.get("count", 0))
                    put(name + ".sum", m.get("sum", 0.0))
                    p99, saturated = _stats.percentile(
                        m, 0.99, with_saturation=True)
                    put(name + ".p99", p99)
                    # saturation is explicit, not inferred: a p99 AT the
                    # top boundary means "at least this" only when the
                    # quantile actually landed in the overflow bucket
                    put(name + ".p99_saturated", 1.0 if saturated else 0.0)
                    overflow = _stats.overflow_count(m)
                    if overflow:
                        put(name + ".overflow", overflow)
                    ex = _stats.quantile_exemplar(m, 0.99)
                    if ex is not None:
                        # exemplars are strings; they ride a side table
                        # beside the scalar rings, newest wins
                        self.metrics_exemplars.setdefault(
                            source, {})[name] = ex
                else:
                    put(name, m.get("value", 0.0))
            except (TypeError, ValueError, AttributeError):
                continue  # one malformed metric must not drop the batch
        self.metrics_last_push[source] = ts
        # Worker/driver sources are keyed per pid and churn with jobs;
        # nothing else removes a dead process's rings. Evict sources
        # idle past a full retention window (~2s cadence * ring length)
        # so the history stays bounded by live pushers, not by every
        # process that ever pushed.
        cutoff = ts - 2.0 * self.metrics_history_samples
        for stale in [s for s, t in self.metrics_last_push.items()
                      if t < cutoff]:
            self.metrics_history.pop(stale, None)
            self.metrics_last_push.pop(stale, None)
            self.metrics_exemplars.pop(stale, None)

    async def h_push_metrics(self, conn, d):
        """Metric sample push from a worker/driver process (raylets ride
        the heartbeat piggyback instead)."""
        source = d.get("source") or "?"
        self._ingest_metrics(source, d.get("metrics") or {})
        return True

    async def h_get_metrics_history(self, conn, d):
        samples = int(d.get("samples") or 0)
        out = {}
        for source, rings in self.metrics_history.items():
            out[source] = {
                name: list(ring)[-samples:] if samples > 0 else list(ring)
                for name, ring in rings.items()}
        if d.get("meta"):
            # history-epoch envelope (opt-in, shape-preserving for old
            # callers): started_at changing between two reads means the
            # director restarted and the rings reset — the documented
            # lossy-restart contract `ray-tpu top` renders as a marker
            return {"meta": {"started_at": self.started_at,
                             "retention_samples":
                                 self.metrics_history_samples},
                    # p99 exemplars: the trace id behind each histogram's
                    # current tail (`ray-tpu top` prints it; `ray-tpu
                    # trace --trace-id` resolves it to the span tree)
                    "exemplars": {s: dict(ex) for s, ex in
                                  self.metrics_exemplars.items()},
                    "series": out}
        return out

    async def h_debug_state(self, conn, d):
        """Director live state: membership + heartbeat ages, actor/pg/
        job table sizes, pubsub fan-out, observability-ring occupancy,
        shard tier state (each live shard's own debug_state embedded,
        bounded wait)."""
        t_start = time.monotonic()
        mono = time.monotonic()
        nodes = []
        for node_id, info in list(self.nodes.items()):
            last = self.last_heartbeat.get(node_id)
            conn_n = self.node_conns.get(node_id)
            nodes.append({
                "node_id": node_id.hex()[:8],
                "address": info.get("address", ""),
                "state": info.get("state", ""),
                "is_head": bool(info.get("is_head")),
                "heartbeat_age_s": (round(mono - last, 3)
                                    if last is not None else None),
                "conn_live": bool(conn_n is not None
                                  and not conn_n.closed),
            })
        actor_states: dict[str, int] = {}
        for rec in self.actors.values():
            actor_states[rec["state"]] = (
                actor_states.get(rec["state"], 0) + 1)
        snap = {
            "role": "gcs",
            "started_at": self.started_at,
            "nodes_table": nodes,
            "actors_by_state": actor_states,
            "pending_actor_queue": len(self._pending_actor_queue),
            "placement_groups": {
                "total": len(self.placement_groups),
                "pending": sum(1 for r in self.placement_groups.values()
                               if r["state"] in ("PENDING", "INFEASIBLE"))},
            # per-pg bundle->node rows with topology coords (`ray-tpu
            # state placement`; the doctor's topology_mismatch check),
            # bounded like the other introspection surfaces
            "placement_table": self._placement_table(limit=200),
            "jobs": len(self.jobs),
            "kv_keys": len(self.kv),
            "object_locations": len(self.object_locations),
            "pubsub": {ch: len(subs)
                       for ch, subs in list(self.subscriptions.items())
                       if subs},
            "rings": {"events": len(self.events),
                      "profile_events": len(self.profile_events),
                      "trace_spans": len(self.trace_spans),
                      "profile_samples": len(self.profile_samples),
                      "metrics_sources": len(self.metrics_history)},
            "rpc": {"server_conns": len(self.server.connections)},
        }
        if self.shard_addresses:
            async def one(idx):
                try:
                    c = await self._shard_conn(idx)
                    return await asyncio.wait_for(
                        c.call("debug_state", {}), timeout=2.0)
                except Exception as e:
                    return {"error": f"{type(e).__name__}: {e}",
                            "address": self.shard_addresses[idx]}

            snap["shards"] = list(await asyncio.gather(
                *(one(i) for i in range(len(self.shard_addresses)))))
        return _debug.finish_snapshot(snap, t_start)

    def _placement_table(self, limit: int = 200) -> list[dict]:
        """Flat bundle->node rows for every placement group: strategy,
        cost-model name, per-bundle node + topology coord + slice —
        what `ray-tpu state placement` prints and the doctor's
        topology_mismatch finding scans."""
        rows = []
        for rec in list(self.placement_groups.values())[:limit]:
            plan = rec.get("topology_plan") or {}
            base = {
                "pg": rec["pg_id"].hex()[:12],
                "name": rec.get("name", ""),
                "strategy": rec["strategy"],
                "cost_model": (plan.get("cost_model")
                               or rec.get("cost_model") or ""),
                "state": rec["state"],
            }
            if plan:
                base["ring_circumference"] = plan.get("ring_circumference")
            if rec.get("detail"):
                base["detail"] = rec["detail"]
            if rec["state"] != "CREATED":
                rows.append(base)
                continue
            for b in rec["bundles"]:
                topo = b.get("topology") or {}
                nid = b.get("node_id")
                rows.append({
                    **base,
                    "bundle": b.get("bundle_index"),
                    "node": nid.hex()[:8] if isinstance(nid, bytes)
                    else str(nid),
                    "slice": topo.get("slice_id") or "",
                    "coords": ",".join(str(c) for c in
                                       topo.get("coords") or ()) or "",
                })
        return rows

    async def h_get_metrics(self, conn, d):
        """This process's metric registry + computed cluster gauges."""
        from ray_tpu._private import stats

        snap = stats.snapshot()
        snap["gcs.nodes_alive"] = {
            "type": "gauge",
            "value": sum(1 for n in self.nodes.values()
                         if n.get("state") == "ALIVE")}
        snap["gcs.nodes_draining"] = {
            "type": "gauge",
            "value": sum(1 for n in self.nodes.values()
                         if n.get("state") == "DRAINING")}
        snap["gcs.actors_alive"] = {
            "type": "gauge",
            "value": sum(1 for r in self.actors.values()
                         if r["state"] == ALIVE)}
        snap["gcs.placement_groups"] = {
            "type": "gauge", "value": len(self.placement_groups)}
        return snap

    # ---- object directory ----
    async def h_add_object_location(self, conn, d):
        rec = self.object_locations.setdefault(
            d["object_id"], {"nodes": set(), "size": 0})
        rec["nodes"].add(d["node_id"])
        if d.get("size"):
            rec["size"] = int(d["size"])
        return True

    async def h_remove_object_location(self, conn, d):
        rec = self.object_locations.get(d["object_id"])
        if rec:
            rec["nodes"].discard(d["node_id"])
            if not rec["nodes"]:
                del self.object_locations[d["object_id"]]
        return True

    async def h_get_object_locations(self, conn, d):
        rec = self.object_locations.get(d["object_id"])
        return list(rec["nodes"]) if rec else []

    async def h_get_object_locations_batch(self, conn, d):
        """Locations + sizes for a set of objects in one round trip —
        feeds the raylets' locality-aware lease targeting (arg-byte
        weighting) and multi-source pull planning."""
        out = {}
        for oid in d["object_ids"]:
            rec = self.object_locations.get(oid)
            if rec:
                out[oid] = {"nodes": list(rec["nodes"]),
                            "size": rec["size"]}
        return out

    # ---- placement groups ----
    async def h_create_placement_group(self, conn, d):
        """2-phase bundle reservation across raylets (reference:
        gcs_placement_group_scheduler.h:49; strategies :133-160). Infeasible
        groups stay PENDING and are retried as nodes join / resources free
        (STRICT_SPREAD wanting more nodes than the fleet HAS goes
        INFEASIBLE instead — typed at the client — until nodes join)."""
        pg_id = d["pg_id"]
        # unknown cost-model specs fail HERE, typed at creation — never
        # as a silently-heuristic placement
        _topo.resolve_cost_model(d.get("cost_model"))
        # Idempotent: a call replayed across a GCS restart (lost reply)
        # must not reset a CREATED group to PENDING and double-reserve
        # its bundles.
        if pg_id not in self.placement_groups:
            self.placement_groups[pg_id] = {
                "pg_id": pg_id, "bundles": [dict(b) for b in d["bundles"]],
                "strategy": d.get("strategy", "PACK"), "state": "PENDING",
                "name": d.get("name", ""),
                "cost_model": d.get("cost_model") or "",
            }
            self._persist_pg(self.placement_groups[pg_id])
            await self._mirror("pgs", pg_id,
                               _pg_public(self.placement_groups[pg_id]))
        return {"state": await self._try_create_pg(pg_id)}

    async def _retry_pending_pgs(self):
        for pg_id, rec in list(self.placement_groups.items()):
            # INFEASIBLE retries too: a joining node can make a
            # too-wide STRICT_SPREAD placeable again
            if rec["state"] in ("PENDING", "INFEASIBLE"):
                await self._try_create_pg(pg_id)

    async def _try_create_pg(self, pg_id) -> str:
        rec = self.placement_groups.get(pg_id)
        if rec is None:
            return "REMOVED"
        if rec["state"] == "CREATED":
            return "CREATED"
        # INFEASIBLE records re-evaluate in place (the state only moves
        # once the outcome actually changes — _do_create_pg flips it
        # back to PENDING or on to CREATED; flipping it here would
        # re-persist + republish an unchanged record every retry sweep)
        # In-flight guard: while one 2PC attempt awaits raylet RPCs, a
        # concurrent retry (heartbeat/node-join) must not start a second
        # one — double prepare_bundle would double-reserve node resources.
        if rec.get("creating"):
            return "PENDING"
        rec["creating"] = True
        try:
            return await self._do_create_pg(pg_id, rec)
        finally:
            rec["creating"] = False

    async def _do_create_pg(self, pg_id, rec) -> str:
        bundles = rec["bundles"]
        strategy = rec["strategy"]
        t_score = time.perf_counter()
        try:
            placement = self._place_bundles(bundles, strategy,
                                            cost_model=rec.get("cost_model"))
        finally:
            M_PLACEMENT_SCORE_S.observe(time.perf_counter() - t_score)
        plan = self._last_topology_plan
        if placement is None:
            alive = sum(1 for n in self.node_conns.values()
                        if n is not None and not n.closed)
            if strategy == "STRICT_SPREAD" and len(bundles) > alive:
                # the fleet CANNOT hold this group today: surface typed
                # (PlacementGroupInfeasibleError at ready()) instead of
                # an indistinguishable forever-PENDING; node joins flip
                # it back to PENDING and retry
                detail = (f"{len(bundles)} STRICT_SPREAD bundles "
                          f"need distinct nodes; fleet has {alive}")
                if (rec["state"] == "INFEASIBLE"
                        and rec.get("detail") == detail):
                    # unchanged verdict: no persist/mirror/publish churn
                    # on every heartbeat-driven retry sweep
                    return "INFEASIBLE"
                rec["state"] = "INFEASIBLE"
                rec["detail"] = detail
                self._persist_pg(rec)
                await self._mirror("pgs", pg_id, _pg_public(rec))
                await self.publish(f"pg:{pg_id.hex()}", _pg_public(rec))
                return "INFEASIBLE"
            if rec["state"] == "INFEASIBLE":
                # structurally placeable again (a node joined) but not
                # yet reserved: back to PENDING so ready() stops raising
                rec["state"] = "PENDING"
                rec.pop("detail", None)
                self._persist_pg(rec)
                await self._mirror("pgs", pg_id, _pg_public(rec))
                await self.publish(f"pg:{pg_id.hex()}", _pg_public(rec))
            return "PENDING"
        if _fp.ARMED:
            # reserve seam, BETWEEN scoring and the 2PC prepare: `delay`
            # widens the window a scored node can die in (the chaos
            # case); `raise` aborts this attempt — the group stays
            # PENDING and the heartbeat-driven retry re-scores
            try:
                await _fp.fire_async_strict("placement.reserve")
            except _fp.FailpointError:
                logger.warning("placement.reserve failpoint aborted the "
                               "2PC for pg %s; will retry",
                               pg_id.hex()[:8])
                return "PENDING"
        # prepare
        prepared = []
        ok = True
        for idx, node_id in placement.items():
            conn_n = self.node_conns.get(node_id)
            try:
                res = await conn_n.call("prepare_bundle", {
                    "pg_id": pg_id, "bundle_index": idx,
                    "resources": bundles[idx]["resources"],
                })
                if not res:
                    ok = False
                    break
                prepared.append((idx, node_id))
            except Exception:
                ok = False
                break
        if not ok:
            for idx, node_id in prepared:
                conn_n = self.node_conns.get(node_id)
                if conn_n:
                    try:
                        await conn_n.call("cancel_bundle",
                                          {"pg_id": pg_id, "bundle_index": idx})
                    except Exception:
                        pass
            return "PENDING"
        # commit
        committed = []
        for idx, node_id in placement.items():
            conn_n = self.node_conns.get(node_id)
            try:
                if conn_n is None or conn_n.closed:
                    raise ConnectionError("node connection lost")
                await conn_n.call("commit_bundle",
                                  {"pg_id": pg_id, "bundle_index": idx})
                committed.append(idx)
            except Exception:
                # A node died between prepare and commit: unwind everything
                # (committed bundles returned, prepared ones cancelled) and
                # stay PENDING for the next retry.
                for jdx, jnode in placement.items():
                    conn_j = self.node_conns.get(jnode)
                    if conn_j is None or conn_j.closed:
                        continue
                    method = ("return_bundle" if jdx in committed
                              else "cancel_bundle")
                    try:
                        await conn_j.call(method, {"pg_id": pg_id,
                                                   "bundle_index": jdx})
                    except Exception:
                        pass
                return "PENDING"
        if self.placement_groups.get(pg_id) is not rec:
            # Removed while the 2PC was in flight: give the bundles back.
            for idx, node_id in placement.items():
                conn_n = self.node_conns.get(node_id)
                if conn_n is not None and not conn_n.closed:
                    try:
                        await conn_n.call("return_bundle", {
                            "pg_id": pg_id, "bundle_index": idx})
                    except Exception:
                        pass
            return "REMOVED"
        rec["state"] = "CREATED"
        rec.pop("detail", None)
        rec["bundles"] = [
            {"bundle_index": i, "resources": bundles[i]["resources"],
             "node_id": placement[i],
             # the assigned node's torus coord rides each bundle row —
             # `ray-tpu state placement`, the doctor's topology_mismatch
             # check, and transport derivation all read it
             "topology": self.nodes.get(placement[i], {}).get("topology")}
            for i in range(len(bundles))
        ]
        if plan is not None:
            # ICI_RING placed by topology: the plan gates client-side
            # transport derivation (topology.transport_plan) — a PACK
            # fallback carries none, so ad-hoc gangs keep probing
            rec["topology_plan"] = plan
        self._persist_pg(rec)
        # mirror-then-publish (same ordering rule as actors), then wake
        # PlacementGroup.ready() waiters parked on the pg channel — the
        # payload carries the full record so waiters don't even need the
        # read-back
        await self._mirror("pgs", pg_id, _pg_public(rec))
        await self.publish(f"pg:{pg_id.hex()}", _pg_public(rec))
        return "CREATED"

    def _nodes_by_slice(self, node_ids):
        """Group nodes by TPU slice_id (ICI domain). Nodes without a
        slice descriptor are excluded."""
        slices: dict[str, list] = {}
        for nid in node_ids:
            desc = self.nodes.get(nid, {}).get("tpu_slice")
            if desc and desc.get("slice_id"):
                slices.setdefault(desc["slice_id"], []).append(nid)
        return slices

    def _place_ici_ring(self, bundles, needs, avail, cost_model: str):
        """ICI_RING core: enumerate candidate bundle->node assignments
        over the snake order of coord-bearing nodes, score each with the
        request's cost model, take the cheapest that fits.

        Candidates per snake offset: a greedy FILL (consecutive ranks
        pack onto each node while it fits, then advance — one free node
        big enough yields the all-on-one-host/shm assignment) and a
        STRIDED spread (ranks spaced across the torus). The fill family
        contains the minimal rings the default model wants; the strided
        family gives an inverted/learned model genuinely different
        geometry to prefer. Returns placement dict or None (no located
        candidates / nothing fits / scoring seam failed)."""
        if self._topo_cache is None:
            cached: dict[bytes, _topo.TopologyCoord] = {}
            for nid, info in self.nodes.items():
                c = _topo.TopologyCoord.from_dict(info.get("topology"))
                if c is not None:
                    cached[nid] = c
            self._topo_cache = (cached, sorted(
                cached, key=lambda n: (cached[n].slice_id,
                                       _topo.snake_key(cached[n]))))
        coords, snake = self._topo_cache
        # liveness/availability filter is per-decision (conn state moves
        # without a membership event); the snake sort is not
        live = [nid for nid in snake
                if nid in avail
                and (cn := self.node_conns.get(nid)) is not None
                and not cn.closed]
        if not live:
            return None
        if _fp.ARMED:
            # scoring seam: `raise` models a failed topology read —
            # placement degrades to the counted PACK fallback; `delay`
            # stretches the scoring window the latency gate watches
            try:
                _fp.fire_strict("placement.topology_score")
            except _fp.FailpointError:
                logger.warning("placement.topology_score failpoint: "
                               "falling back to PACK")
                return None
        try:
            model = _topo.resolve_cost_model(cost_model)
        except ValueError:
            # model vanished since creation (process restart without the
            # registering import): heuristic fallback is counted, not
            # silent
            logger.warning("cost model %r unresolvable at scoring time; "
                           "falling back to PACK", cost_model)
            return None
        bind = getattr(model, "bind_context", None)
        if bind is not None:
            bind({"metrics_history": self.metrics_history,
                  # node-id prefix -> registered coord host_id, so a
                  # model keying on metric sources (<node8>/raylet) can
                  # reach coords whose host_id isn't the node-id hex
                  "node_hosts": {nid.hex()[:8]: c.host_id
                                 for nid, c in coords.items()}})
        order = live
        k = len(needs)
        n = len(order)
        # Fast path for the overwhelmingly common gang shape — every
        # bundle identical: one integer pass over the raw fixed-point
        # dicts computes how many bundle-slots each node fits, and
        # candidate generation becomes index walking (no ResourceSet
        # churn inside the offset loop). This is what keeps the scoring
        # A/B within the PACK arm's latency bucket.
        need_raw = needs[0].raw()
        uniform = all(nd.raw() == need_raw for nd in needs[1:])
        caps: dict[bytes, int] = {}
        if uniform:
            for nid in order:
                araw = avail[nid].raw()
                c = k
                for res, q in need_raw.items():
                    if q > 0:
                        c = min(c, araw.get(res, 0) // q)
                caps[nid] = c

        def fits(assignment) -> bool:
            if uniform:
                used: dict[bytes, int] = {}
                for nid in assignment:
                    used[nid] = used.get(nid, 0) + 1
                    if used[nid] > caps[nid]:
                        return False
                return True
            trial: dict[bytes, ResourceSet] = {}
            for i, nid in enumerate(assignment):
                rs = trial.get(nid)
                if rs is None:
                    rs = trial[nid] = avail[nid].copy()
                if not needs[i].is_subset_of(rs):
                    return False
                rs.subtract(needs[i])
            return True

        def fill_from(offset: int) -> list[bytes] | None:
            """Greedy walk from snake position `offset`: consecutive
            ranks pack onto each node while it fits, then advance."""
            out: list[bytes] = []
            if uniform:
                pos = offset
                while len(out) < k and pos < offset + n:
                    nid = order[pos % n]
                    take = min(caps[nid], k - len(out))
                    out.extend([nid] * take)
                    pos += 1
                return out if len(out) == k else None
            rs = None
            pos = offset
            for i in range(k):
                while pos < offset + n:
                    nid = order[pos % n]
                    if rs is None:
                        rs = avail[nid].copy()
                    if needs[i].is_subset_of(rs):
                        rs.subtract(needs[i])
                        out.append(nid)
                        break
                    pos += 1
                    rs = None
                else:
                    return None
            return out

        # Generate-and-score incrementally, fill candidates first: the
        # default model's minimum for a distinct-node ring is k (every
        # wire hop >= 1), so once a perfect ring scores <= k — and no
        # node could host two ranks (caps <= 1 => no 0-hop same-host
        # shortcuts exist) — stop scanning. Pluggable models see every
        # candidate.
        ring_default = isinstance(model, _topo.RingDistanceCostModel)
        can_pack = (not uniform) or any(c > 1 for c in caps.values())
        seen: set[tuple] = set()
        best, best_cost = None, None
        stride = max(1, n // k)

        def consider(cand) -> bool:
            """Score one candidate; True = stop scanning (provably
            optimal for the default model)."""
            nonlocal best, best_cost
            key = tuple(cand)
            if key in seen:
                return False
            seen.add(key)
            cost = model.score(bundles, [coords[nid] for nid in cand])
            if best_cost is None or cost < best_cost:
                best, best_cost = cand, cost
            return ring_default and not can_pack and best_cost <= k

        done = False
        for offset in range(n):
            if uniform and caps[order[offset]] == 0:
                continue  # identical fill to the next live offset
            filled = fill_from(offset)
            if filled is not None and consider(filled):
                done = True
                break
        if not done and stride > 1:
            for offset in range(n):
                strided = [order[(offset + j * stride) % n]
                           for j in range(k)]
                if fits(strided) and consider(strided):
                    break
        if best is None:
            return None
        for i, nid in enumerate(best):
            avail[nid].subtract(needs[i])
        ring = [coords[nid] for nid in best]
        # Torus holes this plan routed around: coord-bearing nodes that
        # are DRAINING (still registered, masked out of avail) plus
        # recently-departed coords — the placement record shows exactly
        # which coords the snake re-sort skipped.
        now = time.time()
        masked = [dict(self.nodes[nid].get("topology") or {})
                  for nid in snake
                  if self.nodes.get(nid, {}).get("state")
                  not in (None, "ALIVE")]
        masked.extend(dict(t) for ts, t in self._departed_coords.values()
                      if now - ts <= _DEPARTED_COORD_TTL_S)
        self._last_topology_plan = {
            "cost_model": getattr(model, "name", "") or cost_model or "ring",
            "cost": float(best_cost),
            "ring_circumference": _topo.ring_circumference(ring),
            "candidates_scored": len(seen),
            # the (data, fsdp) factorization FSDP-mode meshes derive
            # from this gang (SNIPPETS [2] table; parallel/mesh.py)
            "mesh_shape": list(_topo.mesh_shape_for(k)),
        }
        if masked:
            self._last_topology_plan["masked_coords"] = masked
            M_RING_REPLACEMENTS.inc()
        return {i: nid for i, nid in enumerate(best)}

    def _place_bundles(self, bundles, strategy, cost_model: str = ""):
        """Map bundle_index -> node_id, or None if infeasible now.

        TPU topology (SURVEY §7 step 1; reference strategy analog:
        gcs_placement_group_scheduler.h:133-160): STRICT_PACK means "one
        ICI domain" — a single node, or, for TPU bundles, the hosts of
        ONE slice (equal slice_id ⇔ ICI-connected; never spans slices).
        STRICT_SPREAD prefers distinct hosts of one slice before falling
        back to arbitrary distinct nodes, so a dp group's gradient
        allreduce rides ICI when a big-enough slice exists.

        ICI_RING orders candidate nodes so CONSECUTIVE bundle ranks are
        ICI neighbors (minimal ring circumference over the torus),
        scored by the request's pluggable cost model; with no
        coord-bearing candidates it falls back to PACK, counted by
        `gcs.placement_topology_fallbacks_total`. Sets
        `self._last_topology_plan` (ICI_RING success only) so
        _do_create_pg can stamp the record without re-deriving."""
        self._last_topology_plan = None
        # DRAINING nodes are masked out of every strategy's candidate
        # set: a group placed now must survive the node's departure
        avail = {nid: r.copy() for nid, r in self.available.items()
                 if self.nodes.get(nid, {}).get("state") == "ALIVE"}
        placement: dict[int, bytes] = {}
        node_ids = list(avail.keys())
        if not node_ids:
            return None

        def fits(node_id, res: ResourceSet):
            return res.is_subset_of(avail[node_id])

        def take(node_id, res: ResourceSet):
            avail[node_id].subtract(res)

        needs = [ResourceSet.from_raw(b["resources"]) for b in bundles]
        wants_tpu = any(n.get("TPU") > 0 for n in needs)

        if strategy == "ICI_RING":
            local = self._place_ici_ring(bundles, needs, avail, cost_model)
            if local is not None:
                return local
            # no topology to score (or the scoring seam failed): behave
            # exactly like PACK, but count the downgrade only when the
            # gang actually PLACES topology-blind — a merely
            # capacity-starved fleet stays PENDING and re-enters
            # ICI_RING scoring on the next availability change, which
            # must not ring the fallback alarm once per retry heartbeat
            placed = self._place_bundles(bundles, "PACK", cost_model)
            if placed is not None:
                M_TOPO_FALLBACKS.inc()
            return placed

        def pack_within(cand_ids):
            """Fit all bundles onto `cand_ids`, placing the LARGEST need
            first onto the emptiest node (first-fit-decreasing — a
            smaller bundle grabbing the big node can't strand a larger
            one); returns placement dict or None. Mutates avail."""
            local: dict[int, bytes] = {}
            order = sorted(range(len(needs)),
                           key=lambda i: -needs[i].get("TPU"))
            for i in order:
                need = needs[i]
                cs = [n for n in cand_ids if fits(n, need)]
                if not cs:
                    return None
                node = max(cs, key=lambda n: avail[n].get("TPU"))
                take(node, need)
                local[i] = node
            return local

        if strategy in ("PACK", "STRICT_PACK"):
            # try to fit all on one node first
            for node_id in sorted(node_ids,
                                  key=lambda n: -avail[n].get("CPU")):
                trial = avail[node_id].copy()
                ok = True
                for n in needs:
                    if not n.is_subset_of(trial):
                        ok = False
                        break
                    trial.subtract(n)
                if ok:
                    for i in range(len(bundles)):
                        placement[i] = node_id
                    return placement
            if strategy == "STRICT_PACK":
                if not wants_tpu:
                    return None
                # one ICI domain: all bundles within a single slice
                for slice_id, members in sorted(
                        self._nodes_by_slice(node_ids).items(),
                        key=lambda kv: -sum(avail[n].get("TPU")
                                            for n in kv[1])):
                    saved = {n: avail[n].copy() for n in members}
                    local = pack_within(members)
                    if local is not None:
                        return local
                    avail.update(saved)
                return None
            # PACK falls back to spread-fit
        if strategy == "STRICT_SPREAD":
            if len(bundles) > len(node_ids):
                return None
            if wants_tpu:
                # prefer distinct hosts of ONE slice (ICI for the group)
                for slice_id, members in sorted(
                        self._nodes_by_slice(node_ids).items(),
                        key=lambda kv: -len(kv[1])):
                    if len(members) < len(bundles):
                        continue
                    saved = {n: avail[n].copy() for n in members}
                    used: set[bytes] = set()
                    local: dict[int, bytes] = {}
                    for i, need in enumerate(needs):
                        cs = [n for n in members
                              if n not in used and fits(n, need)]
                        if not cs:
                            local = None
                            break
                        node = random.choice(cs)
                        used.add(node)
                        take(node, need)
                        local[i] = node
                    if local is not None:
                        return local
                    avail.update(saved)
            used = set()
            for i, need in enumerate(needs):
                cands = [n for n in node_ids if n not in used and fits(n, need)]
                if not cands:
                    return None
                node = random.choice(cands)
                used.add(node)
                take(node, need)
                placement[i] = node
            return placement
        # PACK fallback / SPREAD: round-robin best-fit
        order = node_ids if strategy != "SPREAD" else random.sample(
            node_ids, len(node_ids))
        for i, need in enumerate(needs):
            cands = [n for n in order if fits(n, need)]
            if not cands:
                return None
            if strategy == "SPREAD":
                node = min(cands, key=lambda n: sum(
                    1 for j, p in placement.items() if p == n))
            else:
                node = cands[0]
            take(node, need)
            placement[i] = node
        return placement

    async def h_remove_placement_group(self, conn, d):
        self._persist_del("placement_groups", d["pg_id"])
        rec = self.placement_groups.pop(d["pg_id"], None)
        if rec is not None:
            await self._mirror("pgs", d["pg_id"], None)
            await self.publish(f"pg:{d['pg_id'].hex()}",
                               {"pg_id": d["pg_id"], "state": "REMOVED"})
        if rec and rec["state"] == "CREATED":
            for b in rec["bundles"]:
                conn_n = self.node_conns.get(b["node_id"])
                if conn_n is not None and not conn_n.closed:
                    try:
                        await conn_n.call("return_bundle", {
                            "pg_id": d["pg_id"],
                            "bundle_index": b["bundle_index"]})
                    except Exception:
                        pass
        return rec is not None

    async def h_get_placement_group(self, conn, d):
        return self.placement_groups.get(d["pg_id"])

    async def h_get_named_placement_group(self, conn, d):
        for rec in self.placement_groups.values():
            if rec.get("name") and rec["name"] == d["name"]:
                return rec
        return None

    async def h_list_placement_groups(self, conn, d):
        return list(self.placement_groups.values())

    # ---- lifecycle ----
    async def _on_disconnect(self, conn):
        for subs in self.subscriptions.values():
            subs.discard(conn)
        node_id = conn.context.get("node_id")
        if node_id is not None and node_id in self.nodes:
            # Keep the node until heartbeats actually time out? No: a closed
            # raylet connection means the process died — remove immediately.
            await self._remove_node(node_id, reason="raylet disconnected")

    async def _connect_shards(self):
        """Dial every store shard at startup and push an initial mirror
        resync (a director restarted against its persisted tables
        refreshes mirrors that may have gone stale while it was down;
        reconnects after a shard restart resync via on_reconnect)."""
        for idx in range(len(self.shard_addresses)):
            try:
                conn = await self._shard_conn(idx)
                await conn.ensure_connected()
                await self._resync_shard(idx, conn)
            except Exception:
                logger.warning("initial connect to shard %d failed "
                               "(will keep redialing)", idx)

    async def run(self, port: int, ready_file: str | None = None,
                  uds_dir: str | None = None):
        cfg = get_config()
        self._uds_dir = uds_dir
        _debug.start_loop_lag_monitor()
        actual = await self.server.start_tcp(host=cfg.bind_host, port=port,
                                             uds_dir=uds_dir)
        asyncio.create_task(self.heartbeat_checker())
        # continuous profiling: the director samples itself (a KV-armed
        # rate applied in _restore outranks the env default) and folds
        # its own + the shards' windows into the profile ring
        _sprof.start("gcs")
        asyncio.create_task(self._profile_ingest_loop())
        if self.shard_addresses:
            asyncio.create_task(self._connect_shards())
        logger.info("GCS listening on %s:%d (advertised %s)",
                    cfg.bind_host, actual, cfg.node_ip_address)
        if ready_file:
            tmp = ready_file + ".tmp"
            with open(tmp, "w") as f:
                f.write(str(actual))
            os.rename(tmp, ready_file)
        while True:
            await asyncio.sleep(3600)


def _node_public(info):
    return {k: info.get(k) for k in (
        "node_id", "address", "object_manager_address", "bulk_address",
        "resources", "hostname", "is_head", "state", "labels",
        "tpu_slice", "topology")}


def _pg_public(rec):
    return {k: v for k, v in rec.items() if k != "creating"}


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--ready-file", default=None)
    parser.add_argument("--log-file", default=None)
    parser.add_argument("--store-dir", default=None,
                        help="WAL+snapshot dir; enables persistence/restart")
    parser.add_argument("--shard-addresses", default="",
                        help="comma-separated store-shard addresses "
                             "(index order; empty = unsharded)")
    parser.add_argument("--uds-dir", default=None,
                        help="serve a sibling UDS listener here (same-node "
                             "clients skip the loopback-TCP tax)")
    args = parser.parse_args()
    from ray_tpu._private.log_utils import setup_process_logging

    setup_process_logging("gcs_server", args.log_file)
    _fp.set_role("gcs")
    from ray_tpu._private.events import init_events

    init_events("GCS", "gcs",
                os.path.dirname(args.log_file) if args.log_file else None)
    set_config(Config.load())
    storage = None
    if args.store_dir:
        from ray_tpu.gcs.storage import GcsStorage

        storage = GcsStorage(args.store_dir)
    shard_addresses = [a for a in args.shard_addresses.split(",") if a]
    server = GcsServer(get_config(), storage=storage,
                       shard_addresses=shard_addresses)
    asyncio.run(server.run(args.port, args.ready_file,
                           uds_dir=args.uds_dir))


if __name__ == "__main__":
    main()
