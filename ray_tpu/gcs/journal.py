"""Snapshot + append-only journal: the per-shard persistence engine.

Every GCS store shard (shard.py) — and, through GcsStorage (storage.py),
the director itself — persists its tables through one of these: an
append-only log of msgpack op frames plus a periodically rewritten
snapshot (reference: the Redis persistence behind gcs_table_storage.h:294,
collapsed to an in-process engine — no extra server, no network hop).

Recovery = load snapshot, replay the journal in order. A killed shard
therefore restores its exact table state in time bounded by
`compact_bytes` worth of ops (compaction truncates the journal), instead
of waiting for raylets to re-register state at their own cadence.

Frame format: `>I` length header + msgpack(record). Crash semantics,
proven by the PR-4 failpoint sweep and tests/test_gcs_storage.py:

- torn tail (crash mid-append): truncated on open, BEFORE new appends,
  so later valid records never sit behind garbage;
- corruption MID-file with valid (possibly fsynced) records after it:
  refuse to open — auto-truncating would silently destroy durable state;
- `append(sync=True)` fsyncs before returning (records whose loss would
  strand live processes); plain appends are flushed to the OS on every
  call — durable across a process kill, fsynced in batches by
  `maybe_sync` for machine-crash durability without a per-op fsync.

Failpoint seams: `gcs.journal.append` fires before the frame is written
(`raise` models a full disk / IO error with nothing written; `exit`
kills pre-write so the op is never acked), `gcs.journal.replay` fires
once at recovery start (`raise` models an unreadable journal).
"""

from __future__ import annotations

import os
import struct
import threading
import time

import msgpack

from ray_tpu._private import failpoints as _fp

_HDR = struct.Struct(">I")


class JournalCorruption(RuntimeError):
    """Journal bytes are damaged mid-file; refusing to auto-truncate."""


class Journal:
    """Single-writer snapshot + op journal under `dir_path`. Records are
    arbitrary msgpack-serializable values (bytes keys fine); the snapshot
    object is opaque to the engine. Thread-safe appends."""

    def __init__(self, dir_path: str, compact_bytes: int = 4 << 20,
                 journal_name: str = "journal.bin",
                 snapshot_name: str = "snapshot.bin",
                 sync_interval_s: float = 0.05):
        self.dir = dir_path
        self.compact_bytes = compact_bytes
        os.makedirs(dir_path, exist_ok=True)
        self._snap_path = os.path.join(dir_path, snapshot_name)
        self._journal_path = os.path.join(dir_path, journal_name)
        self._lock = threading.Lock()
        self._sync_interval = sync_interval_s
        self._last_sync = 0.0
        self._sync_thread: threading.Thread | None = None
        self._file = None  # opened by recover()

    # -- recovery ------------------------------------------------------

    def recover(self, apply_snapshot, apply_record) -> int:
        """Load the snapshot (if any) through `apply_snapshot(obj)`, then
        replay journal records in append order through
        `apply_record(rec)`. Truncates a torn tail, then opens the
        journal for appending. Returns the number of replayed records."""
        if _fp.ARMED:
            _fp.fire_strict("gcs.journal.replay")
        if os.path.exists(self._snap_path):
            with open(self._snap_path, "rb") as f:
                apply_snapshot(msgpack.unpackb(
                    f.read(), raw=False, strict_map_key=False))
        replayed = 0
        valid_end = None
        if os.path.exists(self._journal_path):
            with open(self._journal_path, "rb") as f:
                data = f.read()
            off = 0
            while off + _HDR.size <= len(data):
                (length,) = _HDR.unpack_from(data, off)
                end = off + _HDR.size + length
                if end > len(data):
                    valid_end = off  # torn tail from a crash mid-append
                    break
                try:
                    rec = msgpack.unpackb(data[off + _HDR.size:end],
                                          raw=False, strict_map_key=False)
                except Exception:
                    if end == len(data):
                        valid_end = off  # last frame garbled: tail crash
                        break
                    raise JournalCorruption(
                        f"journal corrupt at offset {off} with "
                        f"{len(data) - end} bytes after it; refusing to "
                        f"auto-truncate (inspect {self._journal_path})")
                apply_record(rec)
                replayed += 1
                off = end
            else:
                if off != len(data):
                    valid_end = off  # trailing partial header
        if valid_end is not None:
            # Cut the torn frame off BEFORE appending, or every later
            # (valid) record would sit behind the garbage and be
            # discarded on the next recovery.
            with open(self._journal_path, "ab") as f:
                f.truncate(valid_end)
        self._file = open(self._journal_path, "ab")
        return replayed

    # -- mutation ------------------------------------------------------

    def append(self, record, sync: bool = False) -> int:
        """Append one record; returns the journal size after the write.
        The frame is flushed to the OS before returning (survives a
        process kill); `sync=True` additionally fsyncs (survives a
        machine crash)."""
        if _fp.ARMED:
            _fp.fire_strict("gcs.journal.append")
        body = msgpack.packb(record, use_bin_type=True)
        with self._lock:
            f = self._file
            f.write(_HDR.pack(len(body)) + body)
            f.flush()
            if sync:
                os.fsync(f.fileno())
                self._last_sync = time.monotonic()
            return f.tell()

    def append_lazy(self, record) -> None:
        """Group-commit half 1: buffer the frame WITHOUT flushing. The
        record is NOT process-kill durable until flush() — callers must
        not ack until then (shard.py coalesces one flush() per event-
        loop batch, so N concurrent table ops cost one write syscall
        instead of N)."""
        if _fp.ARMED:
            _fp.fire_strict("gcs.journal.append")
        body = msgpack.packb(record, use_bin_type=True)
        with self._lock:
            self._file.write(_HDR.pack(len(body)) + body)

    def flush(self) -> None:
        """Group-commit half 2: push every buffered frame to the OS
        (process-kill durable)."""
        with self._lock:
            if self._file is not None:
                self._file.flush()

    def maybe_sync(self):
        """Group-commit fsync: called opportunistically (e.g. per handler
        batch); fsyncs at most every `sync_interval_s`, on a DAEMON
        THREAD. Inline fsync would write back every byte dirtied since
        the last one before returning (~25ms/MB on the gVisor gofer fs)
        and stall the serving event loop; acks only need the flush
        append() already did (process-kill durable) — the threaded fsync
        is the machine-crash backstop and must not block serving."""
        now = time.monotonic()
        if now - self._last_sync < self._sync_interval:
            return
        t = self._sync_thread
        if t is not None and t.is_alive():
            return
        self._last_sync = now
        self._sync_thread = threading.Thread(
            target=self._fsync_quiet, name="journal-fsync", daemon=True)
        self._sync_thread.start()

    def _fsync_quiet(self):
        f = self._file
        try:
            if f is not None:
                # concurrent append()s are fine (they land in the next
                # fsync); a concurrent compaction close raises ValueError
                os.fsync(f.fileno())
        except (OSError, ValueError):
            pass

    def size(self) -> int:
        with self._lock:
            return self._file.tell() if self._file else 0

    # -- compaction ----------------------------------------------------

    def maybe_compact(self, state_fn) -> bool:
        """Rewrite the snapshot from `state_fn()` and truncate the
        journal once it outgrows `compact_bytes`."""
        with self._lock:
            if self._file is None or self._file.tell() <= self.compact_bytes:
                return False
            self._compact_locked(state_fn())
            return True

    def compact(self, state):
        with self._lock:
            self._compact_locked(state)

    def _compact_locked(self, state):
        tmp = self._snap_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(msgpack.packb(state, use_bin_type=True))
            f.flush()
            os.fsync(f.fileno())
        os.rename(tmp, self._snap_path)
        self._file.close()
        self._file = open(self._journal_path, "wb")

    def close(self):
        with self._lock:
            if self._file is None:
                return
            try:
                self._file.flush()
                os.fsync(self._file.fileno())
                self._file.close()
            except Exception:
                pass
            self._file = None
