"""Sharded GCS client: deterministic key→shard routing in the caller.

Wraps the director connection every process already holds and fans the
key-partitioned table ops (KV, object directory, actor/pg reads) out to
the store shards (shard.py) directly — in steady state the director
never sees them, so control-plane throughput scales with shard count
instead of serializing through one event loop (Ray §4.1; ROADMAP 2).

Routing is `crc32(key) % nshards` (shard_for) — every process computes
the same owner for a key with no directory lookup on the op path. The
shard map (addresses, fixed across shard restarts) is fetched once from
the director (`get_shard_map`) and cached. `RAY_TPU_GCS_SHARDS=1` (the
default) yields an empty map and this wrapper passes everything through
to the director — today's single-process layout, byte-identical.

Director-owned ops keep their single home: membership + heartbeats,
scheduling, placement 2PC, pubsub (`subscribe`/`publish` and every push),
jobs, events/profile/trace/metrics tables, and the `ray_tpu:` control
keys (failpoint arming, trace sampling) whose writes must fan out on the
director's pubsub plane.

Shard connections are rpc.ReconnectingConnection — a shard restarted by
the node monitor (same port, journal replay) is transparently redialed
and idempotent ops retried, exactly like the director today.
"""

from __future__ import annotations

import asyncio
import zlib

from ray_tpu._private import rpc

# Director-owned control keys (failpoints, trace sampling): their kv_put
# must run WHERE the pubsub plane lives.
CONTROL_KEY_PREFIX = "ray_tpu:"


def shard_for(key, nshards: int) -> int:
    """Deterministic key→shard index; identical in every process."""
    if isinstance(key, str):
        key = key.encode()
    return zlib.crc32(key) % nshards


def _kv_key(d):
    key = d["key"]
    return None if key.startswith(CONTROL_KEY_PREFIX) else key


# method -> key extractor; None routes to the director
_ROUTED = {
    "kv_put": _kv_key,
    "kv_get": _kv_key,
    "kv_del": _kv_key,
    "kv_exists": _kv_key,
    "add_object_location": lambda d: d["object_id"],
    "remove_object_location": lambda d: d["object_id"],
    "get_object_locations": lambda d: d["object_id"],
    "get_actor": lambda d: d["actor_id"],
    "get_placement_group": lambda d: d["pg_id"],
}


class GcsClient:
    """Drop-in facade over the director connection (same call/notify/
    subscribe surface), adding shard routing. Must be used from one
    event loop (the process's io loop), like the connection it wraps."""

    def __init__(self, director, config=None, uds_dir: str | None = None):
        self.director = director
        self._config = config
        # same-node fast path: when the shard's sibling UDS socket exists
        # under this dir, dial it instead of loopback TCP (rpc.prefer_uds
        # — remote shards pass through untouched)
        self._uds_dir = uds_dir
        self._shard_addrs: list[str] | None = None
        self._shards: dict[int, rpc.ReconnectingConnection] = {}
        self._map_lock: asyncio.Lock | None = None

    # -- shard discovery -------------------------------------------------

    async def _addresses(self) -> list[str]:
        if self._shard_addrs is not None:
            return self._shard_addrs
        if self._map_lock is None:
            self._map_lock = asyncio.Lock()
        async with self._map_lock:
            if self._shard_addrs is None:
                reply = await self.director.call("get_shard_map", {})
                self._shard_addrs = list((reply or {}).get("addresses", []))
        return self._shard_addrs

    async def _shard_conn(self, idx: int) -> rpc.ReconnectingConnection:
        conn = self._shards.get(idx)
        if conn is None:
            addrs = await self._addresses()
            retry = (self._config.gcs_reconnect_timeout_s
                     if self._config is not None else 30.0)
            local_ips = ("127.0.0.1",) + (
                (self._config.node_ip_address,)
                if self._config is not None else ())
            conn = self._shards[idx] = rpc.ReconnectingConnection(
                rpc.prefer_uds(addrs[idx], self._uds_dir,
                               local_ips=local_ips),
                name=f"->gcs-shard{idx}", retry_timeout=retry)
        return conn

    async def _route(self, method: str, data):
        """Connection owning this op, or the director."""
        extract = _ROUTED.get(method)
        if extract is None:
            return self.director
        key = extract(data)
        if key is None:
            return self.director
        addrs = await self._addresses()
        if not addrs:
            return self.director
        return await self._shard_conn(shard_for(key, len(addrs)))

    # -- call surface ----------------------------------------------------

    async def call(self, method: str, data=None, timeout: float | None = None):
        if method == "get_object_locations_batch":
            return await self._batch_locations(data, timeout)
        if method == "kv_keys":
            return await self._kv_keys(data, timeout)
        conn = await self._route(method, data)
        reply = await conn.call(method, data, timeout)
        if (reply is None and conn is not self.director
                and method in ("get_actor", "get_placement_group")):
            # Mirror miss: the director's push is best-effort (a shard
            # mid-restart loses it until the reconnect resync), so None
            # from a MIRROR is "not visible here yet", not "removed" —
            # only the owning director's answer is authoritative enough
            # for callers that treat None as removal (pg.ready()).
            reply = await self.director.call(method, data, timeout)
        return reply

    async def notify(self, method: str, data=None):
        conn = await self._route(method, data)
        await conn.notify(method, data)

    async def _batch_locations(self, data, timeout):
        addrs = await self._addresses()
        if not addrs:
            return await self.director.call("get_object_locations_batch",
                                            data, timeout)
        by_shard: dict[int, list] = {}
        for oid in data["object_ids"]:
            by_shard.setdefault(shard_for(oid, len(addrs)), []).append(oid)
        if len(by_shard) == 1:
            idx, oids = next(iter(by_shard.items()))
            conn = await self._shard_conn(idx)
            return await conn.call("get_object_locations_batch",
                                   {"object_ids": oids}, timeout)
        async def one(idx, oids):
            conn = await self._shard_conn(idx)
            return await conn.call("get_object_locations_batch",
                                   {"object_ids": oids}, timeout)

        parts = await asyncio.gather(
            *[one(idx, oids) for idx, oids in by_shard.items()])
        out = {}
        for part in parts:
            out.update(part or {})
        return out

    async def _kv_keys(self, data, timeout):
        addrs = await self._addresses()
        if not addrs:
            return await self.director.call("kv_keys", data, timeout)
        conns = [await self._shard_conn(i) for i in range(len(addrs))]
        parts = await asyncio.gather(
            self.director.call("kv_keys", data, timeout),
            *[c.call("kv_keys", data, timeout) for c in conns])
        seen: dict = dict.fromkeys(k for part in parts for k in (part or ()))
        return list(seen)

    async def barrier(self) -> None:
        """One ping per live connection (director + every dialed shard):
        frames are read in order per connection, so the replies arriving
        means every previously sent frame — including notify()s, which
        carry no reply of their own — has been dispatched server-side."""
        conns = [self.director, *self._shards.values()]
        await asyncio.gather(*(c.call("ping", {}) for c in conns))

    async def shard_metrics(self) -> dict[str, dict]:
        """Per-shard metric snapshots keyed by address (cluster_metrics).
        Concurrent: a dead shard costs one 2s timeout, not one each."""
        addrs = await self._addresses()

        async def one(i):
            try:
                conn = await self._shard_conn(i)
                return await conn.call("get_metrics", {}, timeout=2.0)
            except Exception:
                return {}

        snaps = await asyncio.gather(*(one(i) for i in range(len(addrs))))
        return dict(zip(addrs, snaps))

    # -- passthrough (director) -----------------------------------------

    async def push(self, channel: str, data=None):
        await self.director.push(channel, data)

    def set_push_handler(self, fn):
        self.director.set_push_handler(fn)

    async def ensure_connected(self):
        return await self.director.ensure_connected()

    @property
    def closed(self) -> bool:
        return self.director.closed

    @property
    def context(self):
        return self.director.context

    async def close(self):
        for conn in self._shards.values():
            try:
                await conn.close()
            except Exception:
                pass
        await self.director.close()
