"""GCS store shard: one key-partition of the control-plane tables.

The sharded control plane (Ray paper §4.1 analog, ROADMAP item 2) splits
the GCS into a stateless-ish *director* (gcs/server.py — membership,
scheduling, pubsub, placement) and N *store shards*, each owning a
deterministic key-partition (client.shard_for) of the high-rate tables:

- the KV store (every key except the director-owned `ray_tpu:` control
  keys — failpoint arming and trace-sampling ride the director's pubsub),
- the object directory (add/remove/get locations + the batched locality
  lookup — the hottest steady-state op stream in the cluster),
- read-only mirrors of the actor and placement-group directories (the
  director owns the writes and pushes every public-record transition
  here, so `get_actor` / `get_placement_group` polls scale with shard
  count instead of serializing through the scheduler's event loop).

Clients (core workers, raylets) route by key directly to the owning
shard — steady-state ops never touch the director (gcs/client.py).

Each shard persists through a snapshot + append-only journal
(journal.py): a killed shard replays to its exact pre-kill tables in
bounded time instead of waiting for raylet re-registration, and the node
monitor restarts it on its fixed port so client routing never remaps.

Failpoint seams: `gcs.shard.apply` before every mutating table apply
(`raise` -> the client's ReconnectingConnection retries idempotently;
`exit` kills the shard mid-workload — the chaos sweep's primary-kill),
plus the journal's `gcs.journal.append` / `gcs.journal.replay`.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import time

import msgpack

from ray_tpu._private import debug_state as _debug
from ray_tpu._private import failpoints as _fp
from ray_tpu._private import rpc
from ray_tpu._private import sampling_profiler as _sprof
from ray_tpu._private import stats as _stats
from ray_tpu._private.config import Config, get_config, set_config
from ray_tpu.gcs.journal import Journal

logger = logging.getLogger("ray_tpu.gcs.shard")

M_SHARD_OPS = _stats.Count(
    "gcs.shard_ops_total", "table ops served by this store shard")
M_SHARD_REPLAYS = _stats.Count(
    "gcs.shard_journal_replays_total",
    "journal records replayed at shard startup")


class GcsShard:
    def __init__(self, index: int, journal: Journal | None = None):
        self.index = index
        self.kv: dict[str, bytes] = {}
        # oid -> {"nodes": set[bytes], "size": int}
        self.object_locations: dict[bytes, dict] = {}
        # director-pushed read mirrors
        self.actors: dict[bytes, dict] = {}
        self.placement_groups: dict[bytes, dict] = {}
        self.journal = journal
        self._flush_fut: asyncio.Future | None = None
        # last drained-but-unacked profiler window (h_drain_profile_samples)
        self._profile_pending: dict | None = None
        if journal is not None:
            replayed = journal.recover(self._apply_snapshot, self._apply)
            if replayed:
                M_SHARD_REPLAYS.inc(replayed)
                logger.info("shard %d replayed %d journal records",
                            index, replayed)
        self.server = rpc.Server(self._handlers(), name=f"gcs-shard{index}")

    # ---- state application (live ops and journal replay share this) ----

    def _apply_snapshot(self, snap):
        self.kv = dict(snap.get("kv", {}))
        self.object_locations = {
            oid: {"nodes": set(rec[0]), "size": rec[1]}
            for oid, rec in snap.get("oloc", {}).items()}
        self.actors = dict(snap.get("actors", {}))
        self.placement_groups = dict(snap.get("pgs", {}))

    def _state(self) -> dict:
        return {
            "kv": self.kv,
            "oloc": {oid: [sorted(rec["nodes"]), rec["size"]]
                     for oid, rec in self.object_locations.items()},
            "actors": self.actors,
            "pgs": self.placement_groups,
        }

    def canonical_state(self) -> bytes:
        """Deterministic byte serialization of the full table state —
        byte-equal across a kill + journal replay (the chaos sweep's
        bit-identical restore check)."""
        def canon(v):
            if isinstance(v, dict):
                return [[canon(k), canon(v[k])]
                        for k in sorted(v, key=lambda x: (str(type(x)), x))]
            if isinstance(v, (set, frozenset)):
                return sorted(v)
            if isinstance(v, (list, tuple)):
                return [canon(x) for x in v]
            return v

        return msgpack.packb(canon(self._state()), use_bin_type=True)

    def _apply(self, rec):
        op = rec[0]
        if op == "kv_put":
            self.kv[rec[1]] = rec[2]
        elif op == "kv_del":
            self.kv.pop(rec[1], None)
        elif op == "oloc_add":
            entry = self.object_locations.setdefault(
                rec[1], {"nodes": set(), "size": 0})
            entry["nodes"].add(rec[2])
            if rec[3]:
                entry["size"] = int(rec[3])
        elif op == "oloc_rem":
            entry = self.object_locations.get(rec[1])
            if entry:
                entry["nodes"].discard(rec[2])
                if not entry["nodes"]:
                    del self.object_locations[rec[1]]
        elif op == "mirror":
            table = self.actors if rec[1] == "actors" else self.placement_groups
            table[rec[2]] = rec[3]
        elif op == "mirror_del":
            table = self.actors if rec[1] == "actors" else self.placement_groups
            table.pop(rec[2], None)
        elif op == "prune":
            for oid in [o for o, entry in self.object_locations.items()
                        if rec[1] in entry["nodes"]]:
                entry = self.object_locations[oid]
                entry["nodes"].discard(rec[1])
                if not entry["nodes"]:
                    del self.object_locations[oid]

    async def _mutate(self, rec):
        """One mutating table op: failpoint seam, apply, group-commit
        journal. The ack (handler return) is withheld until the record
        is flushed to the OS — process-kill durable — but the flush is
        COALESCED: every mutation in one event-loop batch shares a
        single write syscall (_flush_batch) instead of paying one each,
        which is what lets a shard's op rate scale past the legacy
        per-op-flush ceiling."""
        if _fp.ARMED:
            # shard-apply seam: `raise` -> RemoteError at the caller,
            # whose ReconnectingConnection/idempotent-op retries; `exit`
            # kills this shard primary mid-apply (chaos sweep)
            _fp.fire_strict("gcs.shard.apply")
        M_SHARD_OPS.inc()
        self._apply(rec)
        if self.journal is not None:
            self.journal.append_lazy(rec)
            await self._group_flush()
            self.journal.maybe_sync()
            self.journal.maybe_compact(self._state)

    def _group_flush(self) -> asyncio.Future:
        """One journal flush per event-loop batch: the first mutation of
        a tick schedules the flush via call_soon (running AFTER every
        handler queued in this tick has appended), later mutations in
        the same tick just await the shared future."""
        fut = self._flush_fut
        if fut is None or fut.done():
            loop = asyncio.get_running_loop()
            fut = self._flush_fut = loop.create_future()

            def _flush_batch():
                try:
                    self.journal.flush()
                    fut.set_result(None)
                except Exception as e:  # full disk etc. -> typed error
                    fut.set_exception(e)

            loop.call_soon(_flush_batch)
        return fut

    # ---- handlers ----

    def _handlers(self):
        return {
            "kv_put": self.h_kv_put,
            "kv_get": self.h_kv_get,
            "kv_del": self.h_kv_del,
            "kv_exists": self.h_kv_exists,
            "kv_keys": self.h_kv_keys,
            "add_object_location": self.h_add_object_location,
            "remove_object_location": self.h_remove_object_location,
            "get_object_locations": self.h_get_object_locations,
            "get_object_locations_batch": self.h_get_object_locations_batch,
            "get_actor": self.h_get_actor,
            "get_placement_group": self.h_get_placement_group,
            "mirror_apply": self.h_mirror_apply,
            "prune_node": self.h_prune_node,
            "configure_failpoints": self.h_configure_failpoints,
            "configure_profiling": self.h_configure_profiling,
            "drain_profile_samples": self.h_drain_profile_samples,
            "shard_snapshot": self.h_shard_snapshot,
            "get_metrics": self.h_get_metrics,
            "debug_state": self.h_debug_state,
            "debug_stacks": lambda conn, d: _debug.collect_stacks(),
            "ping": lambda conn, d: "pong",
        }

    async def h_debug_state(self, conn, d):
        """Shard live state: partition table sizes, journal occupancy,
        conn depth — the per-shard row inside the director's snapshot."""
        t_start = time.monotonic()
        snap = {
            "role": "gcs-shard",
            "index": self.index,
            "kv_keys": len(self.kv),
            "object_locations": len(self.object_locations),
            "actor_mirrors": len(self.actors),
            "pg_mirrors": len(self.placement_groups),
            "ops_total": M_SHARD_OPS.snapshot()["value"],
            "journal": ({"pending_flush": self._flush_fut is not None
                         and not self._flush_fut.done()}
                        if self.journal is not None else None),
            "rpc": {"server_conns": len(self.server.connections)},
        }
        return _debug.finish_snapshot(snap, t_start)

    # kv — same wire surface as the director's handlers, so routing is
    # invisible to callers
    async def h_kv_put(self, conn, d):
        key = d["key"]
        if not d.get("overwrite", True) and key in self.kv:
            return False
        await self._mutate(["kv_put", key, d["value"]])
        return True

    async def h_kv_get(self, conn, d):
        M_SHARD_OPS.inc()
        return self.kv.get(d["key"])

    async def h_kv_del(self, conn, d):
        existed = d["key"] in self.kv
        await self._mutate(["kv_del", d["key"]])
        return existed

    async def h_kv_exists(self, conn, d):
        M_SHARD_OPS.inc()
        return d["key"] in self.kv

    async def h_kv_keys(self, conn, d):
        prefix = d.get("prefix", "")
        return [k for k in self.kv if k.startswith(prefix)]

    # object directory partition
    async def h_add_object_location(self, conn, d):
        await self._mutate(["oloc_add", d["object_id"], d["node_id"],
                      int(d.get("size") or 0)])
        return True

    async def h_remove_object_location(self, conn, d):
        await self._mutate(["oloc_rem", d["object_id"], d["node_id"]])
        return True

    async def h_get_object_locations(self, conn, d):
        M_SHARD_OPS.inc()
        rec = self.object_locations.get(d["object_id"])
        return list(rec["nodes"]) if rec else []

    async def h_get_object_locations_batch(self, conn, d):
        M_SHARD_OPS.inc()
        out = {}
        for oid in d["object_ids"]:
            rec = self.object_locations.get(oid)
            if rec:
                out[oid] = {"nodes": list(rec["nodes"]),
                            "size": rec["size"]}
        return out

    # directory mirrors (director-pushed)
    async def h_get_actor(self, conn, d):
        M_SHARD_OPS.inc()
        return self.actors.get(d["actor_id"])

    async def h_get_placement_group(self, conn, d):
        M_SHARD_OPS.inc()
        return self.placement_groups.get(d["pg_id"])

    async def h_mirror_apply(self, conn, d):
        """Director pushes actor/pg public records (single or bulk
        resync after a shard restart). `value=None` deletes."""
        for table, key, value in d["records"]:
            if value is None:
                await self._mutate(["mirror_del", table, key])
            else:
                await self._mutate(["mirror", table, key, value])
        return True

    async def h_prune_node(self, conn, d):
        """Director broadcast on node death: drop every object location
        entry naming the dead node (no copy there anymore)."""
        await self._mutate(["prune", d["node_id"]])
        return True

    async def h_configure_failpoints(self, conn, d):
        """Live fault-injection arming forwarded by the director (shards
        don't subscribe to the pubsub plane — the director pushes the
        spec here on every `ray_tpu:failpoints` KV write and on shard
        reconnect)."""
        _fp.apply_kv_value(d["spec"])
        return True

    async def h_configure_profiling(self, conn, d):
        """Live profiler arming forwarded by the director (same push
        path as configure_failpoints — shards don't subscribe to the
        pubsub plane)."""
        _sprof.apply_kv_value(d["spec"])
        return True

    async def h_drain_profile_samples(self, conn, d):
        """Drain this shard's sampler window for the director's profile
        ring (the director polls on its ~2s ingest cadence).

        At-least-once: the drained batch is held until the NEXT call
        carries `ack` = its t_end (proof the director ingested it); an
        unmatched ack means the reply was lost (director timeout) — the
        held window merges back into the bounded table and rides the
        fresh drain, instead of being destroyed invisibly."""
        pending = self._profile_pending
        if pending is not None:
            if d.get("ack") == pending.get("t_end"):
                self._profile_pending = None
            else:
                _sprof.merge_back(pending)
                self._profile_pending = None
        batch = _sprof.drain_batch("gcs-shard")
        self._profile_pending = batch
        return batch or {}

    async def h_shard_snapshot(self, conn, d):
        """Canonical table-state bytes (chaos sweep bit-identical check)
        + op counter."""
        return {"state": self.canonical_state(),
                "ops": M_SHARD_OPS.snapshot()["value"],
                "index": self.index}

    async def h_get_metrics(self, conn, d):
        snap = _stats.snapshot()
        snap["gcs.shard_kv_keys"] = {"type": "gauge", "value": len(self.kv)}
        snap["gcs.shard_object_locations"] = {
            "type": "gauge", "value": len(self.object_locations)}
        return snap

    async def run(self, port: int, ready_file: str | None = None,
                  uds_dir: str | None = None):
        cfg = get_config()
        _debug.start_loop_lag_monitor()
        _sprof.start("gcs-shard")
        actual = await self.server.start_tcp(host=cfg.bind_host, port=port,
                                             uds_dir=uds_dir)
        logger.info("GCS shard %d listening on %s:%d", self.index,
                    cfg.bind_host, actual)
        if ready_file:
            tmp = ready_file + ".tmp"
            with open(tmp, "w") as f:
                f.write(str(actual))
            os.rename(tmp, ready_file)
        while True:
            await asyncio.sleep(3600)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--index", type=int, required=True)
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--ready-file", default=None)
    parser.add_argument("--log-file", default=None)
    parser.add_argument("--store-dir", default=None,
                        help="journal+snapshot dir; enables recovery")
    parser.add_argument("--uds-dir", default=None,
                        help="serve a sibling UDS listener here (same-node "
                             "clients skip the loopback-TCP tax)")
    args = parser.parse_args()
    from ray_tpu._private.log_utils import setup_process_logging

    setup_process_logging(f"gcs_shard_{args.index}", args.log_file)
    _fp.set_role("gcs")
    set_config(Config.load())
    journal = Journal(args.store_dir) if args.store_dir else None
    shard = GcsShard(args.index, journal=journal)
    asyncio.run(shard.run(args.port, args.ready_file,
                          uds_dir=args.uds_dir))


if __name__ == "__main__":
    main()
