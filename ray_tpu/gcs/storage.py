"""GCS table storage: crash-safe persistence for the control plane.

Plays the role of the reference's GcsTableStorage over Redis/in-memory
store clients (reference: src/ray/gcs/gcs_server/gcs_table_storage.h:294,
src/ray/gcs/store_client/redis_store_client.h): every control-plane
mutation (KV, jobs, actors, named actors, placement groups, node table)
is written through to disk, and a restarted GCS reloads the exact table
state. The design differs deliberately: instead of an external Redis
process, a single-writer append-only WAL of msgpack frames plus periodic
snapshot compaction under the session directory — no extra process, no
network hop, fsync only on actor/PG state transitions (the records whose
loss would strand live workers).

File layout (under `<dir>/`):
    snapshot.bin   msgpack({table: {key: value}})   (atomic rename)
    wal.bin        appended msgpack frames [op, table, key, value]

Recovery = load snapshot, replay WAL in order. Compaction rewrites the
snapshot and truncates the WAL once it outgrows `compact_bytes`.
"""

from __future__ import annotations

import os
import struct
import threading

import msgpack

_HDR = struct.Struct(">I")
PUT, DELETE = 0, 1


class GcsStorage:
    """Write-through table store. Keys/values must be msgpack-serializable
    (bytes keys fine). Thread-safe for the single-process GCS server."""

    def __init__(self, dir_path: str, compact_bytes: int = 4 << 20):
        self.dir = dir_path
        self.compact_bytes = compact_bytes
        os.makedirs(dir_path, exist_ok=True)
        self._snap_path = os.path.join(dir_path, "snapshot.bin")
        self._wal_path = os.path.join(dir_path, "wal.bin")
        self._lock = threading.Lock()
        self.tables: dict[str, dict] = {}
        valid_end = self._load()
        if valid_end is not None:
            # A crash mid-append left a torn frame: cut it off BEFORE
            # appending, or every later (valid) record would sit behind
            # the garbage and be discarded on the next recovery.
            with open(self._wal_path, "ab") as f:
                f.truncate(valid_end)
        self._wal = open(self._wal_path, "ab")

    # -- recovery ------------------------------------------------------

    def _load(self) -> int | None:
        """Replay snapshot+WAL. Returns the WAL offset of a torn tail (to
        truncate at), or None when the WAL is clean."""
        if os.path.exists(self._snap_path):
            with open(self._snap_path, "rb") as f:
                raw = msgpack.unpackb(f.read(), raw=False, strict_map_key=False)
            self.tables = {t: dict(kv) for t, kv in raw.items()}
        if os.path.exists(self._wal_path):
            with open(self._wal_path, "rb") as f:
                data = f.read()
            off = 0
            while off + _HDR.size <= len(data):
                (length,) = _HDR.unpack_from(data, off)
                end = off + _HDR.size + length
                if end > len(data):
                    return off  # torn tail from a crash mid-append
                try:
                    op, table, key, value = msgpack.unpackb(
                        data[off + _HDR.size:end], raw=False,
                        strict_map_key=False)
                except Exception:
                    if end == len(data):
                        return off  # last frame garbled: tail crash
                    # Corruption MID-file with valid (possibly fsynced)
                    # records after it: truncating would silently destroy
                    # durable state — fail loudly instead.
                    raise RuntimeError(
                        f"GCS WAL corrupt at offset {off} with "
                        f"{len(data) - end} bytes after it; refusing to "
                        f"auto-truncate (inspect {self._wal_path})")
                tbl = self.tables.setdefault(table, {})
                if op == PUT:
                    tbl[key] = value
                else:
                    tbl.pop(key, None)
                off = end
            if off != len(data):
                return off  # trailing partial header
        return None

    # -- mutation ------------------------------------------------------

    def _append(self, op: int, table: str, key, value, sync: bool):
        body = msgpack.packb([op, table, key, value], use_bin_type=True)
        with self._lock:
            self._wal.write(_HDR.pack(len(body)) + body)
            self._wal.flush()
            if sync:
                os.fsync(self._wal.fileno())
            if self._wal.tell() > self.compact_bytes:
                self._compact_locked()

    def put(self, table: str, key, value, sync: bool = False):
        self.tables.setdefault(table, {})[key] = value
        self._append(PUT, table, key, value, sync)

    def delete(self, table: str, key, sync: bool = False):
        self.tables.setdefault(table, {}).pop(key, None)
        self._append(DELETE, table, key, None, sync)

    def get(self, table: str, key, default=None):
        return self.tables.get(table, {}).get(key, default)

    def table(self, table: str) -> dict:
        return self.tables.get(table, {})

    # -- compaction ----------------------------------------------------

    def _compact_locked(self):
        tmp = self._snap_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(msgpack.packb(self.tables, use_bin_type=True))
            f.flush()
            os.fsync(f.fileno())
        os.rename(tmp, self._snap_path)
        self._wal.close()
        self._wal = open(self._wal_path, "wb")

    def compact(self):
        with self._lock:
            self._compact_locked()

    def close(self):
        with self._lock:
            try:
                self._wal.flush()
                os.fsync(self._wal.fileno())
                self._wal.close()
            except Exception:
                pass
