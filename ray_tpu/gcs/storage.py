"""GCS table storage: crash-safe persistence for the director.

Plays the role of the reference's GcsTableStorage over Redis/in-memory
store clients (reference: src/ray/gcs/gcs_server/gcs_table_storage.h:294,
src/ray/gcs/store_client/redis_store_client.h): every control-plane
mutation (KV, jobs, actors, named actors, placement groups, node table)
is written through to disk, and a restarted GCS reloads the exact table
state. The design differs deliberately: instead of an external Redis
process, a single-writer append-only WAL of msgpack frames plus periodic
snapshot compaction under the session directory — no extra process, no
network hop, fsync only on actor/PG state transitions (the records whose
loss would strand live workers).

Since the sharded control plane landed, the frame/snapshot engine lives
in journal.py (the same engine every store shard persists through); this
class is the table-shaped wrapper the director uses.

File layout (under `<dir>/`):
    snapshot.bin   msgpack({table: {key: value}})   (atomic rename)
    wal.bin        appended msgpack frames [op, table, key, value]

Recovery = load snapshot, replay WAL in order. Compaction rewrites the
snapshot and truncates the WAL once it outgrows `compact_bytes`.
"""

from __future__ import annotations

from ray_tpu.gcs.journal import Journal

PUT, DELETE = 0, 1


class GcsStorage:
    """Write-through table store. Keys/values must be msgpack-serializable
    (bytes keys fine). Thread-safe for the single-process GCS server."""

    def __init__(self, dir_path: str, compact_bytes: int = 4 << 20):
        self.dir = dir_path
        self.tables: dict[str, dict] = {}
        self.journal = Journal(dir_path, compact_bytes,
                               journal_name="wal.bin")
        self.journal.recover(self._apply_snapshot, self._apply_record)

    def _apply_snapshot(self, raw):
        self.tables = {t: dict(kv) for t, kv in raw.items()}

    def _apply_record(self, rec):
        op, table, key, value = rec
        tbl = self.tables.setdefault(table, {})
        if op == PUT:
            tbl[key] = value
        else:
            tbl.pop(key, None)

    # -- mutation ------------------------------------------------------

    def put(self, table: str, key, value, sync: bool = False):
        self.tables.setdefault(table, {})[key] = value
        self.journal.append([PUT, table, key, value], sync=sync)
        self.journal.maybe_sync()
        self.journal.maybe_compact(lambda: self.tables)

    def delete(self, table: str, key, sync: bool = False):
        self.tables.setdefault(table, {}).pop(key, None)
        self.journal.append([DELETE, table, key, None], sync=sync)
        self.journal.maybe_sync()
        self.journal.maybe_compact(lambda: self.tables)

    def get(self, table: str, key, default=None):
        return self.tables.get(table, {}).get(key, default)

    def table(self, table: str) -> dict:
        return self.tables.get(table, {})

    # -- compaction ----------------------------------------------------

    def compact(self):
        self.journal.compact(self.tables)

    def close(self):
        self.journal.close()
