"""Env registry + creation (reference: rllib/env/ + tune/registry.py
register_env). Accepts gymnasium env ids or registered creator fns."""

from __future__ import annotations

from typing import Callable

_ENV_REGISTRY: dict[str, Callable] = {}


def register_env(name: str, creator: Callable):
    """register_env("my_env", lambda config: MyEnv(config))"""
    _ENV_REGISTRY[name] = creator


def make_env(env_spec, env_config: dict | None = None):
    env_config = env_config or {}
    if callable(env_spec):
        return env_spec(env_config)
    if env_spec in _ENV_REGISTRY:
        return _ENV_REGISTRY[env_spec](env_config)
    import gymnasium

    return gymnasium.make(env_spec, **env_config)
