"""Env registry + creation (reference: rllib/env/ + tune/registry.py
register_env). Accepts gymnasium env ids or registered creator fns."""

from __future__ import annotations

from typing import Callable

_ENV_REGISTRY: dict[str, Callable] = {}


class MultiAgentEnv:
    """Multi-agent env interface (reference: rllib/env/multi_agent_env.py).

    reset() -> (obs_dict, info_dict); step(action_dict) ->
    (obs_dict, reward_dict, terminated_dict, truncated_dict, info_dict).
    Dicts are keyed by agent id; terminated/truncated carry the special
    "__all__" key ending the episode for everyone. Agents may appear and
    disappear between steps — only agents present in obs act next step."""

    observation_space = None
    action_space = None

    def reset(self, seed=None):
        raise NotImplementedError

    def step(self, action_dict: dict):
        raise NotImplementedError

    def close(self):
        pass


def register_env(name: str, creator: Callable):
    """register_env("my_env", lambda config: MyEnv(config))"""
    _ENV_REGISTRY[name] = creator


__all__ = ["MultiAgentEnv", "make_env", "register_env"]


def make_env(env_spec, env_config: dict | None = None):
    env_config = env_config or {}
    if callable(env_spec):
        return env_spec(env_config)
    if env_spec in _ENV_REGISTRY:
        return _ENV_REGISTRY[env_spec](env_config)
    import gymnasium

    return gymnasium.make(env_spec, **env_config)
