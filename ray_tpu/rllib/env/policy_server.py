"""External-simulator serving (reference: rllib/env/policy_server_input.py
PolicyServerInput + rllib/env/policy_client.py PolicyClient).

An external process (a game, a robot, a production system) drives episodes
against a policy hosted over HTTP; the server side accumulates the
resulting trajectories as SampleBatches that a trainer can consume as an
input reader. Transport is plain JSON over a threaded http.server (no
asyncio requirement on the simulator side)."""

from __future__ import annotations

import json
import queue
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from ray_tpu.rllib.policy.sample_batch import SampleBatch

__all__ = ["PolicyClient", "PolicyServerInput"]


class PolicyServerInput:
    """Host `policy` on http://host:port; acts as an input reader:
    next() blocks until a completed episode batch is available."""

    def __init__(self, policy, address: str = "127.0.0.1", port: int = 0):
        self.policy = policy
        self._episodes: "queue.Queue[SampleBatch]" = queue.Queue()
        self._live: dict = {}  # episode_id -> column buffers
        self._lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(length))
                try:
                    resp = outer._handle(req)
                    body = json.dumps(resp).encode()
                    self.send_response(200)
                except Exception as e:  # surfaced to the client
                    body = json.dumps({"error": str(e)}).encode()
                    self.send_response(500)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer((address, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()

    # -- protocol --------------------------------------------------------

    def _handle(self, req: dict) -> dict:
        cmd = req["command"]
        if cmd == "start_episode":
            eid = req["episode_id"]
            with self._lock:
                self._live[eid] = {k: [] for k in (
                    SampleBatch.OBS, SampleBatch.ACTIONS,
                    SampleBatch.REWARDS, SampleBatch.DONES,
                    SampleBatch.ACTION_LOGP, SampleBatch.VF_PREDS)}
            return {"ok": True}
        if cmd == "get_action":
            eid = req["episode_id"]
            obs = np.asarray(req["observation"], np.float32).ravel()
            actions, extra = self.policy.compute_actions(obs[None])
            with self._lock:
                buf = self._live[eid]
                buf[SampleBatch.OBS].append(obs)
                buf[SampleBatch.ACTIONS].append(actions[0])
                buf[SampleBatch.ACTION_LOGP].append(
                    extra[SampleBatch.ACTION_LOGP][0])
                buf[SampleBatch.VF_PREDS].append(
                    extra[SampleBatch.VF_PREDS][0])
            act = actions[0]
            return {"action": act.tolist() if hasattr(act, "tolist")
                    else act}
        if cmd == "log_returns":
            with self._lock:
                self._live[req["episode_id"]][SampleBatch.REWARDS].append(
                    float(req["reward"]))
            return {"ok": True}
        if cmd == "end_episode":
            eid = req["episode_id"]
            with self._lock:
                buf = self._live.pop(eid)
            n = len(buf[SampleBatch.ACTIONS])
            rewards = buf[SampleBatch.REWARDS][:n]
            rewards += [0.0] * (n - len(rewards))
            if n:
                dones = [False] * (n - 1) + [True]
                batch = SampleBatch({
                    SampleBatch.OBS: np.stack(buf[SampleBatch.OBS]),
                    SampleBatch.ACTIONS: np.asarray(
                        buf[SampleBatch.ACTIONS]),
                    SampleBatch.REWARDS: np.asarray(rewards, np.float32),
                    SampleBatch.DONES: np.asarray(dones),
                    SampleBatch.ACTION_LOGP: np.asarray(
                        buf[SampleBatch.ACTION_LOGP], np.float32),
                    SampleBatch.VF_PREDS: np.asarray(
                        buf[SampleBatch.VF_PREDS], np.float32),
                    SampleBatch.EPS_ID: np.full(n, hash(eid) % (2**31)),
                })
                self._episodes.put(batch)
            return {"ok": True}
        raise ValueError(f"unknown command {cmd!r}")

    # -- input-reader surface -------------------------------------------

    def next(self, timeout: float | None = 60) -> SampleBatch:
        return self._episodes.get(timeout=timeout)

    def stop(self):
        self._server.shutdown()
        self._thread.join(timeout=5)


class PolicyClient:
    """Client for an external simulator process (reference:
    rllib/env/policy_client.py:31)."""

    def __init__(self, address: str):
        self.address = address.rstrip("/")
        self._next_eid = 0

    def _call(self, payload: dict) -> dict:
        req = urllib.request.Request(
            self.address, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                out = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            # surface the server-side exception message, not a bare 500
            try:
                detail = json.loads(e.read()).get("error", str(e))
            except Exception:
                detail = str(e)
            raise RuntimeError(f"policy server error: {detail}") from None
        if "error" in out:
            raise RuntimeError(out["error"])
        return out

    def start_episode(self, episode_id: str | None = None) -> str:
        if episode_id is None:
            episode_id = f"client-{id(self)}-{self._next_eid}"
            self._next_eid += 1
        self._call({"command": "start_episode",
                    "episode_id": episode_id})
        return episode_id

    def get_action(self, episode_id: str, observation):
        obs = np.asarray(observation, np.float32)
        out = self._call({"command": "get_action",
                          "episode_id": episode_id,
                          "observation": obs.tolist()})
        return out["action"]

    def log_returns(self, episode_id: str, reward: float):
        self._call({"command": "log_returns", "episode_id": episode_id,
                    "reward": float(reward)})

    def end_episode(self, episode_id: str):
        self._call({"command": "end_episode", "episode_id": episode_id})
