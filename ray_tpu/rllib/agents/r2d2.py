"""R2D2 — recurrent experience replay in distributed Q-learning
(reference: rllib/agents/dqn/r2d2.py in later snapshots; Kapturowski et
al. 2019). Value-based learning for partially-observable envs.

A recurrent (LSTM) Q network acts with per-env hidden state threaded by
the rollout worker (the same state/unroll columns the recurrent policy
family records); replay stores fixed-length SEQUENCES with the sampled
initial state of each; training replays every sequence through the LSTM
— a burn-in prefix rebuilds state off stored (possibly stale) values
before TD errors count — and targets come from a target network run over
the same sequences, double-DQN style. One jitted step does the whole
sequence TD update."""

from __future__ import annotations

import numpy as np

from ray_tpu.rllib.agents.dqn import linear_epsilon
from ray_tpu.rllib.agents.trainer import COMMON_CONFIG, Trainer
from ray_tpu.rllib.execution.replay_buffer import ReplayBuffer
from ray_tpu.rllib.models.catalog import ModelCatalog
from ray_tpu.rllib.policy.policy import Policy
from ray_tpu.rllib.policy.recurrent_policy import (STATE_C, STATE_H,
                                                   UNROLL_ID,
                                                   chop_sequences)
from ray_tpu.rllib.policy.sample_batch import SampleBatch

R2D2_CONFIG = {
    **COMMON_CONFIG,
    "num_workers": 0,
    "rollout_fragment_length": 64,
    "train_batch_size": 16,       # sequences per update
    "seq_len": 16,                # replayed sequence length
    "burn_in": 4,                 # state-rebuild prefix, no TD loss
    "buffer_size": 2000,          # sequences
    "learning_starts": 64,        # sequences
    "sgd_rounds_per_step": 4,
    "target_network_update_freq": 500,
    "lstm_cell_size": 64,
    "double_q": True,
    "lr": 1e-3,
    "exploration_initial_eps": 1.0,
    "exploration_final_eps": 0.05,
    "exploration_fraction": 0.4,
    "total_timesteps_anneal": 10_000,
}


class R2D2Policy(Policy):
    """Recurrent epsilon-greedy Q policy (discrete only)."""

    is_recurrent = True
    discrete = True

    def __init__(self, observation_space, action_space, config: dict):
        import jax
        import jax.numpy as jnp
        import optax

        merged = {**R2D2_CONFIG, **config}
        super().__init__(observation_space, action_space, merged)
        if not hasattr(action_space, "n"):
            raise ValueError("R2D2 requires a discrete action space")
        n_act = int(action_space.n)
        self._n_act = n_act
        init, step, seq, cell = ModelCatalog.get_recurrent_model(
            observation_space, n_act, merged)
        self._step_fn = jax.jit(step)
        self._seq_fn = seq
        self.cell_size = cell
        self.state_sizes = (cell, cell)
        seed = merged.get("seed") or 0
        self.params = init(jax.random.key(seed))
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self._optimizer = optax.adam(merged["lr"])
        self.opt_state = self._optimizer.init(self.params)
        self.eps = float(merged["exploration_initial_eps"])
        self._rng = np.random.RandomState(
            seed + 3 + 7919 * merged.get("worker_index", 0))
        self._build()

    def get_initial_state(self):
        return [np.zeros(self.cell_size, np.float32),
                np.zeros(self.cell_size, np.float32)]

    def _build(self):
        import jax
        import jax.numpy as jnp

        seq = self._seq_fn
        gamma = self.config.get("gamma", 0.99)
        double_q = bool(self.config.get("double_q", True))
        burn_in = int(self.config.get("burn_in", 0))
        optimizer = self._optimizer

        def loss_fn(params, target_params, batch):
            # batch: obs [S,T,D], actions [S,T], rewards/dones/resets/
            # mask [S,T], h0/c0 [S,cell]
            state0 = (batch["h0"], batch["c0"])
            q, _ = seq(params, batch["obs"], state0, batch["resets"])
            q_t, _ = seq(target_params, batch["obs"], state0,
                         batch["resets"])
            q_chosen = jnp.take_along_axis(
                q, batch["actions"][..., None].astype(jnp.int32),
                axis=-1)[..., 0]                       # [S, T]
            if double_q:
                sel = jnp.argmax(q, axis=-1)
            else:
                sel = jnp.argmax(q_t, axis=-1)
            boot = jnp.take_along_axis(q_t, sel[..., None],
                                       axis=-1)[..., 0]
            # in-sequence targets: step t bootstraps from t+1 (the last
            # step of each sequence has no successor and is masked out)
            targets = (batch["rewards"][:, :-1]
                       + gamma * (1.0 - batch["dones"][:, :-1])
                       * boot[:, 1:])
            targets = jax.lax.stop_gradient(targets)
            td = q_chosen[:, :-1] - targets
            # mask: padding, the burn-in prefix, and TRUNCATED episode
            # boundaries. A reset at t+1 only invalidates step t when t
            # was NOT terminal — terminal steps need no successor (their
            # bootstrap is already zeroed by (1-dones)) and they carry
            # the clearest TD signal, so they must stay in the loss.
            dones_t = batch["dones"][:, :-1]
            mask = batch["mask"][:, :-1] * batch["mask"][:, 1:]
            mask = mask * (1.0 - batch["resets"][:, 1:] * (1.0 - dones_t))
            if burn_in:
                mask = mask.at[:, :burn_in].set(0.0)
            n = jnp.maximum(mask.sum(), 1.0)
            huber = jnp.where(jnp.abs(td) < 1.0, 0.5 * td * td,
                              jnp.abs(td) - 0.5)
            return (huber * mask).sum() / n, jnp.abs(td * mask).sum() / n

        @jax.jit
        def train(params, target_params, opt_state, batch):
            (loss, td_abs), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, batch)
            updates, opt_state = optimizer.update(grads, opt_state,
                                                  params)
            params = jax.tree.map(lambda p, u: p + u, params, updates)
            return params, opt_state, loss, td_abs

        self._train = train

    # -- acting (recurrent surface the rollout worker drives) ------------

    def compute_actions_with_state(self, obs_batch, states,
                                   explore: bool = True):
        import jax.numpy as jnp

        obs = jnp.asarray(obs_batch, jnp.float32).reshape(
            len(obs_batch), -1)
        h = jnp.asarray(states[0], jnp.float32)
        c = jnp.asarray(states[1], jnp.float32)
        q, (h2, c2) = self._step_fn(self.params, obs, (h, c))
        q = np.asarray(q)
        actions = q.argmax(axis=-1)
        if explore and self.eps > 0:
            mask = self._rng.random_sample(len(actions)) < self.eps
            actions = np.where(
                mask, self._rng.randint(0, self._n_act, len(actions)),
                actions)
        extra = {SampleBatch.ACTION_LOGP: np.zeros(len(actions),
                                                   np.float32),
                 SampleBatch.VF_PREDS: q.max(axis=-1)}
        return actions, extra, [np.asarray(h2), np.asarray(c2)]

    def compute_actions(self, obs_batch, explore: bool = True):
        h = np.zeros((len(obs_batch), self.cell_size), np.float32)
        acts, extra, _ = self.compute_actions_with_state(
            obs_batch, [h, h.copy()], explore)
        return acts, extra

    def set_epsilon(self, eps: float):
        self.eps = float(eps)
        return True

    def update_target(self):
        import jax
        import jax.numpy as jnp

        self.target_params = jax.tree.map(jnp.copy, self.params)

    def learn_on_sequences(self, seq_batch: dict) -> dict:
        import jax.numpy as jnp

        jb = {k: jnp.asarray(v) for k, v in seq_batch.items()}
        self.params, self.opt_state, loss, td_abs = self._train(
            self.params, self.target_params, self.opt_state, jb)
        return {"loss": float(loss), "td_abs": float(td_abs)}

    def get_weights(self):
        import jax

        return {"q": jax.tree.map(np.asarray, self.params),
                "eps": self.eps}

    def set_weights(self, weights):
        import jax
        import jax.numpy as jnp

        self.params = jax.tree.map(jnp.asarray, weights["q"])
        self.eps = weights["eps"]


class R2D2Trainer(Trainer):
    """reference: rllib/agents/dqn/r2d2.py execution plan — the DQN
    store→replay→train shape over SEQUENCES."""

    _default_config = R2D2_CONFIG
    _name = "R2D2"

    @staticmethod
    def policy_builder(obs_space, action_space, config):
        return R2D2Policy(obs_space, action_space, config)

    def setup(self, config):
        super().setup(config)
        self._buffer = ReplayBuffer(config["buffer_size"],
                                    seed=config.get("seed"))
        self._timesteps = 0
        self._last_target_update = 0

    def train_step(self) -> dict:
        cfg = self.config
        policy = self.workers.local_worker.policy
        policy.set_epsilon(linear_epsilon(cfg, self._timesteps))
        batch = self.workers.sample(cfg["rollout_fragment_length"])
        self._timesteps += batch.count
        # chop the fragment into stored-state sequences and stash them
        # (each buffer ROW is one [T, ...] sequence)
        seq_cols = chop_sequences(
            batch, policy.state_sizes, int(cfg["seq_len"]),
            {"rewards": batch[SampleBatch.REWARDS].astype(np.float32),
             "dones": batch[SampleBatch.DONES].astype(np.float32)})
        self._buffer.add_batch(SampleBatch(seq_cols))
        metrics = {"timesteps_total": self._timesteps,
                   "epsilon": round(policy.eps, 4),
                   "buffer_sequences": len(self._buffer)}
        if len(self._buffer) < cfg["learning_starts"]:
            return metrics
        for _ in range(cfg["sgd_rounds_per_step"]):
            replay = self._buffer.sample(cfg["train_batch_size"])
            metrics.update(policy.learn_on_sequences(dict(replay)))
        if (self._timesteps - self._last_target_update
                >= cfg["target_network_update_freq"]):
            self._last_target_update = self._timesteps
            policy.update_target()
        self.workers.sync_weights()
        return metrics
