"""PPO (reference: rllib/agents/ppo/ppo.py + ppo_torch_policy.py):
GAE advantages (postprocessing.py compute_advantages), clipped surrogate
objective, value clipping, entropy bonus, minibatch SGD epochs.

TPU shape: the whole SGD epoch runs as jitted steps on the learner while
CPU rollout actors collect the next train batch."""

from __future__ import annotations

import weakref

import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.agents.trainer import build_trainer
from ray_tpu.rllib.policy.jax_policy import JAXPolicy
from ray_tpu.rllib.policy.sample_batch import SampleBatch

_SHUFFLE_RNGS = weakref.WeakKeyDictionary()


def _shuffle_rng(workers, seed: int) -> np.random.RandomState:
    rng = _SHUFFLE_RNGS.get(workers)
    if rng is None:
        rng = np.random.RandomState(seed)
        _SHUFFLE_RNGS[workers] = rng
    return rng

PPO_CONFIG: dict = {
    "rollout_fragment_length": 256,
    "train_batch_size": 1024,
    "sgd_minibatch_size": 256,
    "num_sgd_iter": 8,
    "lr": 3e-4,
    "gamma": 0.99,
    "lambda": 0.95,
    "clip_param": 0.2,
    "vf_clip_param": 10.0,
    "vf_loss_coeff": 0.5,
    "entropy_coeff": 0.0,
}


def compute_gae(batch: SampleBatch, last_value: float, gamma: float,
                lam: float) -> SampleBatch:
    """reference: rllib/evaluation/postprocessing.py compute_advantages."""
    rewards = batch[SampleBatch.REWARDS].astype(np.float64)
    values = batch[SampleBatch.VF_PREDS].astype(np.float64)
    dones = batch[SampleBatch.DONES].astype(np.float64)
    n = len(rewards)
    adv = np.zeros(n)
    next_value = last_value
    next_adv = 0.0
    for t in range(n - 1, -1, -1):
        nonterminal = 1.0 - dones[t]
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        next_adv = delta + gamma * lam * nonterminal * next_adv
        adv[t] = next_adv
        next_value = values[t]
    batch[SampleBatch.ADVANTAGES] = adv.astype(np.float32)
    batch[SampleBatch.VALUE_TARGETS] = (adv + values).astype(np.float32)
    return batch


class PPOPolicy(JAXPolicy):
    def __init__(self, observation_space, action_space, config):
        merged = {**PPO_CONFIG, **config}
        super().__init__(observation_space, action_space, merged,
                         loss_fn=ppo_loss)

    def postprocess_trajectory(self, batch, other_agent_batches=None,
                               episode=None):
        """Per-episode GAE; bootstrap non-terminated fragment tails with
        the value function."""
        out = []
        for episode_batch in batch.split_by_episode():
            if episode_batch[SampleBatch.DONES][-1]:
                last_value = 0.0
            else:
                last_value = float(self.compute_values(
                    episode_batch[SampleBatch.NEXT_OBS][-1:])[0])
            out.append(compute_gae(episode_batch, last_value,
                                   self.config["gamma"],
                                   self.config["lambda"]))
        return SampleBatch.concat_samples(out)


def ppo_loss(params, batch, policy: PPOPolicy):
    """reference: ppo_torch_policy.py ppo_surrogate_loss."""
    cfg = policy.config
    pi_out, values = JAXPolicy.model_out(
        params, batch[SampleBatch.OBS].astype(jnp.float32))
    logp = policy.logp_fn()(pi_out, batch[SampleBatch.ACTIONS])
    entropy = policy.entropy_fn()(pi_out).mean()

    adv = batch[SampleBatch.ADVANTAGES]
    adv = (adv - adv.mean()) / (adv.std() + 1e-8)
    ratio = jnp.exp(logp - batch[SampleBatch.ACTION_LOGP])
    surrogate = jnp.minimum(
        adv * ratio,
        adv * jnp.clip(ratio, 1 - cfg["clip_param"],
                       1 + cfg["clip_param"]))
    policy_loss = -surrogate.mean()

    # Dual-clip value loss (reference ppo_torch_policy.py): clip the
    # prediction's movement from the old value, keep the max of the two
    # losses — clipping the error itself would zero gradients exactly
    # when the value function is far from its targets.
    targets = batch[SampleBatch.VALUE_TARGETS]
    old_values = batch[SampleBatch.VF_PREDS]
    vf_loss1 = (values - targets) ** 2
    clipped = old_values + jnp.clip(values - old_values,
                                    -cfg["vf_clip_param"],
                                    cfg["vf_clip_param"])
    vf_loss2 = (clipped - targets) ** 2
    vf_loss = jnp.maximum(vf_loss1, vf_loss2).mean()

    total = (policy_loss + cfg["vf_loss_coeff"] * vf_loss
             - cfg["entropy_coeff"] * entropy)
    return total, {
        "policy_loss": policy_loss,
        "vf_loss": vf_loss,
        "entropy": entropy,
        "mean_ratio": ratio.mean(),
    }


def _sgd_epochs(policy, batch: SampleBatch, config, rng) -> dict:
    metrics: dict = {}
    for _ in range(config["num_sgd_iter"]):
        for mb in batch.minibatches(config["sgd_minibatch_size"], rng):
            metrics = policy.learn_on_batch(mb)
    return metrics


def ppo_train_step(workers, config) -> dict:
    """Collect → minibatch SGD epochs → broadcast (reference:
    ppo.py:238 execution_plan = ParallelRollouts → TrainOneStep)."""
    from ray_tpu.rllib.policy.sample_batch import MultiAgentBatch

    target = config["train_batch_size"]
    batches = []
    collected = 0
    while collected < target:
        b = workers.sample(config["rollout_fragment_length"])
        batches.append(b)
        collected += (b.count if isinstance(b, MultiAgentBatch)
                      else len(b))
    # One shuffle stream per worker set (not per call, and not stashed in
    # the user-visible config) so minibatch composition decorrelates
    # across iterations.
    rng = _shuffle_rng(workers, config.get("seed", 0))
    lw = workers.local_worker
    if isinstance(batches[0], MultiAgentBatch):
        batch = MultiAgentBatch.concat_samples(batches)
        metrics = {
            pid: _sgd_epochs(lw.policies[pid],
                             batch.policy_batches[pid], config, rng)
            for pid in lw.policies_to_train
            if pid in batch.policy_batches}
        metrics["num_env_steps_trained"] = batch.count
    else:
        batch = SampleBatch.concat_samples(batches)
        metrics = _sgd_epochs(lw.policy, batch, config, rng)
        metrics["num_env_steps_trained"] = len(batch)
    workers.sync_weights()
    return metrics


PPOTrainer = build_trainer("PPO", PPO_CONFIG, PPOPolicy, ppo_train_step,
                           supports_multiagent=True)
