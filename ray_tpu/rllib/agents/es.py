"""Evolution strategies (reference: rllib/agents/es/es.py — Salimans et
al. 2017): gradient-free search that parallelizes perfectly over
actors. Each iteration: workers evaluate antithetic parameter
perturbations on full episodes; the learner combines returns into one
weight update (rank-normalized, mirrored sampling).

Shape here: perturbations are generated worker-side from a shared noise
seed + offsets (only integers cross the wire, reference: es.py
SharedNoiseTable), episode evaluation is the unit of actor work, the
update is a single vectorized combine on the driver."""

from __future__ import annotations

import numpy as np

import ray_tpu
from ray_tpu.rllib.agents.trainer import Trainer
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.policy.jax_policy import JAXPolicy

ES_CONFIG: dict = {
    "num_workers": 2,
    "episodes_per_batch": 16,    # perturbation PAIRS per iteration
    "noise_std": 0.05,
    "step_size": 0.02,
    "noise_table_size": 4_000_000,
    # noise_seed defaults from config["seed"] when unset
    "noise_seed": None,
    "eval_episode_len": 1000,
}


def _flatten(params) -> tuple[np.ndarray, list]:
    import jax

    leaves, treedef = jax.tree.flatten(params)
    flat = np.concatenate([np.asarray(l).ravel() for l in leaves])
    shapes = [np.asarray(l).shape for l in leaves]
    return flat.astype(np.float32), (treedef, shapes)


def _unflatten(flat: np.ndarray, spec):
    import jax

    treedef, shapes = spec
    out, off = [], 0
    for shape in shapes:
        n = int(np.prod(shape)) if shape else 1
        out.append(flat[off:off + n].reshape(shape))
        off += n
    return jax.tree.unflatten(treedef, out)


def _noise_table(size: int, seed: int) -> np.ndarray:
    return np.random.RandomState(seed).randn(size).astype(np.float32)


def rank_transform(returns: np.ndarray) -> np.ndarray:
    """Centered-rank normalization (reference: es/utils.py
    compute_centered_ranks) — robust to return scale/outliers."""
    ranks = np.empty(returns.size, dtype=np.float32)
    ranks[returns.ravel().argsort()] = np.arange(returns.size)
    ranks = ranks.reshape(returns.shape)
    return ranks / (returns.size - 1) - 0.5


class _ESWorker:
    """Actor: evaluates antithetic perturbations on full episodes."""

    def __init__(self, env_spec, env_config, policy_config, table_size,
                 noise_seed, worker_seed):
        self.env = make_env(env_spec, env_config or {})
        self.policy = JAXPolicy(self.env.observation_space,
                                self.env.action_space, policy_config)
        self.noise = _noise_table(table_size, noise_seed)
        self._rng = np.random.RandomState(worker_seed)
        flat, self._spec = _flatten(self.policy.params)
        self._dim = flat.size

    def _episode_return(self, flat_params, max_steps) -> float:
        self.policy.set_weights(_unflatten(flat_params, self._spec))
        obs, _ = self.env.reset(
            seed=int(self._rng.randint(0, 2**31 - 1)))
        total, steps, done = 0.0, 0, False
        while not done and steps < max_steps:
            acts, _ = self.policy.compute_actions(
                np.asarray(obs, np.float32).ravel()[None], explore=False)
            act = int(acts[0]) if self.policy.discrete else acts[0]
            obs, r, term, trunc, _ = self.env.step(act)
            total += float(r)
            steps += 1
            done = term or trunc
        return total

    def evaluate_pairs(self, flat_params: np.ndarray, num_pairs: int,
                       noise_std: float, max_steps: int):
        """[(noise_offset, return_pos, return_neg), ...] — mirrored
        sampling cancels the baseline (reference: es.py antithetic)."""
        flat_params = np.asarray(flat_params, np.float32)
        out = []
        for _ in range(num_pairs):
            off = int(self._rng.randint(
                0, self.noise.size - self._dim))
            eps = self.noise[off:off + self._dim]
            r_pos = self._episode_return(flat_params + noise_std * eps,
                                         max_steps)
            r_neg = self._episode_return(flat_params - noise_std * eps,
                                         max_steps)
            out.append((off, r_pos, r_neg))
        return out

    def stop(self):
        try:
            self.env.close()
        except Exception:
            pass


class ESTrainer(Trainer):
    _name = "ES"
    _default_config = ES_CONFIG

    def setup(self, config: dict):
        if config.get("env") is None:
            raise ValueError("config['env'] must be set")
        # driver-side policy holds the current parameters
        env = make_env(config["env"], config.get("env_config", {}))
        self.policy = JAXPolicy(env.observation_space, env.action_space,
                                config)
        env.close()
        self.flat, self._spec = _flatten(self.policy.params)
        if self.flat.size >= config["noise_table_size"]:
            raise ValueError(
                f"noise_table_size ({config['noise_table_size']}) must "
                f"exceed the policy's parameter count ({self.flat.size})")
        noise_seed = config.get("noise_seed")
        if noise_seed is None:
            noise_seed = (config.get("seed") or 0) + 42
        self._noise_seed = noise_seed
        self.noise = _noise_table(config["noise_table_size"], noise_seed)
        worker_cls = ray_tpu.remote(
            resources={"CPU": config.get("num_cpus_per_worker", 1)})(
            _ESWorker)
        n = max(1, config["num_workers"])
        self.workers = [
            worker_cls.remote(config["env"], config.get("env_config"),
                              {k: v for k, v in config.items()
                               if k not in ("env",)},
                              config["noise_table_size"],
                              self._noise_seed,
                              (config.get("seed") or 0) * 10_000
                              + 1000 + i)
            for i in range(n)
        ]
        self._episodes_total = 0

    def train_step(self) -> dict:  # pragma: no cover - step() overrides
        raise NotImplementedError

    def step(self) -> dict:
        cfg = self.config
        total_pairs = max(1, cfg["episodes_per_batch"])
        base, extra = divmod(total_pairs, len(self.workers))
        counts = [base + (1 if i < extra else 0)
                  for i in range(len(self.workers))]
        results = ray_tpu.get(
            [w.evaluate_pairs.remote(self.flat, c, cfg["noise_std"],
                                     cfg["eval_episode_len"])
             for w, c in zip(self.workers, counts) if c], timeout=600)
        offsets, pos, neg = [], [], []
        for worker_out in results:
            for off, r_pos, r_neg in worker_out:
                offsets.append(off)
                pos.append(r_pos)
                neg.append(r_neg)
        pos = np.asarray(pos, np.float32)
        neg = np.asarray(neg, np.float32)
        ranks = rank_transform(np.stack([pos, neg]))
        weights = ranks[0] - ranks[1]          # mirrored-sample combine
        dim = self.flat.size
        grad = np.zeros(dim, np.float32)
        for w, off in zip(weights, offsets):
            grad += w * self.noise[off:off + dim]
        grad /= len(offsets) * cfg["noise_std"]
        self.flat = self.flat + cfg["step_size"] * grad
        self.policy.set_weights(_unflatten(self.flat, self._spec))
        self._episodes_total += 2 * len(offsets)
        metrics = {
            "episode_reward_mean": float(np.mean(np.concatenate(
                [pos, neg]))),
            "episode_reward_max": float(max(pos.max(), neg.max())),
            "episodes_total": self._episodes_total,
            "grad_norm": float(np.linalg.norm(grad)),
        }
        interval = cfg.get("evaluation_interval") or 0
        if interval and (self.iteration + 1) % interval == 0:
            metrics["evaluation"] = self.evaluate()
        return metrics

    def get_policy(self, policy_id=None):
        return self.policy

    def save_checkpoint(self, checkpoint_dir: str):
        return {"flat": self.flat,
                "episodes_total": self._episodes_total}

    def load_checkpoint(self, state):
        self.flat = state["flat"]
        self._episodes_total = state.get("episodes_total", 0)
        self.policy.set_weights(_unflatten(self.flat, self._spec))

    def cleanup(self):
        try:
            ray_tpu.get([w.stop.remote() for w in self.workers],
                        timeout=30)
        except Exception:
            pass
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
