"""V-trace off-policy correction (IMPALA; reference:
rllib/agents/impala/vtrace_torch.py — the algorithm, not the code: here
it is a single backwards `lax.scan`, which XLA compiles into one fused
loop on TPU instead of the reference's per-timestep python/torch loop).

Shapes are time-major [T, B] (B = trajectory fragments)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def vtrace_returns(behaviour_logp, target_logp, discounts, rewards, values,
                   bootstrap_value, clip_rho: float = 1.0,
                   clip_pg_rho: float = 1.0):
    """Compute v-trace targets vs and policy-gradient advantages.

    Args (all [T, B] except bootstrap_value [B]):
        behaviour_logp: log pi_b(a_t|x_t) from the actor that sampled.
        target_logp:    log pi(a_t|x_t) under the learner's params.
        discounts:      gamma * (1 - done_t).
        rewards, values: r_t, V(x_t).
        bootstrap_value: V(x_{T}) for the step after the fragment.
    Returns (vs, pg_advantages), both [T, B], gradient-stopped.
    """
    rhos = jnp.exp(target_logp - behaviour_logp)
    clipped_rhos = jnp.minimum(clip_rho, rhos)
    cs = jnp.minimum(1.0, rhos)

    values_t_plus_1 = jnp.concatenate(
        [values[1:], bootstrap_value[None]], axis=0)
    deltas = clipped_rhos * (
        rewards + discounts * values_t_plus_1 - values)

    def backward(acc, xs):
        delta, discount, c = xs
        acc = delta + discount * c * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        backward, jnp.zeros_like(bootstrap_value),
        (deltas, discounts, cs), reverse=True)
    vs = vs_minus_v + values

    vs_t_plus_1 = jnp.concatenate([vs[1:], bootstrap_value[None]], axis=0)
    clipped_pg_rhos = jnp.minimum(clip_pg_rho, rhos)
    pg_advantages = clipped_pg_rhos * (
        rewards + discounts * vs_t_plus_1 - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_advantages)
