"""QMIX — cooperative multi-agent Q-learning with monotonic value
factorization (reference: rllib/agents/qmix/qmix.py + qmix_policy.py;
Rashid et al. 2018).

Agents share one Q network (agent-id one-hot appended to the local
observation, the standard parameter-sharing setup) and a hypernetwork
mixer combines per-agent chosen-action Q values into Q_tot conditioned
on the global state (concatenated observations), with abs() on the
mixing weights enforcing monotonicity — so per-agent greedy argmax is
also the Q_tot greedy joint action. One jitted TD step trains agent net
and mixer end-to-end on the TEAM reward.

QMIX needs TIME-ALIGNED joint transitions, which the per-agent
MultiAgentBatch can't express — so this trainer runs its own joint
sampler over the dict-style multi-agent env (fixed agent set)."""

from __future__ import annotations

import numpy as np

from ray_tpu.rllib.agents.trainer import COMMON_CONFIG, Trainer
from ray_tpu.rllib.execution.replay_buffer import ReplayBuffer
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.policy.jax_policy import _mlp_apply, _mlp_init
from ray_tpu.rllib.policy.policy import Policy

QMIX_CONFIG = {
    **COMMON_CONFIG,
    "rollout_fragment_length": 32,
    "train_batch_size": 64,
    "buffer_size": 20_000,
    "learning_starts": 500,
    "sgd_rounds_per_step": 8,
    "target_network_update_freq": 400,
    "mixing_embed_dim": 32,
    "lr": 5e-4,
    "exploration_initial_eps": 1.0,
    "exploration_final_eps": 0.05,
    "total_timesteps_anneal": 10_000,
    "exploration_fraction": 0.4,
}


class QMixPolicy(Policy):
    """Shared agent Q net + hypernetwork mixer, one pytree."""

    def __init__(self, observation_space, action_space, config: dict,
                 n_agents: int):
        import jax
        import jax.numpy as jnp
        import optax

        merged = {**QMIX_CONFIG, **config}
        super().__init__(observation_space, action_space, merged)
        if not hasattr(action_space, "n"):
            raise ValueError("QMIX is discrete-action only")
        self.discrete = True
        self.n_agents = n_agents
        obs_dim = int(np.prod(observation_space.shape))
        self._obs_dim = obs_dim
        n_act = int(action_space.n)
        self._n_act = n_act
        state_dim = obs_dim * n_agents
        hiddens = list(merged.get("fcnet_hiddens", [64, 64]))
        embed = merged["mixing_embed_dim"]
        seed = merged.get("seed") or 0
        keys = jax.random.split(jax.random.key(seed), 6)
        self.params = {
            # shared agent net over [obs ⊕ one-hot agent id]
            "agent": _mlp_init(keys[0],
                               [obs_dim + n_agents] + hiddens + [n_act]),
            # hypernets: state -> mixing weights/biases (abs for
            # monotonicity applied at use time)
            "hw1": _mlp_init(keys[1], [state_dim, n_agents * embed]),
            "hb1": _mlp_init(keys[2], [state_dim, embed]),
            "hw2": _mlp_init(keys[3], [state_dim, embed]),
            "hb2": _mlp_init(keys[4], [state_dim, embed, 1]),
        }
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self._optimizer = optax.adam(merged["lr"])
        self.opt_state = self._optimizer.init(self.params)
        self.eps = float(merged.get("exploration_initial_eps", 1.0))
        self._rng = np.random.RandomState(seed + 17)
        self._eye = np.eye(n_agents, dtype=np.float32)
        self._build()

    # -- nets ------------------------------------------------------------

    @staticmethod
    def _agent_q(params, obs_id):
        """[B, n, obs+n] -> [B, n, n_act]."""
        return _mlp_apply(params["agent"], obs_id)

    @staticmethod
    def _mix(params, q_chosen, state):
        """Monotonic mixer: q_chosen [B, n], state [B, s] -> [B]."""
        import jax.numpy as jnp

        b, n = q_chosen.shape
        embed_w1 = jnp.abs(_mlp_apply(params["hw1"], state))
        w1 = embed_w1.reshape(b, n, -1)
        b1 = _mlp_apply(params["hb1"], state)
        hidden = jnp.einsum("bn,bne->be", q_chosen, w1) + b1
        hidden = jnp.where(hidden > 0, hidden, 0.01 * hidden)  # elu-ish
        w2 = jnp.abs(_mlp_apply(params["hw2"], state))
        b2 = _mlp_apply(params["hb2"], state)[:, 0]
        return jnp.einsum("be,be->b", hidden, w2) + b2

    def _build(self):
        import jax
        import jax.numpy as jnp

        gamma = self.config.get("gamma", 0.99)
        optimizer = self._optimizer
        n = self.n_agents

        @jax.jit
        def q_values(params, obs_id):
            return QMixPolicy._agent_q(params, obs_id)

        def loss_fn(params, target_params, batch):
            obs_id = batch["obs_id"]          # [B, n, obs+n]
            next_obs_id = batch["next_obs_id"]
            state = batch["state"]            # [B, s]
            next_state = batch["next_state"]
            acts = batch["actions"]           # [B, n] int32
            q_all = QMixPolicy._agent_q(params, obs_id)
            q_chosen = jnp.take_along_axis(
                q_all, acts[..., None], axis=-1)[..., 0]  # [B, n]
            q_tot = QMixPolicy._mix(params, q_chosen, state)
            q_next = QMixPolicy._agent_q(target_params, next_obs_id)
            q_next_max = q_next.max(axis=-1)  # [B, n]
            q_tot_next = QMixPolicy._mix(target_params, q_next_max,
                                         next_state)
            y = jax.lax.stop_gradient(
                batch["rewards"] + gamma * (1.0 - batch["dones"])
                * q_tot_next)
            td = q_tot - y
            return (td ** 2).mean(), {"td_mean_abs": jnp.abs(td).mean()}

        @jax.jit
        def train(params, target_params, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, batch)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = jax.tree.map(lambda p, u: p + u, params, updates)
            return params, opt_state, loss, metrics

        self._q_values = q_values
        self._train = train

    # -- acting ----------------------------------------------------------

    def _obs_with_ids(self, obs_rows: np.ndarray) -> np.ndarray:
        """[B, n, obs] -> [B, n, obs+n] with agent one-hots appended."""
        b = obs_rows.shape[0]
        ids = np.broadcast_to(self._eye, (b, *self._eye.shape))
        return np.concatenate([obs_rows, ids], axis=-1).astype(np.float32)

    def compute_joint_actions(self, obs_rows: np.ndarray,
                              explore: bool = True) -> np.ndarray:
        """obs_rows [B, n, obs] -> actions [B, n] (eps-greedy)."""
        q = np.asarray(self._q_values(self.params,
                                      self._obs_with_ids(obs_rows)))
        acts = q.argmax(axis=-1)
        if explore and self.eps > 0:
            rand = self._rng.randint(0, self._n_act, acts.shape)
            mask = self._rng.rand(*acts.shape) < self.eps
            acts = np.where(mask, rand, acts)
        return acts.astype(np.int64)

    def compute_actions(self, obs_batch, explore: bool = True):
        # Policy-surface adapter: rows are per-agent observations of a
        # SINGLE timestep (used by evaluate()); greedy per-agent argmax
        # is Q_tot-greedy by monotonicity
        obs = np.asarray(obs_batch, np.float32).reshape(
            1, len(obs_batch), -1)
        acts = self.compute_joint_actions(obs, explore)[0]
        from ray_tpu.rllib.policy.sample_batch import SampleBatch

        return acts, {SampleBatch.ACTION_LOGP: np.zeros(len(obs_batch)),
                      SampleBatch.VF_PREDS: np.zeros(len(obs_batch))}

    def set_epsilon(self, eps: float):
        self.eps = float(eps)

    def update_target(self):
        import jax
        import jax.numpy as jnp

        self.target_params = jax.tree.map(jnp.copy, self.params)

    def learn_on_joint_batch(self, batch: dict) -> dict:
        import jax.numpy as jnp

        jb = {
            "obs_id": jnp.asarray(self._obs_with_ids(batch["obs"])),
            "next_obs_id": jnp.asarray(
                self._obs_with_ids(batch["next_obs"])),
            "state": jnp.asarray(batch["obs"].reshape(
                len(batch["obs"]), -1), jnp.float32),
            "next_state": jnp.asarray(batch["next_obs"].reshape(
                len(batch["next_obs"]), -1), jnp.float32),
            "actions": jnp.asarray(batch["actions"], jnp.int32),
            "rewards": jnp.asarray(batch["rewards"], jnp.float32),
            "dones": jnp.asarray(batch["dones"], jnp.float32),
        }
        self.params, self.opt_state, loss, metrics = self._train(
            self.params, self.target_params, self.opt_state, jb)
        out = {"total_loss": float(loss)}
        out.update({k: float(v) for k, v in metrics.items()})
        return out

    def get_weights(self):
        import jax

        return {"params": jax.tree.map(np.asarray, self.params),
                "target": jax.tree.map(np.asarray, self.target_params),
                "eps": self.eps}

    def set_weights(self, weights):
        import jax
        import jax.numpy as jnp

        self.params = jax.tree.map(jnp.asarray, weights["params"])
        self.target_params = jax.tree.map(jnp.asarray, weights["target"])
        self.eps = weights["eps"]


class QMixTrainer(Trainer):
    """reference: rllib/agents/qmix/qmix.py execution plan, with a joint
    sampler instead of per-agent batches."""

    _default_config = QMIX_CONFIG
    _name = "QMIX"
    _supports_multiagent = True  # it IS the multi-agent trainer

    def setup(self, config):
        if config.get("env") is None:
            raise ValueError("config['env'] must be set")
        self.env = make_env(config["env"], config.get("env_config", {}))
        seed = config.get("seed")
        obs, _ = self.env.reset(seed=seed)
        self._agent_ids = sorted(obs.keys())
        self._obs = obs
        self.policy = QMixPolicy(
            self.env.observation_space, self.env.action_space, config,
            n_agents=len(self._agent_ids))
        # time-aligned JOINT transitions ride the standard ring buffer:
        # each env step is a one-row SampleBatch whose columns carry the
        # [n_agents, ...] joint arrays
        self._buffer = ReplayBuffer(config["buffer_size"], seed=seed)
        self._timesteps = 0
        self._last_target_update = 0
        self._episode_reward = 0.0
        self._completed: list[float] = []

    def _rows(self, obs_dict) -> np.ndarray:
        return np.stack([np.asarray(obs_dict[a], np.float32).ravel()
                         for a in self._agent_ids])

    def _epsilon(self) -> float:
        from ray_tpu.rllib.agents.dqn import linear_epsilon

        return linear_epsilon(self.config, self._timesteps)

    def train_step(self) -> dict:
        cfg = self.config
        self.policy.set_epsilon(self._epsilon())
        for _ in range(cfg["rollout_fragment_length"]):
            rows = self._rows(self._obs)
            acts = self.policy.compute_joint_actions(rows[None])[0]
            action_dict = {a: int(acts[i])
                           for i, a in enumerate(self._agent_ids)}
            next_obs, rewards, terminated, truncated, _ = self.env.step(
                action_dict)
            done = bool(terminated.get("__all__")
                        or truncated.get("__all__"))
            team_r = float(sum(rewards.values()))
            self._episode_reward += team_r
            terminal = float(bool(terminated.get("__all__")))
            if done and not next_obs:
                # no further obs: next_rows is a placeholder, so the TD
                # target must NOT bootstrap from it — a truncated episode
                # (terminated=0) would otherwise bootstrap from the
                # CURRENT obs, biasing Q toward self-consistent loops
                next_rows = rows
                terminal = 1.0
            elif set(next_obs) >= set(self._agent_ids):
                next_rows = self._rows(next_obs)
            else:
                raise ValueError(
                    "QMIX requires a FIXED agent set every step; env "
                    f"returned obs for {sorted(next_obs)} but the "
                    f"episode declares agents {self._agent_ids} "
                    "(early-exiting agents are not supported)")
            from ray_tpu.rllib.policy.sample_batch import SampleBatch

            self._buffer.add_batch(SampleBatch({
                "obs": rows[None], "next_obs": next_rows[None],
                "actions": acts[None],
                "rewards": np.array([team_r], np.float32),
                "dones": np.array([terminal], np.float32),
            }))
            self._timesteps += 1
            if done:
                self._completed.append(self._episode_reward)
                self._episode_reward = 0.0
                next_obs, _ = self.env.reset()
            self._obs = next_obs
        metrics = {"timesteps_total": self._timesteps,
                   "epsilon": round(self.policy.eps, 4),
                   "buffer_size": len(self._buffer)}
        if len(self._buffer) >= cfg["learning_starts"]:
            for _ in range(cfg["sgd_rounds_per_step"]):
                metrics.update(self.policy.learn_on_joint_batch(
                    self._buffer.sample(cfg["train_batch_size"])))
            if (self._timesteps - self._last_target_update
                    >= cfg["target_network_update_freq"]):
                self._last_target_update = self._timesteps
                self.policy.update_target()
        return metrics

    def step(self) -> dict:
        metrics = self.train_step()
        if self._completed:
            metrics["episode_reward_mean"] = float(
                np.mean(self._completed[-50:]))
            metrics["episodes_total"] = len(self._completed)
        interval = self.config.get("evaluation_interval") or 0
        if interval and (self.iteration + 1) % interval == 0:
            metrics["evaluation"] = self.evaluate()
        return metrics

    def get_policy(self, policy_id=None):
        return self.policy

    def evaluate(self, num_episodes: int | None = None) -> dict:
        """Greedy joint-policy episodes on a fresh env (the base
        Trainer's evaluate() assumes a WorkerSet this trainer doesn't
        have)."""
        n = (self.config.get("evaluation_num_episodes", 5)
             if num_episodes is None else num_episodes)
        env = make_env(self.config["env"],
                       self.config.get("env_config", {}))
        rewards, lengths = [], []
        try:
            for _ in range(n):
                obs, _ = env.reset()
                total, steps, done = 0.0, 0, False
                while not done and steps < 10_000:
                    rows = self._rows(obs)[None]
                    acts = self.policy.compute_joint_actions(
                        rows, explore=False)[0]
                    obs, rew, term, trunc, _ = env.step(
                        {a: int(acts[i])
                         for i, a in enumerate(self._agent_ids)})
                    total += float(sum(rew.values()))
                    steps += 1
                    done = bool(term.get("__all__")
                                or trunc.get("__all__"))
                rewards.append(total)
                lengths.append(steps)
        finally:
            try:
                env.close()
            except Exception:
                pass
        return {"episode_reward_mean": float(np.mean(rewards)),
                "episode_len_mean": float(np.mean(lengths)),
                "episodes": n}

    def compute_action(self, obs, explore: bool = False):
        """Joint action for one timestep's obs dict -> action dict."""
        if not isinstance(obs, dict):
            raise ValueError(
                "QMIX acts jointly: pass the env's obs dict "
                "({agent_id: obs}); per-agent scalars have no meaning "
                "through the mixer")
        acts = self.policy.compute_joint_actions(
            self._rows(obs)[None], explore=explore)[0]
        return {a: int(acts[i]) for i, a in enumerate(self._agent_ids)}

    def save_checkpoint(self, checkpoint_dir):
        return {"weights": self.policy.get_weights()}

    def load_checkpoint(self, state):
        self.policy.set_weights(state["weights"])

    def cleanup(self):
        try:
            self.env.close()
        except Exception:
            pass
