"""A3C — asynchronous advantage actor-critic (reference:
rllib/agents/a3c/a3c.py execution_plan = AsyncGradients → ApplyGradients,
a3c_torch_policy.py loss).

Execution shape: each rollout actor samples a fragment, computes
gradients *locally* (stale weights are the point of A3C), ships them to
the learner which applies them and sends fresh weights back to just that
worker — no barrier across workers (reference:
rllib/execution/rollout_ops.py:92 AsyncGradients)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

import ray_tpu
from ray_tpu.rllib.agents.pg import discounted_returns
from ray_tpu.rllib.agents.trainer import build_trainer
from ray_tpu.rllib.policy.jax_policy import JAXPolicy
from ray_tpu.rllib.policy.sample_batch import SampleBatch

A3C_CONFIG: dict = {
    "rollout_fragment_length": 64,
    "num_workers": 2,
    "lr": 1e-3,
    "gamma": 0.99,
    "vf_loss_coeff": 0.5,
    "entropy_coeff": 0.01,
    # gradient applications per Trainable.step() call
    "grads_per_step": 16,
}


class A3CPolicy(JAXPolicy):
    def __init__(self, observation_space, action_space, config):
        merged = {**A3C_CONFIG, **config}
        super().__init__(observation_space, action_space, merged,
                         loss_fn=a3c_loss)

    def postprocess_trajectory(self, batch, other_agent_batches=None,
                               episode=None):
        out = []
        for eb in batch.split_by_episode():
            if eb[SampleBatch.DONES][-1]:
                last_value = 0.0
            else:
                last_value = float(self.compute_values(
                    eb[SampleBatch.NEXT_OBS][-1:])[0])
            returns = discounted_returns(
                eb[SampleBatch.REWARDS].astype(np.float64),
                eb[SampleBatch.DONES].astype(np.float64),
                self.config["gamma"], last_value)
            eb[SampleBatch.VALUE_TARGETS] = returns
            eb[SampleBatch.ADVANTAGES] = (
                returns - eb[SampleBatch.VF_PREDS]).astype(np.float32)
            out.append(eb)
        return SampleBatch.concat_samples(out)


def a3c_loss(params, batch, policy: A3CPolicy):
    """reference: a3c_torch_policy.py actor_critic_loss."""
    cfg = policy.config
    pi_out, values = JAXPolicy.model_out(
        params, batch[SampleBatch.OBS].astype(jnp.float32))
    logp = policy.logp_fn()(pi_out, batch[SampleBatch.ACTIONS])
    entropy = policy.entropy_fn()(pi_out).mean()
    pi_loss = -(logp * batch[SampleBatch.ADVANTAGES]).mean()
    vf_loss = ((values - batch[SampleBatch.VALUE_TARGETS]) ** 2).mean()
    total = (pi_loss + cfg["vf_loss_coeff"] * vf_loss
             - cfg["entropy_coeff"] * entropy)
    return total, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                   "entropy": entropy}


def a3c_train_step(workers, config) -> dict:
    """Async gradients: wait for any worker's grads, apply on the learner,
    refresh only that worker, immediately relaunch it."""
    policy = workers.local_worker.policy
    metrics: dict = {}
    trained = 0

    if not workers.remote_workers:
        # degenerate single-process mode: synchronous A2C-style steps
        for _ in range(config["grads_per_step"]):
            batch = workers.local_worker.sample(
                config["rollout_fragment_length"])
            grads, metrics = policy.compute_gradients(batch)
            policy.apply_gradients(grads)
            trained += metrics.pop("batch_count", len(batch))
        metrics["num_env_steps_trained"] = trained
        return metrics

    frag = config["rollout_fragment_length"]
    inflight = {
        w.sample_and_gradients.remote(frag): w
        for w in workers.remote_workers
    }
    applied = 0
    while applied < config["grads_per_step"]:
        ready, _ = ray_tpu.wait(list(inflight), num_returns=1, timeout=300)
        if not ready:
            raise TimeoutError(
                f"A3C: no gradients from {len(inflight)} rollout workers "
                "within 300s (worker hung or dead?)")
        ref = ready[0]
        worker = inflight.pop(ref)
        grads, info = ray_tpu.get(ref)
        policy.apply_gradients(grads)
        trained += info.pop("batch_count", 0)
        metrics = info
        applied += 1
        worker.set_weights.remote(policy.get_weights())
        inflight[worker.sample_and_gradients.remote(frag)] = worker
    # drain stragglers so next step starts clean (one shared timeout)
    try:
        ray_tpu.get(list(inflight), timeout=300)
    except Exception:
        pass
    metrics["num_env_steps_trained"] = trained
    metrics["grads_applied"] = applied
    return metrics


A3CTrainer = build_trainer("A3C", A3C_CONFIG, A3CPolicy, a3c_train_step)
