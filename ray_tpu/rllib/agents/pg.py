"""Vanilla policy gradient / REINFORCE (reference: rllib/agents/pg/pg.py
+ pg_torch_policy.py pg_torch_loss): loss = -logp(a|s) * R_t with
discounted Monte-Carlo returns computed in postprocessing."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.agents.trainer import build_trainer
from ray_tpu.rllib.policy.jax_policy import JAXPolicy
from ray_tpu.rllib.policy.sample_batch import SampleBatch

PG_CONFIG: dict = {
    "rollout_fragment_length": 200,
    "train_batch_size": 1000,
    "lr": 1e-3,
    "gamma": 0.99,
}


def discounted_returns(rewards: np.ndarray, dones: np.ndarray,
                       gamma: float, last_value: float = 0.0) -> np.ndarray:
    """reference: rllib/evaluation/postprocessing.py discount_cumsum."""
    out = np.zeros(len(rewards))
    running = last_value
    for t in range(len(rewards) - 1, -1, -1):
        running = rewards[t] + gamma * running * (1.0 - dones[t])
        out[t] = running
    return out.astype(np.float32)


class PGPolicy(JAXPolicy):
    def __init__(self, observation_space, action_space, config):
        merged = {**PG_CONFIG, **config}
        super().__init__(observation_space, action_space, merged,
                         loss_fn=pg_loss)

    def postprocess_trajectory(self, batch, other_agent_batches=None,
                               episode=None):
        out = []
        for eb in batch.split_by_episode():
            if eb[SampleBatch.DONES][-1]:
                last_value = 0.0
            else:
                # bootstrap truncated tails so fragment boundaries don't
                # bias returns toward zero
                last_value = float(self.compute_values(
                    eb[SampleBatch.NEXT_OBS][-1:])[0])
            eb[SampleBatch.ADVANTAGES] = discounted_returns(
                eb[SampleBatch.REWARDS].astype(np.float64),
                eb[SampleBatch.DONES].astype(np.float64),
                self.config["gamma"], last_value)
            out.append(eb)
        return SampleBatch.concat_samples(out)


def pg_loss(params, batch, policy: PGPolicy):
    pi_out, _ = JAXPolicy.model_out(
        params, batch[SampleBatch.OBS].astype(jnp.float32))
    logp = policy.logp_fn()(pi_out, batch[SampleBatch.ACTIONS])
    returns = batch[SampleBatch.ADVANTAGES]
    returns = (returns - returns.mean()) / (returns.std() + 1e-8)
    loss = -(logp * returns).mean()
    return loss, {"policy_loss": loss}


def pg_train_step(workers, config) -> dict:
    target = config["train_batch_size"]
    batches, collected = [], 0
    while collected < target:
        b = workers.sample(config["rollout_fragment_length"])
        batches.append(b)
        collected += len(b)
    batch = SampleBatch.concat_samples(batches)
    metrics = workers.local_worker.learn_on_batch(batch)
    workers.sync_weights()
    metrics["num_env_steps_trained"] = len(batch)
    return metrics


PGTrainer = build_trainer("PG", PG_CONFIG, PGPolicy, pg_train_step)


def _recurrent_pg_policy(obs_space, action_space, config):
    from ray_tpu.rllib.policy.recurrent_policy import RecurrentPGPolicy

    return RecurrentPGPolicy(obs_space, action_space,
                             {**PG_CONFIG, **config})


# LSTM actor-critic for partially-observable envs (reference:
# models/tf/recurrent_net.py + any use_lstm=True agent); same execution
# plan as PG — whole-batch updates keep sequences intact.
RecurrentPGTrainer = build_trainer("RecurrentPG", PG_CONFIG,
                                   _recurrent_pg_policy, pg_train_step)
