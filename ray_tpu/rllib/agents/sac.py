"""Soft actor-critic (reference: rllib/agents/sac/sac.py +
sac_torch_policy.py — Haarnoja et al.): off-policy continuous control
with twin Q critics, a squashed-Gaussian actor, learned temperature
against a target entropy, and polyak-averaged target critics.

Execution shape mirrors the DQN family here: rollout actors fill a
replay buffer, the (TPU-hostable) learner runs one fused jitted update
per minibatch — actor, both critics, and temperature step in a single
jit with donated state."""

from __future__ import annotations

import math

import numpy as np

from ray_tpu.rllib.agents.trainer import Trainer
from ray_tpu.rllib.execution.replay_buffer import ReplayBuffer
from ray_tpu.rllib.policy.jax_policy import (JAXPolicy, _mlp_apply,
                                             _mlp_init)
from ray_tpu.rllib.policy.policy import Policy
from ray_tpu.rllib.policy.sample_batch import SampleBatch

SAC_CONFIG: dict = {
    "rollout_fragment_length": 64,
    "learning_starts": 500,
    "buffer_size": 100_000,
    "train_batch_size": 128,
    "sgd_iters_per_step": 32,
    "gamma": 0.99,
    "tau": 0.01,                 # polyak coefficient
    "lr": 3e-4,
    "initial_alpha": 0.2,
    "target_entropy": None,      # default: -act_dim
    "fcnet_hiddens": [64, 64],
}

_LOG_STD_MIN, _LOG_STD_MAX = -10.0, 2.0


class SACPolicy(Policy):
    """Squashed-Gaussian actor + twin Q critics, all as one pytree."""

    def __init__(self, observation_space, action_space, config: dict):
        import jax
        import jax.numpy as jnp
        import optax

        merged = {**SAC_CONFIG, **config}
        super().__init__(observation_space, action_space, merged)
        if hasattr(action_space, "n"):
            raise ValueError("SAC here is continuous-control only; use "
                             "DQN for discrete actions")
        self.discrete = False
        obs_dim = int(np.prod(observation_space.shape))
        act_dim = int(np.prod(action_space.shape))
        self._act_dim = act_dim
        self._act_scale = (action_space.high - action_space.low) / 2.0
        self._act_mid = (action_space.high + action_space.low) / 2.0
        hiddens = list(merged.get("fcnet_hiddens", [64, 64]))
        seed = merged.get("seed") or 0
        keys = jax.random.split(jax.random.key(seed), 4)
        q_sizes = [obs_dim + act_dim] + hiddens + [1]
        self.params = {
            "pi": _mlp_init(keys[0], [obs_dim] + hiddens + [2 * act_dim]),
            "q1": _mlp_init(keys[1], q_sizes),
            "q2": _mlp_init(keys[2], q_sizes),
            "log_alpha": jnp.asarray(
                math.log(merged["initial_alpha"]), jnp.float32),
        }
        self.target = {"q1": jax.tree.map(lambda x: x, self.params["q1"]),
                       "q2": jax.tree.map(lambda x: x, self.params["q2"])}
        self._target_entropy = (merged["target_entropy"]
                                if merged["target_entropy"] is not None
                                else -float(act_dim))
        self._optimizer = optax.adam(merged["lr"])
        self.opt_state = self._optimizer.init(self.params)
        self._rng = jax.random.key(seed + 1)
        self._build()

    # -- nets ------------------------------------------------------------

    @staticmethod
    def _pi_dist(params, obs):
        import jax.numpy as jnp

        out = _mlp_apply(params["pi"], obs)
        mean, log_std = jnp.split(out, 2, axis=-1)
        log_std = jnp.clip(log_std, _LOG_STD_MIN, _LOG_STD_MAX)
        return mean, log_std

    @staticmethod
    def _sample_squashed(params, obs, key):
        """-> (action in [-1,1], logp) with tanh-squash correction."""
        import jax
        import jax.numpy as jnp

        mean, log_std = SACPolicy._pi_dist(params, obs)
        std = jnp.exp(log_std)
        raw = mean + std * jax.random.normal(key, mean.shape)
        logp = jnp.sum(
            -0.5 * ((raw - mean) / std) ** 2 - log_std
            - 0.5 * math.log(2 * math.pi), axis=-1)
        act = jnp.tanh(raw)
        # change of variables for tanh (stable form)
        logp -= jnp.sum(2.0 * (math.log(2.0) - raw
                               - jax.nn.softplus(-2.0 * raw)), axis=-1)
        return act, logp

    @staticmethod
    def _q(params_q, obs, act):
        import jax.numpy as jnp

        return _mlp_apply(params_q, jnp.concatenate([obs, act], -1))[:, 0]

    def _build(self):
        import jax
        import jax.numpy as jnp

        gamma = self.config["gamma"]
        tau = self.config["tau"]
        target_entropy = self._target_entropy
        optimizer = self._optimizer

        @jax.jit
        def act(params, obs, key):
            a, _ = SACPolicy._sample_squashed(params, obs, key)
            return a

        @jax.jit
        def act_greedy(params, obs):
            mean, _ = SACPolicy._pi_dist(params, obs)
            return jnp.tanh(mean)

        def loss_fn(params, target, batch, key):
            obs = batch["obs"]
            nxt = batch["new_obs"]
            k1, k2 = jax.random.split(key)
            alpha = jnp.exp(params["log_alpha"])
            # critic targets from the target nets + fresh next actions
            a2, logp2 = SACPolicy._sample_squashed(params, nxt, k2)
            q_next = jnp.minimum(
                SACPolicy._q(target["q1"], nxt, a2),
                SACPolicy._q(target["q2"], nxt, a2))
            backup = batch["rewards"] + gamma * (1 - batch["dones"]) * (
                q_next - jax.lax.stop_gradient(alpha) * logp2)
            backup = jax.lax.stop_gradient(backup)
            q1 = SACPolicy._q(params["q1"], obs, batch["actions"])
            q2 = SACPolicy._q(params["q2"], obs, batch["actions"])
            critic_loss = ((q1 - backup) ** 2).mean() + (
                (q2 - backup) ** 2).mean()
            # actor: maximize min-Q of reparameterized action - alpha*logp
            a_new, logp_new = SACPolicy._sample_squashed(params, obs, k1)
            q_new = jnp.minimum(
                SACPolicy._q(jax.lax.stop_gradient(params["q1"]), obs,
                             a_new),
                SACPolicy._q(jax.lax.stop_gradient(params["q2"]), obs,
                             a_new))
            actor_loss = (jax.lax.stop_gradient(alpha) * logp_new
                          - q_new).mean()
            # temperature toward target entropy
            alpha_loss = (-jnp.exp(params["log_alpha"])
                          * jax.lax.stop_gradient(
                              logp_new + target_entropy)).mean()
            total = critic_loss + actor_loss + alpha_loss
            return total, {"critic_loss": critic_loss,
                           "actor_loss": actor_loss,
                           "alpha": alpha}

        @jax.jit
        def update(params, target, opt_state, batch, key):
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target, batch, key)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = jax.tree.map(lambda p, u: p + u, params, updates)
            target = jax.tree.map(
                lambda t, p: (1 - tau) * t + tau * p, target,
                {"q1": params["q1"], "q2": params["q2"]})
            return params, target, opt_state, loss, metrics

        self._act = act
        self._act_greedy = act_greedy
        self._update = update

    # -- Policy surface --------------------------------------------------

    def compute_actions(self, obs_batch, explore: bool = True):
        import jax
        import jax.numpy as jnp

        obs = jnp.asarray(obs_batch, jnp.float32).reshape(
            len(obs_batch), -1)
        if explore:
            self._rng, sub = jax.random.split(self._rng)
            act = self._act(self.params, obs, sub)
        else:
            act = self._act_greedy(self.params, obs)
        scaled = np.asarray(act) * self._act_scale + self._act_mid
        return scaled, {SampleBatch.ACTION_LOGP: np.zeros(len(obs_batch)),
                        SampleBatch.VF_PREDS: np.zeros(len(obs_batch))}

    def postprocess_trajectory(self, batch, other_agent_batches=None,
                               episode=None):
        return batch

    def learn_on_batch(self, batch: SampleBatch) -> dict:
        import jax
        import jax.numpy as jnp

        # actions come back in env scale; train in squashed [-1,1]
        norm_act = ((batch[SampleBatch.ACTIONS] - self._act_mid)
                    / self._act_scale)
        jb = {
            "obs": jnp.asarray(batch[SampleBatch.OBS], jnp.float32),
            "new_obs": jnp.asarray(batch[SampleBatch.NEXT_OBS],
                                   jnp.float32),
            "actions": jnp.asarray(
                np.clip(norm_act, -0.999, 0.999), jnp.float32),
            "rewards": jnp.asarray(batch[SampleBatch.REWARDS],
                                   jnp.float32),
            "dones": jnp.asarray(batch[SampleBatch.DONES], jnp.float32),
        }
        self._rng, sub = jax.random.split(self._rng)
        (self.params, self.target, self.opt_state, loss,
         metrics) = self._update(self.params, self.target,
                                 self.opt_state, jb, sub)
        out = {"total_loss": float(loss)}
        out.update({k: float(v) for k, v in metrics.items()})
        return out

    def get_weights(self):
        import jax

        return {"params": jax.tree.map(np.asarray, self.params),
                "target": jax.tree.map(np.asarray, self.target)}

    def set_weights(self, weights):
        import jax
        import jax.numpy as jnp

        self.params = jax.tree.map(jnp.asarray, weights["params"])
        self.target = jax.tree.map(jnp.asarray, weights["target"])


class SACTrainer(Trainer):
    """reference: rllib/agents/sac/sac.py execution plan (store →
    replay → fused train), same shape as the DQN family here."""

    _default_config = SAC_CONFIG
    _name = "SAC"

    @staticmethod
    def policy_builder(obs_space, action_space, config):
        return SACPolicy(obs_space, action_space, config)

    def setup(self, config):
        super().setup(config)
        self._buffer = ReplayBuffer(config["buffer_size"],
                                    seed=config.get("seed"))

    def train_step(self) -> dict:
        config = self.config
        batch = self.workers.sample(config["rollout_fragment_length"])
        self._buffer.add_batch(batch)
        metrics: dict = {"buffer_size": len(self._buffer)}
        if len(self._buffer) >= config["learning_starts"]:
            policy = self.workers.local_worker.policy
            for _ in range(config["sgd_iters_per_step"]):
                replay = self._buffer.sample(config["train_batch_size"])
                metrics.update(policy.learn_on_batch(replay))
            self.workers.sync_weights()
        metrics["num_env_steps_sampled"] = len(batch)
        return metrics
