"""IMPALA — importance-weighted actor-learner architecture (reference:
rllib/agents/impala/impala.py + execution/learner_thread.py:16; algorithm:
Espeholt et al. 2018).

Architecture here: CPU rollout actors sample continuously and
asynchronously (each completed fragment immediately triggers the next
sample call with refreshed weights — no synchronous barrier), a
LearnerThread drains fragments into the jitted V-trace SGD step so env
stepping and device compute overlap, and the V-trace correction itself is
one fused `lax.scan` (vtrace.py) — the TPU-idiomatic replacement for the
reference's torch per-timestep loop."""

from __future__ import annotations

import time

import jax.numpy as jnp

import ray_tpu
from ray_tpu.rllib.agents.trainer import COMMON_CONFIG, Trainer
from ray_tpu.rllib.agents.vtrace import vtrace_returns
from ray_tpu.rllib.execution.learner_thread import LearnerThread
from ray_tpu.rllib.policy.jax_policy import JAXPolicy
from ray_tpu.rllib.policy.sample_batch import SampleBatch

IMPALA_CONFIG = {
    **COMMON_CONFIG,
    "num_workers": 2,
    "num_envs_per_worker": 1,
    "rollout_fragment_length": 50,
    "train_batch_size": 500,
    "lr": 5e-4,
    "entropy_coeff": 0.01,
    "vf_loss_coeff": 0.5,
    "vtrace_clip_rho_threshold": 1.0,
    "vtrace_clip_pg_rho_threshold": 1.0,
    "broadcast_interval": 1,   # fragments between weight refreshes
    "learner_queue_size": 16,
}


def impala_loss(params, batch, policy):
    """V-trace actor-critic loss over time-major [T, B] fragments."""
    cfg = policy.config
    n_envs = int(cfg.get("num_envs_per_worker", 1))
    obs = batch[SampleBatch.OBS]
    n = obs.shape[0]
    b, t = n_envs, n // n_envs

    def tm(x):
        # env-major flat [B*T, ...] -> time-major [T, B, ...]
        return x.reshape(b, t, *x.shape[1:]).swapaxes(0, 1)

    pi_out, values = JAXPolicy.model_out(params, obs.reshape(n, -1))
    target_logp = policy.logp_fn()(pi_out, batch[SampleBatch.ACTIONS])
    entropy = policy.entropy_fn()(pi_out).mean()

    dones = tm(batch[SampleBatch.DONES].astype(jnp.float32))
    discounts = cfg.get("gamma", 0.99) * (1.0 - dones)
    last_next_obs = tm(batch[SampleBatch.NEXT_OBS])[-1]
    _, bootstrap_v = JAXPolicy.model_out(
        params, last_next_obs.reshape(b, -1))

    vs, pg_adv = vtrace_returns(
        behaviour_logp=tm(batch[SampleBatch.ACTION_LOGP]),
        target_logp=tm(target_logp),
        discounts=discounts,
        rewards=tm(batch[SampleBatch.REWARDS]),
        values=tm(values),
        bootstrap_value=bootstrap_v,
        clip_rho=cfg.get("vtrace_clip_rho_threshold", 1.0),
        clip_pg_rho=cfg.get("vtrace_clip_pg_rho_threshold", 1.0))

    pg_loss = -(tm(target_logp) * pg_adv).mean()
    vf_loss = 0.5 * ((vs - tm(values)) ** 2).mean()
    total = (pg_loss + cfg.get("vf_loss_coeff", 0.5) * vf_loss
             - cfg.get("entropy_coeff", 0.01) * entropy)
    return total, {"pg_loss": pg_loss, "vf_loss": vf_loss,
                   "entropy": entropy}


class ImpalaPolicy(JAXPolicy):
    # V-trace needs dones + the bootstrap observation on device.
    _NON_LOSS_COLUMNS = frozenset({SampleBatch.EPS_ID, "infos"})

    def __init__(self, observation_space, action_space, config):
        super().__init__(observation_space, action_space, config,
                         loss_fn=impala_loss)

    def postprocess_trajectory(self, batch: SampleBatch) -> SampleBatch:
        return batch  # advantages come from v-trace on the learner


class ImpalaTrainer(Trainer):
    """reference: rllib/agents/impala/impala.py ImpalaTrainer."""

    _default_config = IMPALA_CONFIG
    _name = "IMPALA"

    @staticmethod
    def policy_builder(obs_space, action_space, config):
        return ImpalaPolicy(obs_space, action_space, config)

    def setup(self, config):
        super().setup(config)
        self._learner = LearnerThread(
            self.workers.local_worker,
            max_queue=config.get("learner_queue_size", 16))
        self._learner.start()
        self._sampled = 0
        self._t0 = time.perf_counter()
        # One always-in-flight sample call per rollout actor.
        self._inflight: dict = {
            w.sample.remote(): w for w in self.workers.remote_workers}
        self._since_broadcast = {id(w): 0
                                 for w in self.workers.remote_workers}

    def train_step(self) -> dict:
        target = self.config.get("train_batch_size", 500)
        trained = 0
        if not self.workers.remote_workers:
            # Degenerate sync mode (num_workers=0): sample/learn inline.
            while trained < target:
                batch = self.workers.local_worker.sample()
                self._sampled += batch.count
                self._learner.inqueue.put(batch)
                n, _ = self._learner.outqueue.get()
                trained += n
            return self._metrics(trained)
        while trained < target:
            ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                                    timeout=60)
            for ref in ready:
                w = self._inflight.pop(ref)
                batch = ray_tpu.get(ref)
                self._sampled += batch.count
                # Backpressure: blocks when the learner is the bottleneck.
                self._learner.inqueue.put(batch)
                self._since_broadcast[id(w)] += 1
                if (self._since_broadcast[id(w)]
                        >= self.config.get("broadcast_interval", 1)):
                    self._since_broadcast[id(w)] = 0
                    w.set_weights.remote(
                        self.workers.local_worker.get_weights())
                self._inflight[w.sample.remote()] = w
            while not self._learner.outqueue.empty():
                n, _ = self._learner.outqueue.get()
                trained += n
        return self._metrics(trained)

    def _metrics(self, trained: int) -> dict:
        wall = time.perf_counter() - self._t0
        return {
            "env_steps_sampled": self._sampled,
            "env_steps_trained": self._learner.num_steps_trained,
            "env_steps_per_s": round(self._sampled / wall, 1),
            **self._learner.stats(),
        }

    def cleanup(self):
        self._learner.stop()
        super().cleanup()
