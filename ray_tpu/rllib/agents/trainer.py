"""Trainer — RL algorithm shell extending tune.Trainable (reference:
rllib/agents/trainer.py:414 Trainer, train :503, setup :551;
trainer_template.py build_trainer)."""

from __future__ import annotations

from typing import Callable

from ray_tpu.rllib.evaluation.worker_set import WorkerSet
from ray_tpu.tune.trainable import Trainable

COMMON_CONFIG: dict = {
    "env": None,
    "env_config": {},
    "num_workers": 0,
    "num_envs_per_worker": 1,
    "num_cpus_per_worker": 1,
    "rollout_fragment_length": 200,
    "train_batch_size": 2000,
    "gamma": 0.99,
    "lr": 5e-4,
    "fcnet_hiddens": [64, 64],
    "seed": None,
}


class Trainer(Trainable):
    """Subclasses define: default_config() -> dict,
    policy_builder(obs_space, act_space, config) -> Policy,
    train_step(worker_set, config) -> metrics dict."""

    _default_config: dict = COMMON_CONFIG
    _name = "Trainer"

    def __init__(self, config: dict | None = None, env=None):
        config = dict(config or {})
        if env is not None:
            config["env"] = env
        merged = {**COMMON_CONFIG, **self._default_config, **config}
        super().__init__(merged)

    # trainers whose train_step understands MultiAgentBatch set this
    _supports_multiagent = False

    def setup(self, config: dict):
        if config.get("env") is None:
            raise ValueError("config['env'] must be set")
        if (config.get("multiagent", {}).get("policies")
                and not self._supports_multiagent):
            raise ValueError(
                f"{self._name} does not support config['multiagent'] "
                "(its train step consumes single-policy SampleBatches); "
                "use PPO or write a custom train_step")
        self.workers = WorkerSet(
            config["env"], type(self).policy_builder, config,
            num_workers=config.get("num_workers", 0))

    # -- to implement ---------------------------------------------------

    @staticmethod
    def policy_builder(obs_space, action_space, config):
        raise NotImplementedError

    def train_step(self) -> dict:
        raise NotImplementedError

    # -- Trainable surface ----------------------------------------------

    def step(self) -> dict:
        metrics = self.train_step()
        metrics.update(self.workers.collect_metrics())
        return metrics

    def save_checkpoint(self, checkpoint_dir: str):
        return {"weights": self.workers.local_worker.get_weights()}

    def load_checkpoint(self, state):
        self.workers.local_worker.set_weights(state["weights"])
        self.workers.sync_weights()

    def get_policy(self, policy_id: str | None = None):
        lw = self.workers.local_worker
        policies = getattr(lw, "policies", None)
        if policy_id is not None:
            if policies is None:
                raise ValueError(
                    "policy_id given but this is a single-policy trainer")
            return policies[policy_id]
        if policies is None:
            return lw.policy
        if len(policies) == 1:
            return next(iter(policies.values()))
        raise ValueError(
            f"multi-agent trainer has policies {sorted(policies)}; "
            "pass get_policy(policy_id=...)")

    def compute_action(self, obs, explore: bool = False):
        import numpy as np

        actions, _ = self.get_policy().compute_actions(
            np.asarray(obs)[None], explore=explore)
        return actions[0]

    def cleanup(self):
        self.workers.stop()


def build_trainer(name: str, default_config: dict,
                  policy_builder: Callable,
                  train_step: Callable,
                  supports_multiagent: bool = False) -> type:
    """reference: rllib/agents/trainer_template.py:build_trainer."""

    cls = type(name, (Trainer,), {
        "_name": name,
        "_default_config": default_config,
        "_supports_multiagent": supports_multiagent,
        "policy_builder": staticmethod(policy_builder),
        "train_step": lambda self: train_step(self.workers, self.config),
    })
    return cls
