"""Trainer — RL algorithm shell extending tune.Trainable (reference:
rllib/agents/trainer.py:414 Trainer, train :503, setup :551;
trainer_template.py build_trainer)."""

from __future__ import annotations

from typing import Callable

from ray_tpu.rllib.evaluation.worker_set import WorkerSet
from ray_tpu.tune.trainable import Trainable

COMMON_CONFIG: dict = {
    "env": None,
    "env_config": {},
    "num_workers": 0,
    "num_envs_per_worker": 1,
    "num_cpus_per_worker": 1,
    "rollout_fragment_length": 200,
    "train_batch_size": 2000,
    "gamma": 0.99,
    "lr": 5e-4,
    "fcnet_hiddens": [64, 64],
    "seed": None,
    # greedy-policy evaluation episodes every N train() calls
    # (reference: trainer.py evaluation_interval/evaluation_num_episodes)
    "evaluation_interval": 0,
    "evaluation_num_episodes": 5,
}


class Trainer(Trainable):
    """Subclasses define: default_config() -> dict,
    policy_builder(obs_space, act_space, config) -> Policy,
    train_step(worker_set, config) -> metrics dict."""

    _default_config: dict = COMMON_CONFIG
    _name = "Trainer"

    def __init__(self, config: dict | None = None, env=None):
        config = dict(config or {})
        if env is not None:
            config["env"] = env
        merged = {**COMMON_CONFIG, **self._default_config, **config}
        super().__init__(merged)

    # trainers whose train_step understands MultiAgentBatch set this
    _supports_multiagent = False

    def setup(self, config: dict):
        if config.get("env") is None:
            raise ValueError("config['env'] must be set")
        if (config.get("multiagent", {}).get("policies")
                and not self._supports_multiagent):
            raise ValueError(
                f"{self._name} does not support config['multiagent'] "
                "(its train step consumes single-policy SampleBatches); "
                "use PPO or write a custom train_step")
        self.workers = WorkerSet(
            config["env"], type(self).policy_builder, config,
            num_workers=config.get("num_workers", 0))

    # -- to implement ---------------------------------------------------

    @staticmethod
    def policy_builder(obs_space, action_space, config):
        raise NotImplementedError

    def train_step(self) -> dict:
        raise NotImplementedError

    # -- Trainable surface ----------------------------------------------

    def step(self) -> dict:
        metrics = self.train_step()
        metrics.update(self.workers.collect_metrics())
        interval = self.config.get("evaluation_interval") or 0
        # iteration is 0-based DURING a step: +1 so interval=N evaluates
        # on calls N, 2N, ... (not on the untrained first call)
        if interval and (self.iteration + 1) % interval == 0:
            # multi-agent raises a clear unsupported error from
            # evaluate() itself — no silent skip
            metrics["evaluation"] = self.evaluate()
        return metrics

    def evaluate(self, num_episodes: int | None = None) -> dict:
        """Greedy-policy episodes on a fresh env (reference:
        rllib/agents/trainer.py _evaluate / evaluation_workers — here a
        driver-side env since the greedy forward is cheap).
        Single-agent only: multi-agent envs act through dict obs the
        greedy loop doesn't speak."""
        import numpy as np

        from ray_tpu.rllib.env import make_env

        lw = getattr(self.workers, "local_worker", None)
        if lw is not None and hasattr(lw, "policies"):
            raise ValueError(
                "evaluate() supports single-agent trainers only; roll "
                "multi-agent evaluation with your env's dict API")
        n = (self.config.get("evaluation_num_episodes", 5)
             if num_episodes is None else num_episodes)
        if n <= 0:
            raise ValueError(
                "evaluation_num_episodes must be >= 1 (unset "
                "evaluation_interval to disable evaluation)")
        env = make_env(self.config["env"],
                       self.config.get("env_config", {}))
        policy = self.get_policy()
        rewards, lengths = [], []
        try:
            for ep in range(n):
                obs, _ = env.reset(seed=10_000 + ep)
                total, steps = 0.0, 0
                done = False
                while not done and steps < 10_000:
                    acts, _ = policy.compute_actions(
                        np.asarray(obs, np.float32).ravel()[None],
                        explore=False)
                    act = int(acts[0]) if policy.discrete else acts[0]
                    obs, r, term, trunc, _ = env.step(act)
                    total += float(r)
                    steps += 1
                    done = term or trunc
                rewards.append(total)
                lengths.append(steps)
        finally:
            try:
                env.close()
            except Exception:
                pass
        return {
            "episode_reward_mean": float(np.mean(rewards)),
            "episode_reward_min": float(np.min(rewards)),
            "episode_reward_max": float(np.max(rewards)),
            "episode_len_mean": float(np.mean(lengths)),
            "episodes": n,
        }

    def save_checkpoint(self, checkpoint_dir: str):
        return {"weights": self.workers.local_worker.get_weights()}

    def load_checkpoint(self, state):
        self.workers.local_worker.set_weights(state["weights"])
        self.workers.sync_weights()

    def get_policy(self, policy_id: str | None = None):
        lw = self.workers.local_worker
        policies = getattr(lw, "policies", None)
        if policy_id is not None:
            if policies is None:
                raise ValueError(
                    "policy_id given but this is a single-policy trainer")
            return policies[policy_id]
        if policies is None:
            return lw.policy
        if len(policies) == 1:
            return next(iter(policies.values()))
        raise ValueError(
            f"multi-agent trainer has policies {sorted(policies)}; "
            "pass get_policy(policy_id=...)")

    def compute_action(self, obs, explore: bool = False):
        import numpy as np

        actions, _ = self.get_policy().compute_actions(
            np.asarray(obs)[None], explore=explore)
        return actions[0]

    def cleanup(self):
        self.workers.stop()


def build_trainer(name: str, default_config: dict,
                  policy_builder: Callable,
                  train_step: Callable,
                  supports_multiagent: bool = False) -> type:
    """reference: rllib/agents/trainer_template.py:build_trainer."""

    cls = type(name, (Trainer,), {
        "_name": name,
        "_default_config": default_config,
        "_supports_multiagent": supports_multiagent,
        "policy_builder": staticmethod(policy_builder),
        "train_step": lambda self: train_step(self.workers, self.config),
    })
    return cls
