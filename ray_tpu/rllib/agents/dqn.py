"""DQN family — Q-learning with replay + target network (reference:
rllib/agents/dqn/dqn.py, dqn_torch_policy.py; algorithm: Mnih et al. 2015,
double-DQN: van Hasselt 2015). One jitted TD step (loss + grads + Adam +
TD errors for prioritized replay) instead of the reference's separate
torch passes."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu
from ray_tpu.rllib.agents.trainer import COMMON_CONFIG, Trainer
from ray_tpu.rllib.execution.replay_buffer import (PrioritizedReplayBuffer,
                                                   ReplayBuffer)
from ray_tpu.rllib.policy.jax_policy import _mlp_apply, _mlp_init
from ray_tpu.rllib.policy.policy import Policy
from ray_tpu.rllib.policy.sample_batch import SampleBatch

DQN_CONFIG = {
    **COMMON_CONFIG,
    "num_workers": 0,
    "rollout_fragment_length": 4,
    "train_batch_size": 32,
    "lr": 5e-4,
    "buffer_size": 50_000,
    "prioritized_replay": True,
    "prioritized_replay_alpha": 0.6,
    "prioritized_replay_beta": 0.4,
    "learning_starts": 1000,
    "target_network_update_freq": 500,
    "double_q": True,
    "exploration_initial_eps": 1.0,
    "exploration_final_eps": 0.02,
    "exploration_fraction": 0.1,   # of total_timesteps_anneal
    "total_timesteps_anneal": 25_000,
    "sgd_rounds_per_step": 1,
}


class DQNPolicy(Policy):
    """Epsilon-greedy Q policy; discrete action spaces only."""

    discrete = True

    def __init__(self, observation_space, action_space, config: dict):
        super().__init__(observation_space, action_space, config)
        import optax

        if not hasattr(action_space, "n"):
            raise ValueError("DQN requires a discrete action space")
        obs_dim = int(np.prod(observation_space.shape))
        hiddens = list(config.get("fcnet_hiddens", [64, 64]))
        n_act = int(action_space.n)
        seed = config.get("seed") or 0
        self.params = _mlp_init(jax.random.key(seed),
                                [obs_dim] + hiddens + [n_act])
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self._optimizer = optax.adam(config.get("lr", 5e-4))
        self.opt_state = self._optimizer.init(self.params)
        self.eps = float(config.get("exploration_initial_eps", 1.0))
        # fold the worker index into the exploration stream: workers
        # must explore INDEPENDENTLY (identical streams make one
        # worker's exploration a nested copy of another's)
        self._rng = np.random.RandomState(
            seed + 1 + 7919 * config.get("worker_index", 0))
        # learner broadcasts must not overwrite a fixed per-worker
        # epsilon (APEX's exploration spread)
        self._pin_epsilon = bool(config.get("pin_epsilon", False))
        gamma = config.get("gamma", 0.99)
        double_q = bool(config.get("double_q", True))
        # conservative Q-learning penalty (reference: agents/cql —
        # Kumar et al. 2020): alpha * (logsumexp_a Q(s,·) − Q(s, a_data))
        # pushes down out-of-distribution action values, which is what
        # makes PURELY OFFLINE training stable
        cql_alpha = float(config.get("cql_alpha", 0.0))
        optimizer = self._optimizer

        @jax.jit
        def q_values(params, obs):
            return _mlp_apply(params, obs)

        @jax.jit
        def td_step(params, target_params, opt_state, batch):
            obs = batch[SampleBatch.OBS]
            next_obs = batch[SampleBatch.NEXT_OBS]
            actions = batch[SampleBatch.ACTIONS].astype(jnp.int32)
            rewards = batch[SampleBatch.REWARDS]
            not_done = 1.0 - batch[SampleBatch.DONES].astype(jnp.float32)
            weights = batch.get("weights")

            q_next_target = _mlp_apply(target_params, next_obs)
            if double_q:
                sel = jnp.argmax(_mlp_apply(params, next_obs), axis=-1)
            else:
                sel = jnp.argmax(q_next_target, axis=-1)
            bootstrap = jnp.take_along_axis(
                q_next_target, sel[:, None], axis=-1)[:, 0]
            targets = rewards + gamma * not_done * bootstrap
            targets = jax.lax.stop_gradient(targets)

            def loss_fn(p):
                q_all = _mlp_apply(p, obs)
                q = jnp.take_along_axis(
                    q_all, actions[:, None], axis=-1)[:, 0]
                td = q - targets
                huber = jnp.where(jnp.abs(td) < 1.0, 0.5 * td * td,
                                  jnp.abs(td) - 0.5)
                if weights is not None:
                    huber = huber * weights
                loss = huber.mean()
                cql = (jax.scipy.special.logsumexp(q_all, axis=-1)
                       - q).mean()
                if cql_alpha > 0:
                    loss = loss + cql_alpha * cql
                return loss, (td, cql)

            (loss, (td, cql)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = jax.tree.map(lambda p, u: p + u, params, updates)
            return params, opt_state, loss, (td, cql)

        self._q_values = q_values
        self._td_step = td_step

    # -- acting ----------------------------------------------------------

    def compute_actions(self, obs_batch, explore=True):
        obs = jnp.asarray(obs_batch, jnp.float32).reshape(len(obs_batch), -1)
        q = np.asarray(self._q_values(self.params, obs))
        actions = q.argmax(axis=-1)
        if explore and self.eps > 0:
            mask = self._rng.random_sample(len(actions)) < self.eps
            actions = np.where(
                mask, self._rng.randint(0, q.shape[-1], len(actions)),
                actions)
        return actions, {
            SampleBatch.ACTION_LOGP: np.zeros(len(actions), np.float32),
            SampleBatch.VF_PREDS: q.max(axis=-1),
        }

    # -- learning --------------------------------------------------------

    def learn_on_batch(self, batch: SampleBatch) -> dict:
        jb = {k: jnp.asarray(v) for k, v in batch.items()
              if k != "batch_indexes" and v.dtype != object}
        self.params, self.opt_state, loss, (td, cql) = self._td_step(
            self.params, self.target_params, self.opt_state, jb)
        return {"loss": float(loss), "cql_gap": float(cql),
                "td_errors": np.asarray(td)}

    def update_target(self):
        self.target_params = jax.tree.map(jnp.copy, self.params)

    def set_epsilon(self, eps: float):
        self.eps = float(eps)
        return True

    def get_weights(self):
        return {"q": jax.tree.map(np.asarray, self.params),
                "eps": self.eps}

    def set_weights(self, weights):
        self.params = jax.tree.map(jnp.asarray, weights["q"])
        # APEX pins per-worker exploration epsilons: the learner's
        # broadcast must not overwrite them (reference: apex.py
        # per-worker epsilon schedule)
        if not self._pin_epsilon:
            self.eps = weights["eps"]


def linear_epsilon(config: dict, timesteps: int) -> float:
    """Shared linear exploration anneal (reference: dqn.py exploration
    schedule); used by DQN and QMIX."""
    anneal = (config.get("total_timesteps_anneal", 25_000)
              * config.get("exploration_fraction", 0.1))
    frac = min(1.0, timesteps / max(1, anneal))
    e0 = config.get("exploration_initial_eps", 1.0)
    e1 = config.get("exploration_final_eps", 0.02)
    return e0 + frac * (e1 - e0)


class DQNTrainer(Trainer):
    """reference: rllib/agents/dqn/dqn.py DQNTrainer execution plan
    (store → sample → train → update target)."""

    _default_config = DQN_CONFIG
    _name = "DQN"

    @staticmethod
    def policy_builder(obs_space, action_space, config):
        return DQNPolicy(obs_space, action_space, config)

    def setup(self, config):
        super().setup(config)
        self._buffer = self._make_buffer(config)
        self._timesteps = 0
        self._last_target_update = 0

    def _make_buffer(self, config):
        """Overridable: APEX replaces the local buffer with shard actors
        and returns None here."""
        if config.get("prioritized_replay", True):
            return PrioritizedReplayBuffer(
                config["buffer_size"],
                alpha=config.get("prioritized_replay_alpha", 0.6),
                seed=config.get("seed"))
        return ReplayBuffer(config["buffer_size"],
                            seed=config.get("seed"))

    def _epsilon(self) -> float:
        return linear_epsilon(self.config, self._timesteps)

    def train_step(self) -> dict:
        cfg = self.config
        # Collect a fragment and stash it (store op).
        batch = self.workers.sample(cfg.get("rollout_fragment_length", 4))
        self._buffer.add_batch(batch)
        self._timesteps += batch.count
        eps = self._epsilon()
        # Remote workers pick the epsilon up with the weight broadcast
        # below (get_weights carries it).
        self.workers.local_worker.policy.set_epsilon(eps)

        metrics = {"timesteps_total": self._timesteps,
                   "epsilon": round(eps, 4),
                   "buffer_size": len(self._buffer)}
        if len(self._buffer) < cfg.get("learning_starts", 1000):
            return metrics

        # Replay → TD step(s).
        for _ in range(cfg.get("sgd_rounds_per_step", 1)):
            if isinstance(self._buffer, PrioritizedReplayBuffer):
                replay = self._buffer.sample(
                    cfg.get("train_batch_size", 32),
                    beta=cfg.get("prioritized_replay_beta", 0.4))
            else:
                replay = self._buffer.sample(cfg.get("train_batch_size", 32))
            info = self.workers.local_worker.learn_on_batch(replay)
            if isinstance(self._buffer, PrioritizedReplayBuffer):
                self._buffer.update_priorities(replay["batch_indexes"],
                                               info.pop("td_errors"))
            else:
                info.pop("td_errors", None)
            metrics.update(info)

        # Target network sync.
        if (self._timesteps - self._last_target_update
                >= cfg.get("target_network_update_freq", 500)):
            self._last_target_update = self._timesteps
            self.workers.local_worker.policy.update_target()
        self.workers.sync_weights()
        return metrics
