"""MARWIL — monotonic advantage re-weighted imitation learning
(reference: rllib/agents/marwil/marwil.py + marwil_policy.py; Wang et
al. 2018).

Imitation from logged data, but better-than-the-demonstrator: each
logged action's log-likelihood is weighted by exp(beta * advantage)
where the advantage comes against a learned value baseline — good
demonstrated actions are cloned hard, bad ones barely. beta=0 reduces
to plain behavior cloning. Works purely offline (config["input"]) or
on-policy; one jitted step trains policy and value heads together."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.agents.pg import PGPolicy, pg_train_step
from ray_tpu.rllib.agents.trainer import COMMON_CONFIG, build_trainer
from ray_tpu.rllib.policy.jax_policy import JAXPolicy
from ray_tpu.rllib.policy.sample_batch import SampleBatch

MARWIL_CONFIG = {
    **COMMON_CONFIG,
    "beta": 1.0,             # 0 = plain behavior cloning
    "vf_coeff": 1.0,
    # moving-average normalizer for advantages inside the exp()
    # (reference: marwil_policy.py ma_adv_norm)
    "norm_update_rate": 1e-3,
    "train_batch_size": 512,
    "rollout_fragment_length": 256,
    "lr": 1e-3,
}


class MARWILPolicy(PGPolicy):
    """Shares PG's return-bootstrapping postprocess; only the loss (and
    its moving-average advantage normalizer) differs."""

    def __init__(self, observation_space, action_space, config):
        merged = {**MARWIL_CONFIG, **config}
        JAXPolicy.__init__(self, observation_space, action_space, merged,
                           loss_fn=marwil_loss)
        # running normalizer for squared advantages (device scalar)
        self.ma_adv_sq = jnp.asarray(1.0)

    def learn_on_batch(self, batch: SampleBatch) -> dict:
        # offline batches arrive WITHOUT the postprocessed returns
        # column — compute it here like the on-policy path would
        if SampleBatch.ADVANTAGES not in batch:
            batch = self.postprocess_trajectory(batch)
        jb = {k: jnp.asarray(v) for k, v in batch.items()
              if k not in self._NON_LOSS_COLUMNS and v.dtype != object}
        jb["ma_adv_sq"] = self.ma_adv_sq
        self.params, self.opt_state, loss, metrics = self._sgd_step(
            self.params, self.opt_state, jb)
        self.ma_adv_sq = metrics.pop("ma_adv_sq")
        out = {"total_loss": float(loss)}
        out.update({k: float(v) for k, v in metrics.items()})
        return out


def marwil_loss(params, batch, policy: MARWILPolicy):
    cfg = policy.config
    pi_out, values = JAXPolicy.model_out(
        params, batch[SampleBatch.OBS].astype(jnp.float32))
    returns = batch[SampleBatch.ADVANTAGES]
    adv = returns - jax.lax.stop_gradient(values)
    vf_loss = ((values - returns) ** 2).mean()
    # moving-average normalization keeps exp() in a sane range
    # (reference: marwil_policy.py update of the squared-adv EMA)
    ma = batch["ma_adv_sq"]
    ma = ma + cfg["norm_update_rate"] * ((adv ** 2).mean() - ma)
    scale = jax.lax.rsqrt(jnp.maximum(ma, 1e-8))
    weights = jnp.exp(cfg["beta"]
                      * jnp.clip(adv * scale, -5.0, 5.0))
    logp = policy.logp_fn()(pi_out, batch[SampleBatch.ACTIONS])
    bc_loss = -(jax.lax.stop_gradient(weights) * logp).mean()
    total = bc_loss + cfg["vf_coeff"] * vf_loss
    return total, {"bc_loss": bc_loss, "vf_loss": vf_loss,
                   "ma_adv_sq": ma}


# same collect-then-learn execution plan as PG (reused, not copied)
MARWILTrainer = build_trainer("MARWIL", MARWIL_CONFIG, MARWILPolicy,
                              pg_train_step)
