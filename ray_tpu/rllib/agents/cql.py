"""CQL — conservative Q-learning for PURELY OFFLINE RL (reference:
rllib/agents/cql (later snapshots) / the offline-RL role the reference's
offline IO feeds; Kumar et al. 2020).

Discrete CQL on the DQN machinery: the TD loss gains
alpha * (logsumexp_a Q(s,·) − Q(s, a_data)), pushing down
out-of-distribution action values so the greedy policy stays inside the
dataset's support. The trainer never steps an env: rollout "sampling"
reads the offline dataset (config["input"], the JsonReader path the
rollout worker already understands), and config["env"] is used only for
observation/action spaces and greedy evaluation."""

from __future__ import annotations

from ray_tpu.rllib.agents.dqn import DQN_CONFIG, DQNTrainer

CQL_CONFIG = {
    **DQN_CONFIG,
    "cql_alpha": 1.0,
    "input": None,               # REQUIRED: offline dataset path
    # no exploration/anneal — actions are never taken in an env
    "exploration_initial_eps": 0.0,
    "exploration_final_eps": 0.0,
    "learning_starts": 200,
    "sgd_rounds_per_step": 16,
}


class CQLTrainer(DQNTrainer):
    """DQN execution plan with the dataset as the only experience
    source and the conservative penalty active."""

    _default_config = CQL_CONFIG
    _name = "CQL"

    def setup(self, config):
        if not config.get("input") or config["input"] == "sampler":
            raise ValueError(
                "CQL is offline-only: set config['input'] to the "
                "dataset path (JsonWriter output)")
        if float(config.get("cql_alpha", 0.0)) <= 0:
            raise ValueError("CQL needs cql_alpha > 0 — with 0 this is "
                             "plain offline DQN")
        super().setup(config)
