"""DDPG and TD3 (reference: rllib/agents/ddpg/ddpg.py + td3.py +
ddpg_torch_policy.py): off-policy continuous control with a deterministic
tanh actor, Q critic(s), polyak target networks, and Gaussian action
noise for exploration. TD3 is DDPG with its three fixes flipped on
(exactly how the reference's td3.py subclasses ddpg.py):

    twin_q               — min over two critics kills Q overestimation
    policy_delay         — actor (and targets) update every d critic steps
    smooth_target_policy — clipped noise on the target action

One fused jitted update does critic + (conditionally, via lax.cond)
actor + polyak steps with donated state, so the learner step is a single
device dispatch, DQN-family style."""

from __future__ import annotations

import numpy as np

from ray_tpu.rllib.agents.trainer import Trainer
from ray_tpu.rllib.execution.replay_buffer import ReplayBuffer
from ray_tpu.rllib.policy.jax_policy import _mlp_apply, _mlp_init
from ray_tpu.rllib.policy.policy import Policy
from ray_tpu.rllib.policy.sample_batch import SampleBatch

DDPG_CONFIG: dict = {
    "rollout_fragment_length": 64,
    "learning_starts": 500,
    "buffer_size": 100_000,
    "train_batch_size": 128,
    "sgd_iters_per_step": 32,
    "gamma": 0.99,
    "tau": 0.005,
    "actor_lr": 1e-3,
    "critic_lr": 1e-3,
    "exploration_noise": 0.1,     # sigma of behavior noise (action scale)
    "fcnet_hiddens": [64, 64],
    # TD3 switches (reference: agents/ddpg/td3.py TD3_DEFAULT_CONFIG)
    "twin_q": False,
    "policy_delay": 1,
    "smooth_target_policy": False,
    "target_noise": 0.2,
    "target_noise_clip": 0.5,
}

TD3_CONFIG: dict = {**DDPG_CONFIG, "twin_q": True, "policy_delay": 2,
                    "smooth_target_policy": True}


class DDPGPolicy(Policy):
    """Deterministic actor μ(s)=tanh(mlp) in [-1,1] + Q critic(s)."""

    def __init__(self, observation_space, action_space, config: dict):
        import jax
        import jax.numpy as jnp
        import optax

        merged = {**DDPG_CONFIG, **config}
        super().__init__(observation_space, action_space, merged)
        if hasattr(action_space, "n"):
            raise ValueError("DDPG/TD3 are continuous-control only; use "
                             "DQN for discrete actions")
        self.discrete = False
        obs_dim = int(np.prod(observation_space.shape))
        act_dim = int(np.prod(action_space.shape))
        self._act_scale = (action_space.high - action_space.low) / 2.0
        self._act_mid = (action_space.high + action_space.low) / 2.0
        hiddens = list(merged.get("fcnet_hiddens", [64, 64]))
        seed = merged.get("seed") or 0
        keys = jax.random.split(jax.random.key(seed), 3)
        q_sizes = [obs_dim + act_dim] + hiddens + [1]
        params = {
            "pi": _mlp_init(keys[0], [obs_dim] + hiddens + [act_dim]),
            "q1": _mlp_init(keys[1], q_sizes),
        }
        if merged["twin_q"]:
            params["q2"] = _mlp_init(keys[2], q_sizes)
        self.params = params
        self.target = jax.tree.map(lambda x: x, params)
        self._optimizer = optax.multi_transform(
            {"pi": optax.adam(merged["actor_lr"]),
             "q": optax.adam(merged["critic_lr"])},
            lambda p: {k: jax.tree.map(
                lambda _: "pi" if k == "pi" else "q", v)
                for k, v in p.items()})
        self.opt_state = self._optimizer.init(self.params)
        self._rng = jax.random.key(seed + 1)
        self._step_count = 0
        self._noise = merged["exploration_noise"]
        self._build()

    @staticmethod
    def _mu(params, obs):
        import jax.numpy as jnp

        return jnp.tanh(_mlp_apply(params["pi"], obs))

    @staticmethod
    def _q(params_q, obs, act):
        import jax.numpy as jnp

        return _mlp_apply(params_q, jnp.concatenate([obs, act], -1))[:, 0]

    def _build(self):
        import jax
        import jax.numpy as jnp

        cfg = self.config
        gamma, tau = cfg["gamma"], cfg["tau"]
        twin = cfg["twin_q"]
        delay = int(cfg["policy_delay"])
        smooth = cfg["smooth_target_policy"]
        t_noise, t_clip = cfg["target_noise"], cfg["target_noise_clip"]
        optimizer = self._optimizer

        def q_target(target, nxt, rewards, dones, key):
            a2 = DDPGPolicy._mu(target, nxt)
            if smooth:
                eps = jnp.clip(
                    t_noise * jax.random.normal(key, a2.shape),
                    -t_clip, t_clip)
                a2 = jnp.clip(a2 + eps, -1.0, 1.0)
            qn = DDPGPolicy._q(target["q1"], nxt, a2)
            if twin:
                qn = jnp.minimum(qn, DDPGPolicy._q(target["q2"], nxt, a2))
            return rewards + gamma * (1.0 - dones) * qn

        def critic_loss(params, target, batch, key):
            backup = jax.lax.stop_gradient(q_target(
                target, batch["new_obs"], batch["rewards"],
                batch["dones"], key))
            q1 = DDPGPolicy._q(params["q1"], batch["obs"],
                               batch["actions"])
            loss = ((q1 - backup) ** 2).mean()
            if twin:
                q2 = DDPGPolicy._q(params["q2"], batch["obs"],
                                   batch["actions"])
                loss = loss + ((q2 - backup) ** 2).mean()
            return loss

        def actor_loss(params, batch):
            a = DDPGPolicy._mu(params, batch["obs"])
            frozen_q = jax.lax.stop_gradient(params["q1"])
            return -DDPGPolicy._q(frozen_q, batch["obs"], a).mean()

        def loss_fn(params, target, batch, key, do_actor):
            c = critic_loss(params, target, batch, key)
            # delayed actor: multiply by the 0/1 gate instead of cond so
            # the grad structure is static (lax.cond over grads of a
            # subtree changes pytree shape)
            a = actor_loss(params, batch) * do_actor
            return c + a, {"critic_loss": c, "actor_loss": a}

        @jax.jit
        def update(params, target, opt_state, batch, key, do_actor):
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target, batch, key,
                                       do_actor)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = jax.tree.map(lambda p, u: p + u, params, updates)
            # polyak only on actor-update steps (TD3 pairs them)
            target = jax.tree.map(
                lambda t, p: (1 - tau * do_actor) * t
                + tau * do_actor * p, target, params)
            return params, target, opt_state, loss, metrics

        @jax.jit
        def act(params, obs, key, sigma):
            a = DDPGPolicy._mu(params, obs)
            return jnp.clip(
                a + sigma * jax.random.normal(key, a.shape), -1.0, 1.0)

        @jax.jit
        def act_greedy(params, obs):
            return DDPGPolicy._mu(params, obs)

        self._update = update
        self._act = act
        self._act_greedy = act_greedy
        self._delay = delay

    # -- Policy surface --------------------------------------------------

    def compute_actions(self, obs_batch, explore: bool = True):
        import jax
        import jax.numpy as jnp

        obs = jnp.asarray(obs_batch, jnp.float32).reshape(
            len(obs_batch), -1)
        if explore:
            self._rng, sub = jax.random.split(self._rng)
            act = self._act(self.params, obs, sub, self._noise)
        else:
            act = self._act_greedy(self.params, obs)
        scaled = np.asarray(act) * self._act_scale + self._act_mid
        return scaled, {SampleBatch.ACTION_LOGP: np.zeros(len(obs_batch)),
                        SampleBatch.VF_PREDS: np.zeros(len(obs_batch))}

    def postprocess_trajectory(self, batch, other_agent_batches=None,
                               episode=None):
        return batch

    def learn_on_batch(self, batch: SampleBatch) -> dict:
        import jax
        import jax.numpy as jnp

        norm_act = ((batch[SampleBatch.ACTIONS] - self._act_mid)
                    / self._act_scale)
        jb = {
            "obs": jnp.asarray(batch[SampleBatch.OBS], jnp.float32),
            "new_obs": jnp.asarray(batch[SampleBatch.NEXT_OBS],
                                   jnp.float32),
            "actions": jnp.asarray(np.clip(norm_act, -1.0, 1.0),
                                   jnp.float32),
            "rewards": jnp.asarray(batch[SampleBatch.REWARDS],
                                   jnp.float32),
            "dones": jnp.asarray(batch[SampleBatch.DONES], jnp.float32),
        }
        self._step_count += 1
        do_actor = jnp.float32(self._step_count % self._delay == 0)
        self._rng, sub = jax.random.split(self._rng)
        (self.params, self.target, self.opt_state, loss,
         metrics) = self._update(self.params, self.target, self.opt_state,
                                 jb, sub, do_actor)
        out = {"total_loss": float(loss)}
        out.update({k: float(v) for k, v in metrics.items()})
        return out

    def get_weights(self):
        import jax

        return {"params": jax.tree.map(np.asarray, self.params),
                "target": jax.tree.map(np.asarray, self.target)}

    def set_weights(self, weights):
        import jax
        import jax.numpy as jnp

        self.params = jax.tree.map(jnp.asarray, weights["params"])
        self.target = jax.tree.map(jnp.asarray, weights["target"])


class DDPGTrainer(Trainer):
    """reference: rllib/agents/ddpg/ddpg.py execution plan — store →
    replay → fused update, the DQN-family shape."""

    _default_config = DDPG_CONFIG
    _name = "DDPG"

    @staticmethod
    def policy_builder(obs_space, action_space, config):
        return DDPGPolicy(obs_space, action_space, config)

    def setup(self, config):
        super().setup(config)
        self._buffer = ReplayBuffer(config["buffer_size"],
                                    seed=config.get("seed"))

    def train_step(self) -> dict:
        config = self.config
        batch = self.workers.sample(config["rollout_fragment_length"])
        self._buffer.add_batch(batch)
        metrics: dict = {"buffer_size": len(self._buffer)}
        if len(self._buffer) >= config["learning_starts"]:
            policy = self.workers.local_worker.policy
            for _ in range(config["sgd_iters_per_step"]):
                replay = self._buffer.sample(config["train_batch_size"])
                metrics.update(policy.learn_on_batch(replay))
            self.workers.sync_weights()
        metrics["num_env_steps_sampled"] = len(batch)
        return metrics


class TD3Trainer(DDPGTrainer):
    """reference: rllib/agents/ddpg/td3.py — DDPG defaults with the three
    TD3 fixes enabled."""

    _default_config = TD3_CONFIG
    _name = "TD3"
