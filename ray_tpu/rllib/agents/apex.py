"""Ape-X: distributed prioritized experience replay (reference:
rllib/agents/dqn/apex.py + execution/replay_ops — Horgan et al. 2018).

The DQN execution plan scaled out: rollout workers sample with
per-worker exploration epsilons, fragments flow DIRECTLY into sharded
replay-buffer ACTORS (the driver only routes ObjectRefs, so experience
bytes move worker→shard through the object plane without a driver copy),
and the learner loop round-robins sampled batches out of the shards,
trains, and pushes TD-error priority updates back to the shard each
batch came from.
"""

from __future__ import annotations

import numpy as np

import ray_tpu
from ray_tpu.rllib.agents.dqn import DQN_CONFIG, DQNPolicy, DQNTrainer
from ray_tpu.rllib.execution.replay_buffer import PrioritizedReplayBuffer
from ray_tpu.rllib.policy.sample_batch import SampleBatch

APEX_CONFIG = {
    **DQN_CONFIG,
    "num_workers": 2,
    "num_replay_buffer_shards": 2,
    "rollout_fragment_length": 50,
    "train_batch_size": 64,
    "learning_starts": 500,
    "sgd_rounds_per_step": 8,
    "target_network_update_freq": 2000,
    # per-worker epsilons spread exploration (reference: apex.py
    # per-worker-epsilon schedule)
    "worker_min_epsilon": 0.05,
    "worker_max_epsilon": 0.6,
}


@ray_tpu.remote
class ReplayShard:
    """One shard of the distributed prioritized buffer (reference:
    execution/replay_ops ReplayActor)."""

    def __init__(self, capacity: int, alpha: float, seed=None):
        self._buffer = PrioritizedReplayBuffer(capacity, alpha=alpha,
                                               seed=seed)

    def add_batch(self, batch) -> int:
        if not isinstance(batch, SampleBatch):
            batch = SampleBatch(batch)
        self._buffer.add_batch(batch)
        return len(self._buffer)

    def sample(self, batch_size: int, beta: float):
        if len(self._buffer) < batch_size:
            return None
        return self._buffer.sample(batch_size, beta=beta)

    def update_priorities(self, idx, priorities) -> bool:
        self._buffer.update_priorities(np.asarray(idx),
                                       np.asarray(priorities))
        return True

    def size(self) -> int:
        return len(self._buffer)


class ApexTrainer(DQNTrainer):
    """reference: rllib/agents/dqn/apex.py apex_execution_plan."""

    _default_config = APEX_CONFIG
    _name = "APEX"

    @staticmethod
    def policy_builder(obs_space, action_space, config):
        idx = config.get("worker_index", 0)
        if idx > 0:
            # rollout workers explore at a FIXED per-worker epsilon (no
            # anneal): the spread covers explore/exploit across the
            # fleet, pinned against the learner's weight broadcasts
            # (pin_epsilon is a DQNPolicy config contract, and each
            # worker's RNG stream is independent via worker_index)
            policy = DQNPolicy(obs_space, action_space,
                               {**config, "pin_epsilon": True})
            n = max(1, config.get("num_workers", 1))
            lo = config.get("worker_min_epsilon", 0.05)
            hi = config.get("worker_max_epsilon", 0.6)
            policy.set_epsilon(
                lo + (hi - lo) * ((idx - 1) / max(1, n - 1)))
        else:
            policy = DQNPolicy(obs_space, action_space, config)
            policy.set_epsilon(0.0)  # learner/eval copy acts greedily
        return policy

    def _make_buffer(self, config):
        return None  # replaced by the shard actors

    def setup(self, config):
        super().setup(config)
        n_shards = config["num_replay_buffer_shards"]
        per_shard = max(1, config["buffer_size"] // n_shards)
        seed = config.get("seed")
        self._shards = [
            ReplayShard.remote(per_shard,
                               config.get("prioritized_replay_alpha", 0.6),
                               None if seed is None else seed + i)
            for i in range(n_shards)
        ]
        self._next_shard = 0
        self._inflight_stores: list = []

    def train_step(self) -> dict:
        cfg = self.config
        if not self.workers.remote_workers:
            raise ValueError("APEX needs num_workers >= 1 rollout actors")
        # 1. sampling: fragment refs flow worker -> shard without being
        # materialized on the driver (the ref is the add_batch argument)
        sample_refs = [w.sample.remote(cfg["rollout_fragment_length"])
                       for w in self.workers.remote_workers]
        for ref in sample_refs:
            shard = self._shards[self._next_shard % len(self._shards)]
            self._next_shard += 1
            self._inflight_stores.append(shard.add_batch.remote(ref))
        self._timesteps += (cfg["rollout_fragment_length"]
                            * len(sample_refs))
        # bound the store pipeline (backpressure, and surfacing errors)
        if len(self._inflight_stores) >= 4 * len(self._shards):
            ray_tpu.get(self._inflight_stores, timeout=120)
            self._inflight_stores = []

        sizes = ray_tpu.get([s.size.remote() for s in self._shards],
                            timeout=60)
        metrics = {"timesteps_total": self._timesteps,
                   "buffer_size": int(sum(sizes)),
                   "num_replay_shards": len(self._shards)}
        if sum(sizes) < cfg["learning_starts"]:
            return metrics

        # 2. learner loop: round-robin sampled batches out of the
        # shards, prefetching round i+1's sample before training on
        # round i's batch so replay round-trips overlap learner compute
        policy = self.workers.local_worker.policy
        beta = cfg.get("prioritized_replay_beta", 0.4)
        rounds = cfg["sgd_rounds_per_step"]

        def request(i):
            shard = self._shards[i % len(self._shards)]
            return shard.sample.remote(cfg["train_batch_size"], beta)

        trained = 0
        pending = request(0)
        for i in range(rounds):
            replay = ray_tpu.get(pending, timeout=60)
            if i + 1 < rounds:
                pending = request(i + 1)
            if replay is None:
                continue
            info = policy.learn_on_batch(replay)
            # drained with _inflight_stores below: a dead shard raises
            # at the next drain instead of silently dropping priority
            # updates (degrading to uniform replay)
            self._inflight_stores.append(
                self._shards[i % len(self._shards)]
                .update_priorities.remote(
                    replay["batch_indexes"], info.pop("td_errors")))
            trained += len(replay)
            metrics.update(info)
        metrics["num_env_steps_trained"] = trained

        # 3. target sync + weight broadcast
        if (self._timesteps - self._last_target_update
                >= cfg.get("target_network_update_freq", 2000)):
            self._last_target_update = self._timesteps
            policy.update_target()
        self.workers.sync_weights()
        return metrics

    def cleanup(self):
        for s in getattr(self, "_shards", []):
            try:
                ray_tpu.kill(s)
            except Exception:
                pass
        super().cleanup()
