"""MAML — model-agnostic meta-learning for RL (reference:
rllib/agents/maml (later snapshots); Finn et al. 2017).

This is where the jax-native design pays off directly: the inner
adaptation step is a literal `jax.grad` composition and the outer
meta-gradient differentiates THROUGH it — one jitted function computes
θ'_i = θ − α·∇L(pre_i, θ) per task and backprops the post-adaptation
policy-gradient loss to θ. The reference needs explicit higher-order
torch autograd plumbing for the same math.

Task protocol (reference MAML env API): the env exposes
`sample_tasks(n)` and `set_task(task)`; each train step samples a task
batch, collects a PRE batch per task with θ, adapts, collects a POST
batch with θ'_i, and applies one outer Adam step on the summed
post-adaptation loss."""

from __future__ import annotations

import numpy as np

from ray_tpu.rllib.agents.pg import discounted_returns
from ray_tpu.rllib.agents.trainer import COMMON_CONFIG, Trainer
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.policy.jax_policy import (JAXPolicy, categorical_logp,
                                             gaussian_logp)

MAML_CONFIG = {
    **COMMON_CONFIG,
    "num_tasks_per_step": 4,
    "inner_lr": 0.5,
    "inner_rollout_steps": 64,
    "lr": 1e-2,                 # outer (meta) Adam lr
    "gamma": 0.99,
}


class MAMLTrainer(Trainer):
    """Driver-local meta-training loop (tasks are cheap envs; the meta
    math is the point). Reuses JAXPolicy's model/act machinery."""

    _default_config = MAML_CONFIG
    _name = "MAML"

    @staticmethod
    def policy_builder(obs_space, action_space, config):
        return JAXPolicy(obs_space, action_space, config)

    def setup(self, config):
        if config.get("env") is None:
            raise ValueError("config['env'] must be set")
        self.env = make_env(config["env"], config.get("env_config", {}))
        if not hasattr(self.env, "sample_tasks") or not hasattr(
                self.env, "set_task"):
            raise ValueError(
                "MAML needs a task-distribution env exposing "
                "sample_tasks(n) and set_task(task) (the reference MAML "
                "env API)")
        self.policy = JAXPolicy(self.env.observation_space,
                                self.env.action_space, config)
        self._build_meta()
        self._timesteps = 0
        self._completed: list[float] = []

    def _build_meta(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        inner_lr = cfg["inner_lr"]
        discrete = self.policy.discrete
        logp_fn = categorical_logp if discrete else gaussian_logp

        def pg_loss(params, batch):
            pi_out, _ = JAXPolicy.model_out(params, batch["obs"])
            logp = logp_fn(pi_out, batch["actions"])
            adv = batch["returns"]
            adv = (adv - adv.mean()) / (adv.std() + 1e-8)
            return -(logp * adv).mean()

        def adapt(params, pre_batch):
            """θ' = θ − α∇L(pre, θ) — the inner step, differentiable."""
            grads = jax.grad(pg_loss)(params, pre_batch)
            return jax.tree.map(lambda p, g: p - inner_lr * g, params,
                                grads)

        def meta_loss(params, pre_batches, post_batches):
            losses = [
                pg_loss(adapt(params, pre), post)
                for pre, post in zip(pre_batches, post_batches)
            ]
            return jnp.stack(losses).mean()

        self._meta_optimizer = optax.adam(cfg["lr"])
        self._meta_opt_state = self._meta_optimizer.init(
            self.policy.params)

        @jax.jit
        def meta_step(params, opt_state, pre_batches, post_batches):
            loss, grads = jax.value_and_grad(meta_loss)(
                params, pre_batches, post_batches)
            updates, opt_state = self._meta_optimizer.update(
                grads, opt_state, params)
            params = jax.tree.map(lambda p, u: p + u, params, updates)
            return params, opt_state, loss

        self._adapt = jax.jit(adapt)
        self._meta_step = meta_step

    # -- rollouts --------------------------------------------------------

    def _collect(self, n_steps: int) -> dict:
        """One on-policy fragment on the CURRENT env task with the
        CURRENT policy params; returns jit-ready columns."""
        import jax.numpy as jnp

        obs_l, act_l, rew_l, done_l = [], [], [], []
        obs, _ = self.env.reset()
        ep_reward = 0.0
        for _ in range(n_steps):
            acts, _extra = self.policy.compute_actions(
                np.asarray(obs, np.float32).ravel()[None])
            act = acts[0]
            env_act = int(act) if self.policy.discrete else act
            nxt, r, term, trunc, _ = self.env.step(env_act)
            obs_l.append(np.asarray(obs, np.float32).ravel())
            act_l.append(act)
            rew_l.append(np.float32(r))
            # truncation counts as done HERE: plain discounted returns
            # have no value bootstrap, so letting the next episode's
            # rewards discount backward across a reset would bias both
            # gradients (rollout_worker keeps trunc done=False only
            # because GAE bootstraps the tail)
            done_l.append(bool(term or trunc))
            ep_reward += float(r)
            self._timesteps += 1
            if term or trunc:
                self._completed.append(ep_reward)
                ep_reward = 0.0
                nxt, _ = self.env.reset()
            obs = nxt
        returns = discounted_returns(
            np.asarray(rew_l, np.float64), np.asarray(done_l, np.float64),
            self.config["gamma"])
        return {"obs": jnp.asarray(np.stack(obs_l)),
                "actions": jnp.asarray(np.stack(act_l)),
                "returns": jnp.asarray(returns),
                "reward_mean": float(np.mean(rew_l))}

    def train_step(self) -> dict:
        cfg = self.config
        tasks = self.env.sample_tasks(cfg["num_tasks_per_step"])
        theta = self.policy.params
        pre_batches, post_batches = [], []
        pre_r, post_r = [], []
        for task in tasks:
            self.env.set_task(task)
            self.policy.params = theta
            pre = self._collect(cfg["inner_rollout_steps"])
            # pop metrics BEFORE the jit boundary: both _adapt call
            # sites must share one pytree structure (one compilation)
            pre_r.append(pre.pop("reward_mean"))
            adapted = self._adapt(theta, pre)
            self.policy.params = adapted
            post = self._collect(cfg["inner_rollout_steps"])
            post_r.append(post.pop("reward_mean"))
            pre_batches.append(pre)
            post_batches.append(post)
        self.policy.params = theta
        (self.policy.params, self._meta_opt_state,
         loss) = self._meta_step(theta, self._meta_opt_state,
                                 pre_batches, post_batches)
        return {
            "meta_loss": float(loss),
            "timesteps_total": self._timesteps,
            "pre_adaptation_reward": float(np.mean(pre_r)),
            "post_adaptation_reward": float(np.mean(post_r)),
        }

    def step(self) -> dict:
        metrics = self.train_step()
        if self._completed:
            metrics["episode_reward_mean"] = float(
                np.mean(self._completed[-100:]))
        interval = self.config.get("evaluation_interval") or 0
        if interval and (self.iteration + 1) % interval == 0:
            metrics["evaluation"] = self.evaluate()
        return metrics

    def evaluate(self, num_episodes: int | None = None) -> dict:
        """ZERO-SHOT greedy evaluation of the meta-init θ across fresh
        tasks (the base Trainer's evaluate assumes a WorkerSet this
        trainer doesn't have); per-task ADAPTED performance is the
        post_adaptation_reward train metric / adapt_to()."""
        n = (self.config.get("evaluation_num_episodes", 5)
             if num_episodes is None else num_episodes)
        rewards, lengths = [], []
        theta = self.policy.params
        for task in self.env.sample_tasks(n):
            self.env.set_task(task)
            obs, _ = self.env.reset()
            total, steps, done = 0.0, 0, False
            while not done and steps < 10_000:
                acts, _ = self.policy.compute_actions(
                    np.asarray(obs, np.float32).ravel()[None],
                    explore=False)
                act = int(acts[0]) if self.policy.discrete else acts[0]
                obs, r, term, trunc, _ = self.env.step(act)
                total += float(r)
                steps += 1
                done = bool(term or trunc)
            rewards.append(total)
            lengths.append(steps)
        self.policy.params = theta
        return {"episode_reward_mean": float(np.mean(rewards)),
                "episode_len_mean": float(np.mean(lengths)),
                "episodes": n}

    def adapt_to(self, task, n_steps: int | None = None):
        """Deploy-time adaptation: one inner step on a fresh task;
        returns the adapted params (θ is left untouched)."""
        self.env.set_task(task)
        theta = self.policy.params
        pre = self._collect(n_steps or self.config["inner_rollout_steps"])
        pre.pop("reward_mean")
        return self._adapt(theta, pre)

    def get_policy(self, policy_id=None):
        return self.policy

    def save_checkpoint(self, checkpoint_dir):
        return {"weights": self.policy.get_weights()}

    def load_checkpoint(self, state):
        self.policy.set_weights(state["weights"])

    def cleanup(self):
        try:
            self.env.close()
        except Exception:
            pass
