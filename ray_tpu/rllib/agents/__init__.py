from ray_tpu.rllib.agents.dqn import DQNTrainer
from ray_tpu.rllib.agents.impala import ImpalaTrainer
from ray_tpu.rllib.agents.ppo import PPOTrainer
from ray_tpu.rllib.agents.trainer import Trainer, build_trainer

__all__ = ["DQNTrainer", "ImpalaTrainer", "PPOTrainer", "Trainer",
           "build_trainer"]
