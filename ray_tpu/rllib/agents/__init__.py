from ray_tpu.rllib.agents.a3c import A3CTrainer
from ray_tpu.rllib.agents.dqn import DQNTrainer
from ray_tpu.rllib.agents.es import ESTrainer
from ray_tpu.rllib.agents.impala import ImpalaTrainer
from ray_tpu.rllib.agents.pg import PGTrainer
from ray_tpu.rllib.agents.ppo import PPOTrainer
from ray_tpu.rllib.agents.sac import SACTrainer
from ray_tpu.rllib.agents.trainer import Trainer, build_trainer

__all__ = ["A3CTrainer", "DQNTrainer", "ESTrainer", "ImpalaTrainer",
           "PGTrainer", "PPOTrainer", "SACTrainer", "Trainer",
           "build_trainer"]
