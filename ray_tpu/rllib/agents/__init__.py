from ray_tpu.rllib.agents.ppo import PPOTrainer
from ray_tpu.rllib.agents.trainer import Trainer, build_trainer

__all__ = ["PPOTrainer", "Trainer", "build_trainer"]
