"""WorkerSet — local learner worker + remote rollout actors (reference:
rllib/evaluation/worker_set.py:27). On TPU the local worker owns the
jitted learner step; remote workers are CPU actors producing batches."""

from __future__ import annotations

import cloudpickle

import ray_tpu
from ray_tpu.rllib.evaluation.multi_agent import MultiAgentRolloutWorker
from ray_tpu.rllib.evaluation.rollout_worker import RolloutWorker
from ray_tpu.rllib.policy.sample_batch import (MultiAgentBatch,
                                               SampleBatch)


class WorkerSet:
    def __init__(self, env_spec, policy_builder, config: dict,
                 num_workers: int = 0):
        ma = config.get("multiagent") or {}
        if ma.get("policies"):
            worker_cls = MultiAgentRolloutWorker
            # spec carries the callables (builders, mapping fn); strip it
            # from the plain config dict shipped to remote actors
            config = {k: v for k, v in config.items() if k != "multiagent"}
            pickled = cloudpickle.dumps({
                "policies": {pid: (spec[0] or policy_builder, *spec[1:])
                             for pid, spec in ma["policies"].items()},
                "policy_mapping_fn": ma["policy_mapping_fn"],
                "policies_to_train": ma.get("policies_to_train"),
            })
        else:
            worker_cls = RolloutWorker
            pickled = cloudpickle.dumps(policy_builder)
        self.local_worker = worker_cls(env_spec, pickled, config,
                                       worker_index=0)
        remote_cls = ray_tpu.remote(
            resources={"CPU": config.get("num_cpus_per_worker", 1)})(
            worker_cls)
        self.remote_workers = [
            remote_cls.remote(env_spec, pickled, config, i + 1)
            for i in range(num_workers)
        ]

    def sync_weights(self):
        """Broadcast local (learner) weights to all rollout actors."""
        if not self.remote_workers:
            return
        weights = self.local_worker.get_weights()
        ray_tpu.get([w.set_weights.remote(weights)
                     for w in self.remote_workers], timeout=120)

    def sample(self, num_steps: int | None = None) -> SampleBatch:
        """ParallelRollouts (reference: execution/rollout_ops.py:21):
        gather one fragment from every worker."""
        if not self.remote_workers:
            return self.local_worker.sample(num_steps)
        batches = ray_tpu.get(
            [w.sample.remote(num_steps) for w in self.remote_workers],
            timeout=600)
        if batches and isinstance(batches[0], MultiAgentBatch):
            return MultiAgentBatch.concat_samples(batches)
        return SampleBatch.concat_samples(batches)

    def collect_metrics(self) -> dict:
        metrics = [self.local_worker.get_metrics()]
        if self.remote_workers:
            metrics += ray_tpu.get(
                [w.get_metrics.remote() for w in self.remote_workers],
                timeout=120)
        rewards = [r for m in metrics for r in m["episode_rewards"]]
        lengths = [l for m in metrics for l in m["episode_lengths"]]
        return {
            "episode_reward_mean": (sum(rewards) / len(rewards)
                                    if rewards else float("nan")),
            "episode_reward_min": min(rewards) if rewards else float("nan"),
            "episode_reward_max": max(rewards) if rewards else float("nan"),
            "episode_len_mean": (sum(lengths) / len(lengths)
                                 if lengths else float("nan")),
            "episodes_this_iter": len(rewards),
        }

    def stop(self):
        self.local_worker.stop()
        for w in self.remote_workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.remote_workers = []
