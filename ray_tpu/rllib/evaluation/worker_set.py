"""WorkerSet — local learner worker + remote rollout actors (reference:
rllib/evaluation/worker_set.py:27). On TPU the local worker owns the
jitted learner step; remote workers are CPU actors producing batches."""

from __future__ import annotations

import cloudpickle

import ray_tpu
from ray_tpu.rllib.evaluation.rollout_worker import RolloutWorker
from ray_tpu.rllib.policy.sample_batch import SampleBatch


class WorkerSet:
    def __init__(self, env_spec, policy_builder, config: dict,
                 num_workers: int = 0):
        pickled_builder = cloudpickle.dumps(policy_builder)
        self.local_worker = RolloutWorker(env_spec, pickled_builder, config,
                                          worker_index=0)
        remote_cls = ray_tpu.remote(
            resources={"CPU": config.get("num_cpus_per_worker", 1)})(
            RolloutWorker)
        self.remote_workers = [
            remote_cls.remote(env_spec, pickled_builder, config, i + 1)
            for i in range(num_workers)
        ]

    def sync_weights(self):
        """Broadcast local (learner) weights to all rollout actors."""
        if not self.remote_workers:
            return
        weights = self.local_worker.get_weights()
        ray_tpu.get([w.set_weights.remote(weights)
                     for w in self.remote_workers], timeout=120)

    def sample(self, num_steps: int | None = None) -> SampleBatch:
        """ParallelRollouts (reference: execution/rollout_ops.py:21):
        gather one fragment from every worker."""
        if not self.remote_workers:
            return self.local_worker.sample(num_steps)
        batches = ray_tpu.get(
            [w.sample.remote(num_steps) for w in self.remote_workers],
            timeout=600)
        return SampleBatch.concat_samples(batches)

    def collect_metrics(self) -> dict:
        metrics = [self.local_worker.get_metrics()]
        if self.remote_workers:
            metrics += ray_tpu.get(
                [w.get_metrics.remote() for w in self.remote_workers],
                timeout=120)
        rewards = [r for m in metrics for r in m["episode_rewards"]]
        lengths = [l for m in metrics for l in m["episode_lengths"]]
        return {
            "episode_reward_mean": (sum(rewards) / len(rewards)
                                    if rewards else float("nan")),
            "episode_reward_min": min(rewards) if rewards else float("nan"),
            "episode_reward_max": max(rewards) if rewards else float("nan"),
            "episode_len_mean": (sum(lengths) / len(lengths)
                                 if lengths else float("nan")),
            "episodes_this_iter": len(rewards),
        }

    def stop(self):
        self.local_worker.stop()
        for w in self.remote_workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.remote_workers = []
