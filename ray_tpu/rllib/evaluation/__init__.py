from ray_tpu.rllib.evaluation.rollout_worker import RolloutWorker
from ray_tpu.rllib.evaluation.worker_set import WorkerSet

__all__ = ["RolloutWorker", "WorkerSet"]
