"""Multi-agent rollout worker (reference: rllib/evaluation/rollout_worker.py
multi-agent paths + sampler.py _env_runner; policy mapping per
rllib/policy/policy.py and agents/trainer.py config["multiagent"]).

Each env step: group live agents by the policy that controls them
(policy_mapping_fn), run one batched compute_actions per policy, step the
env with the joint action dict. Trajectories accumulate per agent and are
postprocessed by that agent's policy at episode/fragment end, yielding a
MultiAgentBatch keyed by policy id."""

from __future__ import annotations

import cloudpickle
import numpy as np

from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.policy.sample_batch import MultiAgentBatch, SampleBatch

_COLS = (SampleBatch.OBS, SampleBatch.ACTIONS, SampleBatch.REWARDS,
         SampleBatch.DONES, SampleBatch.NEXT_OBS, SampleBatch.EPS_ID,
         SampleBatch.ACTION_LOGP, SampleBatch.VF_PREDS)


class MultiAgentRolloutWorker:
    """config["multiagent"] = {
        "policies": {pid: (builder|None, obs_space|None, act_space|None,
                           per_policy_config)},
        "policy_mapping_fn": agent_id -> pid,
        "policies_to_train": [pid, ...]  (default: all),
    }
    `spec` arrives cloudpickled so driver-defined builders/mapping fns
    reach remote worker actors (same convention as RolloutWorker)."""

    def __init__(self, env_spec, spec: bytes, config: dict | None = None,
                 worker_index: int = 0):
        self.config = dict(config or {})
        self.worker_index = worker_index
        ma = cloudpickle.loads(spec)
        self.policy_mapping_fn = ma["policy_mapping_fn"]
        self.policies_to_train = ma.get("policies_to_train") or list(
            ma["policies"])
        self.env = make_env(env_spec, self.config.get("env_config", {}))
        self.policies = {}
        for pid, (builder, obs_space, act_space, pcfg) in (
                ma["policies"].items()):
            obs_space = obs_space or self.env.observation_space
            act_space = act_space or self.env.action_space
            self.policies[pid] = builder(
                obs_space, act_space, {**self.config, **(pcfg or {})})
        seed = self.config.get("seed")
        obs, _ = self.env.reset(
            seed=None if seed is None else seed + worker_index)
        self._agent_obs: dict = dict(obs)
        self._eps_id = worker_index * 1_000_000
        self._episode_reward = 0.0
        self._episode_len = 0
        self._completed_rewards: list[float] = []
        self._completed_lengths: list[int] = []
        self._buffers: dict = {}  # agent_id -> {col: [..]}

    def _buf(self, agent_id):
        if agent_id not in self._buffers:
            self._buffers[agent_id] = {k: [] for k in _COLS}
        return self._buffers[agent_id]

    def _flush_agent(self, agent_id, out: dict):
        """Postprocess one agent's finished fragment into its policy's
        batch list."""
        buf = self._buffers.pop(agent_id, None)
        if not buf or not buf[SampleBatch.OBS]:
            return
        pid = self.policy_mapping_fn(agent_id)
        batch = SampleBatch({k: np.asarray(v) for k, v in buf.items()})
        out.setdefault(pid, []).append(
            self.policies[pid].postprocess_trajectory(batch))

    def sample(self, num_steps: int | None = None) -> MultiAgentBatch:
        horizon = num_steps or self.config.get("rollout_fragment_length",
                                               200)
        out: dict = {}
        env_steps = 0
        while env_steps < horizon:
            # group live agents by policy, one batched forward per policy
            by_policy: dict = {}
            for agent_id, obs in self._agent_obs.items():
                by_policy.setdefault(
                    self.policy_mapping_fn(agent_id), []).append(agent_id)
            actions: dict = {}
            extras: dict = {}
            for pid, agent_ids in by_policy.items():
                obs_batch = np.stack([
                    np.asarray(self._agent_obs[a], np.float32).ravel()
                    for a in agent_ids])
                acts, extra = self.policies[pid].compute_actions(obs_batch)
                for i, a in enumerate(agent_ids):
                    act = acts[i]
                    env_act = (int(act) if self.policies[pid].discrete
                               else act)
                    actions[a] = env_act
                    extras[a] = (obs_batch[i], acts[i],
                                 extra[SampleBatch.ACTION_LOGP][i],
                                 extra[SampleBatch.VF_PREDS][i])
            next_obs, rewards, terminated, truncated, _ = self.env.step(
                actions)
            env_steps += 1
            term_all = bool(terminated.get("__all__"))
            # truncation ends the episode but keeps dones=False so
            # postprocessing bootstraps the tail (same convention as
            # rollout_worker.py)
            done_all = term_all or bool(truncated.get("__all__"))
            for agent_id in actions:
                obs_row, act_row, logp, vf = extras[agent_id]
                term = bool(terminated.get(agent_id, term_all))
                buf = self._buf(agent_id)
                buf[SampleBatch.OBS].append(obs_row)
                buf[SampleBatch.ACTIONS].append(act_row)
                buf[SampleBatch.REWARDS].append(
                    np.float32(rewards.get(agent_id, 0.0)))
                buf[SampleBatch.DONES].append(term)
                nxt = next_obs.get(agent_id)
                buf[SampleBatch.NEXT_OBS].append(
                    obs_row if nxt is None
                    else np.asarray(nxt, np.float32).ravel())
                buf[SampleBatch.EPS_ID].append(self._eps_id)
                buf[SampleBatch.ACTION_LOGP].append(logp)
                buf[SampleBatch.VF_PREDS].append(vf)
                self._episode_reward += float(rewards.get(agent_id, 0.0))
                if term or (agent_id not in next_obs and not done_all):
                    self._flush_agent(agent_id, out)
            self._episode_len += 1
            if done_all:
                for agent_id in list(self._buffers):
                    self._flush_agent(agent_id, out)
                self._completed_rewards.append(self._episode_reward)
                self._completed_lengths.append(self._episode_len)
                self._episode_reward = 0.0
                self._episode_len = 0
                self._eps_id += 1
                next_obs, _ = self.env.reset()
            self._agent_obs = dict(next_obs)
        for agent_id in list(self._buffers):
            self._flush_agent(agent_id, out)
        return MultiAgentBatch(
            {pid: SampleBatch.concat_samples(bs)
             for pid, bs in out.items()}, env_steps)

    # -- learner/weights plumbing ---------------------------------------

    def learn_on_batch(self, batch: MultiAgentBatch) -> dict:
        metrics = {}
        for pid in self.policies_to_train:
            pb = batch.policy_batches.get(pid)
            if pb is not None and pb.count:
                metrics[pid] = self.policies[pid].learn_on_batch(pb)
        return metrics

    def get_weights(self):
        return {pid: p.get_weights() for pid, p in self.policies.items()}

    def set_weights(self, weights):
        for pid, w in weights.items():
            self.policies[pid].set_weights(w)
        return True

    def get_metrics(self) -> dict:
        out = {"episode_rewards": list(self._completed_rewards),
               "episode_lengths": list(self._completed_lengths)}
        self._completed_rewards = []
        self._completed_lengths = []
        return out

    def stop(self):
        try:
            self.env.close()
        except Exception:
            pass
