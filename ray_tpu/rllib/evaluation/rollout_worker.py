"""RolloutWorker — runs the policy in env(s) to produce SampleBatches
(reference: rllib/evaluation/rollout_worker.py:74; sample :655,
learn_on_batch :839). Vectorized over num_envs with a python loop (CPU
actors; the jitted policy batches the forward pass across envs)."""

from __future__ import annotations

import cloudpickle
import numpy as np

from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.policy.sample_batch import SampleBatch


class RolloutWorker:
    def __init__(self, env_spec, policy_builder: bytes | None = None,
                 config: dict | None = None, worker_index: int = 0):
        """policy_builder: cloudpickled fn(obs_space, act_space, config)
        -> Policy. Pickled so driver-defined builders reach worker actors."""
        self.config = dict(config or {})
        self.worker_index = worker_index
        num_envs = self.config.get("num_envs_per_worker", 1)
        env_config = dict(self.config.get("env_config", {}))
        self.envs = [make_env(env_spec, env_config) for _ in range(num_envs)]
        base_seed = self.config.get("seed")
        self._obs = []
        for i, env in enumerate(self.envs):
            seed = (None if base_seed is None
                    else base_seed + worker_index * 1000 + i)
            obs, _ = env.reset(seed=seed)
            self._obs.append(obs)
        self._eps_ids = [worker_index * 1_000_000 + i
                        for i in range(num_envs)]
        self._next_eps = worker_index * 1_000_000 + num_envs
        self._episode_rewards = [0.0] * num_envs
        self._completed_rewards: list[float] = []
        self._completed_lengths: list[int] = []
        self._episode_lengths = [0] * num_envs
        builder = cloudpickle.loads(policy_builder)
        # worker_index rides in the config so builders can vary per
        # worker (e.g. APEX's spread of exploration epsilons)
        self.policy = builder(self.envs[0].observation_space,
                              self.envs[0].action_space,
                              {**self.config,
                               "worker_index": worker_index})
        # recurrent policies thread (h, c) per env across steps and
        # fragments (reference: rollout_worker's state_in/state_out cols)
        self._is_recurrent = getattr(self.policy, "is_recurrent", False)
        if self._is_recurrent:
            self._states = [
                [s.copy() for s in self.policy.get_initial_state()]
                for _ in range(num_envs)]
        self._unroll_counter = worker_index * 10_000_000
        # offline IO (reference: rollout_worker.py input_creator/
        # output_creator wiring of rllib/offline/)
        self._output_writer = None
        if self.config.get("output"):
            from ray_tpu.rllib.offline import JsonWriter

            self._output_writer = JsonWriter(self.config["output"])
        self._input_reader = None
        if self.config.get("input") and self.config["input"] != "sampler":
            from ray_tpu.rllib.offline import JsonReader

            self._input_reader = JsonReader(self.config["input"])

    def sample(self, num_steps: int | None = None) -> SampleBatch:
        """Collect `num_steps` total env steps (across the env vector).

        Columns come out env-major (each env's fragment contiguous in
        time) so split_by_episode/GAE see real trajectories. DONES means
        *terminated*: truncated episodes reset the env but keep
        dones=False so GAE bootstraps their tail with the value fn."""
        if self._input_reader is not None:
            return self._input_reader.next()
        horizon = num_steps or self.config.get("rollout_fragment_length",
                                               200)
        n = len(self.envs)
        cols_keys = [
            SampleBatch.OBS, SampleBatch.ACTIONS, SampleBatch.REWARDS,
            SampleBatch.DONES, SampleBatch.NEXT_OBS, SampleBatch.EPS_ID,
            SampleBatch.ACTION_LOGP, SampleBatch.VF_PREDS]
        if self._is_recurrent:
            from ray_tpu.rllib.policy.recurrent_policy import (STATE_C,
                                                               STATE_H,
                                                               UNROLL_ID)

            cols_keys += [STATE_H, STATE_C, UNROLL_ID]
            unroll_ids = []
            for _ in range(n):
                unroll_ids.append(self._unroll_counter)
                self._unroll_counter += 1
        per_env: list[dict[str, list]] = [
            {k: [] for k in cols_keys} for _ in range(n)]
        steps = 0
        while steps < horizon:
            obs_batch = np.stack([np.asarray(o, np.float32).ravel()
                                  for o in self._obs])
            if self._is_recurrent:
                state_in = [np.stack([s[j] for s in self._states])
                            for j in range(2)]
                actions, extra, state_out = (
                    self.policy.compute_actions_with_state(
                        obs_batch, state_in))
            else:
                actions, extra = self.policy.compute_actions(obs_batch)
            for i, env in enumerate(self.envs):
                act = actions[i]
                if not self.policy.discrete:
                    act = np.clip(act, env.action_space.low,
                                  env.action_space.high)
                next_obs, reward, terminated, truncated, _ = env.step(
                    act if not hasattr(env.action_space, "n")
                    else int(act))
                cols = per_env[i]
                cols[SampleBatch.OBS].append(obs_batch[i])
                cols[SampleBatch.ACTIONS].append(actions[i])
                cols[SampleBatch.REWARDS].append(np.float32(reward))
                cols[SampleBatch.DONES].append(bool(terminated))
                cols[SampleBatch.NEXT_OBS].append(
                    np.asarray(next_obs, np.float32).ravel())
                cols[SampleBatch.EPS_ID].append(self._eps_ids[i])
                cols[SampleBatch.ACTION_LOGP].append(
                    extra[SampleBatch.ACTION_LOGP][i])
                cols[SampleBatch.VF_PREDS].append(
                    extra[SampleBatch.VF_PREDS][i])
                if self._is_recurrent:
                    cols[STATE_H].append(state_in[0][i])
                    cols[STATE_C].append(state_in[1][i])
                    cols[UNROLL_ID].append(unroll_ids[i])
                    self._states[i] = [state_out[0][i], state_out[1][i]]
                self._episode_rewards[i] += float(reward)
                self._episode_lengths[i] += 1
                if terminated or truncated:
                    self._completed_rewards.append(self._episode_rewards[i])
                    self._completed_lengths.append(self._episode_lengths[i])
                    self._episode_rewards[i] = 0.0
                    self._episode_lengths[i] = 0
                    self._eps_ids[i] = self._next_eps
                    self._next_eps += 1
                    if self._is_recurrent:
                        self._states[i] = [
                            s.copy()
                            for s in self.policy.get_initial_state()]
                    next_obs, _ = env.reset()
                self._obs[i] = next_obs
                steps += 1
        batch = SampleBatch.concat_samples([
            SampleBatch({k: np.asarray(v) for k, v in cols.items()})
            for cols in per_env])
        batch = self.policy.postprocess_trajectory(batch)
        if self._output_writer is not None:
            self._output_writer.write(batch)
        return batch

    # -- learner/weights plumbing ---------------------------------------

    def learn_on_batch(self, batch: SampleBatch) -> dict:
        return self.policy.learn_on_batch(batch)

    def sample_and_gradients(self, num_steps: int | None = None):
        """Sample a fragment and compute (but don't apply) gradients on it
        — the A3C async-gradients unit (reference:
        execution/rollout_ops.py:92 AsyncGradients)."""
        batch = self.sample(num_steps)
        grads, info = self.policy.compute_gradients(batch)
        info["batch_count"] = batch.count
        return grads, info

    def apply_gradients(self, grads):
        self.policy.apply_gradients(grads)
        return True

    def get_weights(self):
        return self.policy.get_weights()

    def set_weights(self, weights):
        self.policy.set_weights(weights)
        return True

    def get_metrics(self) -> dict:
        """Drain completed-episode stats (reference:
        collect_metrics/evaluation/metrics.py)."""
        out = {
            "episode_rewards": list(self._completed_rewards),
            "episode_lengths": list(self._completed_lengths),
        }
        self._completed_rewards = []
        self._completed_lengths = []
        return out

    def stop(self):
        if self._output_writer is not None:
            self._output_writer.close()
        for env in self.envs:
            try:
                env.close()
            except Exception:
                pass
