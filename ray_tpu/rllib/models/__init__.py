from ray_tpu.rllib.models.catalog import ModelCatalog

__all__ = ["ModelCatalog"]
