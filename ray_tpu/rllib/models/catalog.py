"""ModelCatalog — pick + build a policy network for a space (reference:
rllib/models/catalog.py:167 ModelCatalog.get_model_v2 and the
fcnet/visionnet defaults). jax-functional: each model is an
(init(key) -> params, apply(params, obs) -> out) pair; flat observation
spaces get the fcnet, image-shaped (H, W, C) spaces the conv stack."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

MODEL_DEFAULTS: dict = {
    # reference: rllib/models/catalog.py MODEL_DEFAULTS
    "fcnet_hiddens": [64, 64],
    "fcnet_activation": "tanh",
    "conv_filters": [(16, 4, 2), (32, 4, 2), (64, 3, 1)],  # (out, k, stride)
    "conv_activation": "relu",
    # recurrent wrapper (reference: models/tf/recurrent_net.py LSTMWrapper)
    "use_lstm": False,
    "lstm_cell_size": 64,
    "max_seq_len": 20,
    # attention wrapper (reference: models/tf/attention_net.py GTrXL):
    # memory = a window of K past encodings attended over per step
    "use_attention": False,
    "attention_memory": 8,
}

_ACTS = {"tanh": jnp.tanh, "relu": jax.nn.relu,
         "swish": jax.nn.swish, "linear": lambda x: x}


def _fc_init(key, sizes):
    params = []
    for n_in, n_out in zip(sizes[:-1], sizes[1:]):
        k, key = jax.random.split(key)
        params.append({"w": jax.random.normal(k, (n_in, n_out))
                       / math.sqrt(n_in),
                       "b": jnp.zeros(n_out)})
    return params


def _fc_apply(params, x, act, final_linear=True):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1 or not final_linear:
            x = act(x)
    return x


def _lstm_init(key, in_dim, cell):
    k1, k2 = jax.random.split(key)
    b = jnp.zeros(4 * cell)
    # forget-gate bias 1.0: the standard keep-memory-early init
    b = b.at[cell:2 * cell].set(1.0)
    return {"wx": jax.random.normal(k1, (in_dim, 4 * cell))
            / math.sqrt(in_dim),
            "wh": jax.random.normal(k2, (cell, 4 * cell))
            / math.sqrt(cell),
            "b": b}


def _lstm_step(p, x, h, c):
    z = x @ p["wx"] + h @ p["wh"] + p["b"]
    i, f, g, o = jnp.split(z, 4, axis=-1)
    c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return h, c


class ModelCatalog:
    @staticmethod
    def get_model_config(config: dict | None = None) -> dict:
        return {**MODEL_DEFAULTS, **(config or {})}

    @staticmethod
    def get_model(obs_space, num_outputs: int, config: dict | None = None):
        """-> (init(key) -> params, apply(params, obs[B,...]) -> [B,out])"""
        cfg = ModelCatalog.get_model_config(config)
        shape = tuple(obs_space.shape)
        if len(shape) == 3:
            return ModelCatalog._convnet(shape, num_outputs, cfg)
        return ModelCatalog._fcnet(int(np.prod(shape)), num_outputs, cfg)

    # -- fcnet (reference: models/catalog.py fcnet path) -----------------

    @staticmethod
    def _fcnet(obs_dim: int, num_outputs: int, cfg: dict):
        sizes = [obs_dim] + list(cfg["fcnet_hiddens"]) + [num_outputs]
        act = _ACTS[cfg["fcnet_activation"]]

        def init(key):
            return {"fc": _fc_init(key, sizes)}

        def apply(params, obs):
            x = obs.reshape(obs.shape[0], -1)
            return _fc_apply(params["fc"], x, act)

        return init, apply

    # -- recurrent (reference: models/tf/recurrent_net.py LSTMWrapper) ---

    @staticmethod
    def get_recurrent_model(obs_space, num_outputs: int,
                            config: dict | None = None):
        """fc encoder → LSTM → linear head, for partially-observable
        envs. Returns (init, step, seq, cell_size):

            init(key) -> params
            step(params, obs[B, D], (h, c))   -> (out[B, O], (h, c))
            seq(params, obs[B, T, D], (h0, c0), resets[B, T])
                -> (out[B, T, O], (h, c))     # lax.scan over time;
                                              # resets=1 zeroes the state
                                              # BEFORE consuming that step
                                              # (episode boundary)
        """
        cfg = ModelCatalog.get_model_config(config)
        obs_dim = int(np.prod(obs_space.shape))
        cell = int(cfg["lstm_cell_size"])
        enc_sizes = [obs_dim] + list(cfg["fcnet_hiddens"])
        act = _ACTS[cfg["fcnet_activation"]]

        def init(key):
            k1, k2, k3 = jax.random.split(key, 3)
            return {"enc": _fc_init(k1, enc_sizes),
                    "lstm": _lstm_init(k2, enc_sizes[-1], cell),
                    "head": _fc_init(k3, [cell, num_outputs])}

        def _encode(params, obs):
            return _fc_apply(params["enc"], obs, act, final_linear=False)

        def step(params, obs, state):
            h, c = state
            x = _encode(params, obs.reshape(obs.shape[0], -1))
            h, c = _lstm_step(params["lstm"], x, h, c)
            return _fc_apply(params["head"], h, act), (h, c)

        def seq(params, obs, state, resets):
            x = _encode(params, obs)          # [B, T, enc]
            xt = jnp.swapaxes(x, 0, 1)        # [T, B, enc]
            rt = jnp.swapaxes(resets, 0, 1)   # [T, B]

            def body(carry, inp):
                h, c = carry
                xi, ri = inp
                keep = (1.0 - ri)[:, None]
                h, c = _lstm_step(params["lstm"], xi, h * keep, c * keep)
                return (h, c), h

            state, hs = jax.lax.scan(body, state, (xt, rt))
            out = _fc_apply(params["head"], jnp.swapaxes(hs, 0, 1), act)
            return out, state

        return init, step, seq, cell

    # -- attention memory (reference: models/tf/attention_net.py) --------

    @staticmethod
    def get_attention_model(obs_space, num_outputs: int,
                            config: dict | None = None):
        """fc encoder → single-head attention over a K-slot memory of
        past encodings → linear head. Same (init, step, seq, state
        sizes) contract as get_recurrent_model, with state = (memory
        [K*enc] flattened, validity [K]); resets zero both, which
        empties the memory."""
        cfg = ModelCatalog.get_model_config(config)
        obs_dim = int(np.prod(obs_space.shape))
        mem_k = int(cfg["attention_memory"])
        enc_sizes = [obs_dim] + list(cfg["fcnet_hiddens"])
        enc = enc_sizes[-1]
        act = _ACTS[cfg["fcnet_activation"]]

        def init(key):
            k1, k2, k3, k4, k5 = jax.random.split(key, 5)
            scale = 1.0 / math.sqrt(enc)
            return {"enc": _fc_init(k1, enc_sizes),
                    "attn": {
                        "wq": jax.random.normal(k2, (enc, enc)) * scale,
                        "wk": jax.random.normal(k3, (enc, enc)) * scale,
                        "wv": jax.random.normal(k4, (enc, enc)) * scale,
                    },
                    "head": _fc_init(k5, [2 * enc, num_outputs])}

        def _encode(params, obs):
            return _fc_apply(params["enc"], obs, act, final_linear=False)

        def _attend(params, e, mem, valid):
            # e [B, enc]; mem [B, K, enc]; valid [B, K]
            a = params["attn"]
            q = e @ a["wq"]
            k = mem @ a["wk"]
            v = mem @ a["wv"]
            scores = jnp.einsum("be,bke->bk", q, k) / math.sqrt(enc)
            scores = jnp.where(valid > 0, scores, -1e30)
            # empty memory (episode start): softmax over all -inf would
            # NaN; zero the context instead
            any_valid = (valid.sum(-1, keepdims=True) > 0)
            probs = jax.nn.softmax(scores, axis=-1)
            ctx = jnp.einsum("bk,bke->be", probs, v)
            return jnp.where(any_valid, ctx, 0.0)

        def _cell_from_enc(params, e, state):
            """e [B, enc] (pre-encoded observation)."""
            mem_flat, valid = state
            b = e.shape[0]
            mem = mem_flat.reshape(b, mem_k, enc)
            ctx = _attend(params, e, mem, valid)
            out = _fc_apply(params["head"],
                            jnp.concatenate([e, ctx], -1), act)
            mem = jnp.concatenate([mem[:, 1:], e[:, None]], axis=1)
            valid = jnp.concatenate(
                [valid[:, 1:], jnp.ones((b, 1), valid.dtype)], axis=1)
            return out, (mem.reshape(b, mem_k * enc), valid)

        def step(params, obs, state):
            e = _encode(params, obs.reshape(obs.shape[0], -1))
            return _cell_from_enc(params, e, state)

        def seq(params, obs, state, resets):
            # encoder has no time dependency: one batched [B*T] matmul
            # outside the scan (only the memory update scans)
            e_seq = _encode(params, obs)      # [B, T, enc]
            et = jnp.swapaxes(e_seq, 0, 1)    # [T, B, enc]
            rt = jnp.swapaxes(resets, 0, 1)   # [T, B]

            def body(carry, inp):
                mem, valid = carry
                ei, ri = inp
                keep = (1.0 - ri)[:, None]
                out, (mem, valid) = _cell_from_enc(
                    params, ei, (mem * keep, valid * keep))
                return (mem, valid), out

            state, outs = jax.lax.scan(body, state, (et, rt))
            return jnp.swapaxes(outs, 0, 1), state

        return init, step, seq, (mem_k * enc, mem_k)

    # -- visionnet (reference: models/catalog.py vision path) ------------

    @staticmethod
    def _convnet(shape: tuple, num_outputs: int, cfg: dict):
        h, w, c = shape
        filters = list(cfg["conv_filters"])
        act = _ACTS[cfg["conv_activation"]]

        def init(key):
            params = {"conv": []}
            c_in = c
            hh, ww = h, w
            for out_c, k, s in filters:
                kk, key = jax.random.split(key)
                fan_in = k * k * c_in
                params["conv"].append({
                    "w": jax.random.normal(kk, (k, k, c_in, out_c))
                    / math.sqrt(fan_in),
                    "b": jnp.zeros(out_c),
                })
                hh = (hh - k) // s + 1
                ww = (ww - k) // s + 1
                c_in = out_c
            flat = hh * ww * c_in
            kk, key = jax.random.split(key)
            params["head"] = _fc_init(kk, [flat, 256, num_outputs])
            return params

        def apply(params, obs):
            x = obs.astype(jnp.float32)
            for layer, (_out, k, s) in zip(params["conv"], filters):
                x = jax.lax.conv_general_dilated(
                    x, layer["w"], window_strides=(s, s), padding="VALID",
                    dimension_numbers=("NHWC", "HWIO", "NHWC"))
                x = act(x + layer["b"])
            x = x.reshape(x.shape[0], -1)
            return _fc_apply(params["head"], x, act)

        return init, apply
