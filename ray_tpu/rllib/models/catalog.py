"""ModelCatalog — pick + build a policy network for a space (reference:
rllib/models/catalog.py:167 ModelCatalog.get_model_v2 and the
fcnet/visionnet defaults). jax-functional: each model is an
(init(key) -> params, apply(params, obs) -> out) pair; flat observation
spaces get the fcnet, image-shaped (H, W, C) spaces the conv stack."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

MODEL_DEFAULTS: dict = {
    # reference: rllib/models/catalog.py MODEL_DEFAULTS
    "fcnet_hiddens": [64, 64],
    "fcnet_activation": "tanh",
    "conv_filters": [(16, 4, 2), (32, 4, 2), (64, 3, 1)],  # (out, k, stride)
    "conv_activation": "relu",
}

_ACTS = {"tanh": jnp.tanh, "relu": jax.nn.relu,
         "swish": jax.nn.swish, "linear": lambda x: x}


def _fc_init(key, sizes):
    params = []
    for n_in, n_out in zip(sizes[:-1], sizes[1:]):
        k, key = jax.random.split(key)
        params.append({"w": jax.random.normal(k, (n_in, n_out))
                       / math.sqrt(n_in),
                       "b": jnp.zeros(n_out)})
    return params


def _fc_apply(params, x, act, final_linear=True):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1 or not final_linear:
            x = act(x)
    return x


class ModelCatalog:
    @staticmethod
    def get_model_config(config: dict | None = None) -> dict:
        return {**MODEL_DEFAULTS, **(config or {})}

    @staticmethod
    def get_model(obs_space, num_outputs: int, config: dict | None = None):
        """-> (init(key) -> params, apply(params, obs[B,...]) -> [B,out])"""
        cfg = ModelCatalog.get_model_config(config)
        shape = tuple(obs_space.shape)
        if len(shape) == 3:
            return ModelCatalog._convnet(shape, num_outputs, cfg)
        return ModelCatalog._fcnet(int(np.prod(shape)), num_outputs, cfg)

    # -- fcnet (reference: models/catalog.py fcnet path) -----------------

    @staticmethod
    def _fcnet(obs_dim: int, num_outputs: int, cfg: dict):
        sizes = [obs_dim] + list(cfg["fcnet_hiddens"]) + [num_outputs]
        act = _ACTS[cfg["fcnet_activation"]]

        def init(key):
            return {"fc": _fc_init(key, sizes)}

        def apply(params, obs):
            x = obs.reshape(obs.shape[0], -1)
            return _fc_apply(params["fc"], x, act)

        return init, apply

    # -- visionnet (reference: models/catalog.py vision path) ------------

    @staticmethod
    def _convnet(shape: tuple, num_outputs: int, cfg: dict):
        h, w, c = shape
        filters = list(cfg["conv_filters"])
        act = _ACTS[cfg["conv_activation"]]

        def init(key):
            params = {"conv": []}
            c_in = c
            hh, ww = h, w
            for out_c, k, s in filters:
                kk, key = jax.random.split(key)
                fan_in = k * k * c_in
                params["conv"].append({
                    "w": jax.random.normal(kk, (k, k, c_in, out_c))
                    / math.sqrt(fan_in),
                    "b": jnp.zeros(out_c),
                })
                hh = (hh - k) // s + 1
                ww = (ww - k) // s + 1
                c_in = out_c
            flat = hh * ww * c_in
            kk, key = jax.random.split(key)
            params["head"] = _fc_init(kk, [flat, 256, num_outputs])
            return params

        def apply(params, obs):
            x = obs.astype(jnp.float32)
            for layer, (_out, k, s) in zip(params["conv"], filters):
                x = jax.lax.conv_general_dilated(
                    x, layer["w"], window_strides=(s, s), padding="VALID",
                    dimension_numbers=("NHWC", "HWIO", "NHWC"))
                x = act(x + layer["b"])
            x = x.reshape(x.shape[0], -1)
            return _fc_apply(params["head"], x, act)

        return init, apply
