"""ray_tpu.rllib — reinforcement learning (the RLlib equivalent;
reference: rllib/). JAX policies with jitted learner steps; CPU rollout
actors feed the (TPU) learner."""

from ray_tpu.rllib.agents import (A3CTrainer, DQNTrainer, ImpalaTrainer,
                                  PGTrainer, PPOTrainer, Trainer,
                                  build_trainer)
from ray_tpu.rllib.env import (MultiAgentEnv, make_env, register_env)
from ray_tpu.rllib.execution import (LearnerThread, PrioritizedReplayBuffer,
                                     ReplayBuffer)
from ray_tpu.rllib.policy import (JAXPolicy, MultiAgentBatch, Policy,
                                  SampleBatch)

__all__ = [
    "A3CTrainer",
    "DQNTrainer",
    "ImpalaTrainer",
    "JAXPolicy",
    "LearnerThread",
    "MultiAgentBatch",
    "MultiAgentEnv",
    "PGTrainer",
    "PPOTrainer",
    "Policy",
    "PrioritizedReplayBuffer",
    "ReplayBuffer",
    "SampleBatch",
    "Trainer",
    "build_trainer",
    "make_env",
    "register_env",
]
