"""SampleBatch — columnar trajectory storage (reference:
rllib/policy/sample_batch.py:17 SampleBatch, :525 MultiAgentBatch)."""

from __future__ import annotations

import numpy as np

# canonical column names (reference: SampleBatch class attrs)
OBS = "obs"
NEXT_OBS = "new_obs"
ACTIONS = "actions"
REWARDS = "rewards"
DONES = "dones"
INFOS = "infos"
EPS_ID = "eps_id"
ACTION_LOGP = "action_logp"
VF_PREDS = "vf_preds"
ADVANTAGES = "advantages"
VALUE_TARGETS = "value_targets"


class SampleBatch(dict):
    """dict[str, np.ndarray] with equal first dims."""

    OBS = OBS
    NEXT_OBS = NEXT_OBS
    ACTIONS = ACTIONS
    REWARDS = REWARDS
    DONES = DONES
    EPS_ID = EPS_ID
    ACTION_LOGP = ACTION_LOGP
    VF_PREDS = VF_PREDS
    ADVANTAGES = ADVANTAGES
    VALUE_TARGETS = VALUE_TARGETS

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        for k, v in list(self.items()):
            if not isinstance(v, np.ndarray):
                self[k] = np.asarray(v)
        lengths = {v.shape[0] for v in self.values()
                   if isinstance(v, np.ndarray) and v.ndim}
        if len(lengths) > 1:
            raise ValueError(f"ragged SampleBatch columns: { {k: v.shape for k, v in self.items()} }")

    @property
    def count(self) -> int:
        for v in self.values():
            return int(v.shape[0])
        return 0

    def __len__(self) -> int:  # row count, matching the reference
        return self.count

    @staticmethod
    def concat_samples(batches: list["SampleBatch"]) -> "SampleBatch":
        if not batches:
            return SampleBatch()
        keys = set(batches[0])
        for b in batches[1:]:
            keys &= set(b)
        return SampleBatch({
            k: np.concatenate([b[k] for b in batches], axis=0)
            for k in keys
        })

    def concat(self, other: "SampleBatch") -> "SampleBatch":
        return SampleBatch.concat_samples([self, other])

    def slice(self, start: int, end: int) -> "SampleBatch":
        return SampleBatch({k: v[start:end] for k, v in self.items()})

    def shuffle(self, rng: np.random.RandomState | None = None):
        idx = (rng or np.random).permutation(self.count)
        for k in self:
            self[k] = self[k][idx]
        return self

    def split_by_episode(self) -> list["SampleBatch"]:
        if EPS_ID not in self:
            return [self]
        out = []
        eps = self[EPS_ID]
        boundaries = np.where(eps[1:] != eps[:-1])[0] + 1
        prev = 0
        for b in list(boundaries) + [self.count]:
            if b > prev:
                out.append(self.slice(prev, b))
            prev = b
        return out

    def minibatches(self, size: int, rng=None):
        """Shuffled minibatch views for SGD epochs."""
        idx = (rng or np.random).permutation(self.count)
        for start in range(0, self.count, size):
            sel = idx[start:start + size]
            yield SampleBatch({k: v[sel] for k, v in self.items()})


class MultiAgentBatch:
    """policy_id -> SampleBatch (reference: sample_batch.py:525)."""

    def __init__(self, policy_batches: dict[str, SampleBatch], count: int):
        self.policy_batches = policy_batches
        self.count = count

    @staticmethod
    def concat_samples(batches: list["MultiAgentBatch"]) -> "MultiAgentBatch":
        keys = {k for b in batches for k in b.policy_batches}
        return MultiAgentBatch(
            {k: SampleBatch.concat_samples(
                [b.policy_batches[k] for b in batches
                 if k in b.policy_batches]) for k in keys},
            sum(b.count for b in batches))
