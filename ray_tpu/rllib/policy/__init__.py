from ray_tpu.rllib.policy.jax_policy import JAXPolicy
from ray_tpu.rllib.policy.policy import Policy
from ray_tpu.rllib.policy.sample_batch import MultiAgentBatch, SampleBatch

__all__ = ["JAXPolicy", "MultiAgentBatch", "Policy", "SampleBatch"]
