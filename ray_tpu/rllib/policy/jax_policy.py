"""JAXPolicy — functional actor-critic MLP with jitted action sampling and
a pluggable jitted loss (reference: rllib/policy/torch_policy.py shape;
model: rllib/models/catalog.py fcnet defaults 2x256 tanh — here 2x64).

All learning state is a pytree (params + opt_state); get/set_weights move
plain numpy across actors."""

from __future__ import annotations

import math
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.policy.policy import Policy
from ray_tpu.rllib.policy.sample_batch import SampleBatch


def _mlp_init(key, sizes):
    params = []
    for i, (n_in, n_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        k1, key = jax.random.split(key)
        params.append({
            "w": jax.random.normal(k1, (n_in, n_out)) / math.sqrt(n_in),
            "b": jnp.zeros(n_out),
        })
    return params


def _mlp_apply(params, x, final_linear=True):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1 or not final_linear:
            x = jnp.tanh(x)
    return x


def categorical_logp(logits, actions):
    logp = jax.nn.log_softmax(logits)
    return jnp.take_along_axis(
        logp, actions[:, None].astype(jnp.int32), axis=1)[:, 0]


def categorical_entropy(logits):
    logp = jax.nn.log_softmax(logits)
    return -jnp.sum(jnp.exp(logp) * logp, axis=-1)


def gaussian_logp(mean_logstd, actions):
    mean, log_std = jnp.split(mean_logstd, 2, axis=-1)
    var = jnp.exp(2 * log_std)
    return jnp.sum(
        -0.5 * ((actions - mean) ** 2 / var)
        - log_std - 0.5 * math.log(2 * math.pi), axis=-1)


def gaussian_entropy(mean_logstd):
    _, log_std = jnp.split(mean_logstd, 2, axis=-1)
    return jnp.sum(log_std + 0.5 * math.log(2 * math.pi * math.e), axis=-1)


class JAXPolicy(Policy):
    """loss_fn(params, batch_jnp, model_fns, config) -> (loss, metrics)."""

    def __init__(self, observation_space, action_space, config: dict,
                 loss_fn: Callable | None = None):
        super().__init__(observation_space, action_space, config)
        import optax

        obs_dim = int(np.prod(observation_space.shape))
        hiddens = list(config.get("fcnet_hiddens", [64, 64]))
        self.discrete = hasattr(action_space, "n")
        if self.discrete:
            act_out = int(action_space.n)
        else:
            act_dim = int(np.prod(action_space.shape))
            act_out = 2 * act_dim  # mean + log_std

        seed = config.get("seed")
        seed = 0 if seed is None else seed
        key = jax.random.key(seed)
        k1, k2 = jax.random.split(key)
        self.params = {
            "pi": _mlp_init(k1, [obs_dim] + hiddens + [act_out]),
            "vf": _mlp_init(k2, [obs_dim] + hiddens + [1]),
        }
        self._optimizer = optax.adam(config.get("lr", 5e-4))
        self.opt_state = self._optimizer.init(self.params)
        self._loss_fn = loss_fn
        self._rng = jax.random.key(seed + 1)
        self._build()

    # -- model fns (used by losses too) ---------------------------------

    @staticmethod
    def model_out(params, obs):
        return (_mlp_apply(params["pi"], obs),
                _mlp_apply(params["vf"], obs)[:, 0])

    def logp_fn(self):
        return categorical_logp if self.discrete else gaussian_logp

    def entropy_fn(self):
        return categorical_entropy if self.discrete else gaussian_entropy

    def _build(self):
        discrete = self.discrete

        @jax.jit
        def act(params, obs, rng):
            pi_out, vf = JAXPolicy.model_out(params, obs)
            rng, sub = jax.random.split(rng)
            if discrete:
                actions = jax.random.categorical(sub, pi_out, axis=-1)
                logp = categorical_logp(pi_out, actions)
            else:
                mean, log_std = jnp.split(pi_out, 2, axis=-1)
                noise = jax.random.normal(sub, mean.shape)
                actions = mean + jnp.exp(log_std) * noise
                logp = gaussian_logp(pi_out, actions)
            return actions, logp, vf, rng

        @jax.jit
        def act_greedy(params, obs):
            pi_out, vf = JAXPolicy.model_out(params, obs)
            if discrete:
                actions = jnp.argmax(pi_out, axis=-1)
            else:
                actions, _ = jnp.split(pi_out, 2, axis=-1)
            return actions, vf

        self._act = act
        self._act_greedy = act_greedy

        if self._loss_fn is not None:
            loss_fn = self._loss_fn
            optimizer = self._optimizer
            policy = self

            @jax.jit
            def sgd_step(params, opt_state, batch):
                (loss, metrics), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params, batch, policy)
                updates, opt_state = optimizer.update(grads, opt_state,
                                                      params)
                params = jax.tree.map(lambda p, u: p + u, params, updates)
                return params, opt_state, loss, metrics

            @jax.jit
            def grad_step(params, batch):
                (loss, metrics), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params, batch, policy)
                return grads, loss, metrics

            @jax.jit
            def apply_step(params, opt_state, grads):
                updates, opt_state = optimizer.update(grads, opt_state,
                                                      params)
                params = jax.tree.map(lambda p, u: p + u, params, updates)
                return params, opt_state

            self._sgd_step = sgd_step
            self._grad_step = grad_step
            self._apply_step = apply_step

    # -- Policy interface ------------------------------------------------

    def compute_actions(self, obs_batch, explore=True):
        obs = jnp.asarray(obs_batch, jnp.float32).reshape(
            len(obs_batch), -1)
        if explore:
            actions, logp, vf, self._rng = self._act(
                self.params, obs, self._rng)
        else:
            actions, vf = self._act_greedy(self.params, obs)
            logp = jnp.zeros(len(obs_batch))
        return (np.asarray(actions),
                {SampleBatch.ACTION_LOGP: np.asarray(logp),
                 SampleBatch.VF_PREDS: np.asarray(vf)})

    def compute_log_likelihoods(self, obs_batch, actions) -> np.ndarray:
        """logp of given actions under the current policy (reference:
        rllib/policy/policy.py compute_log_likelihoods; used by the
        offline IS/WIS estimators)."""
        obs = jnp.asarray(obs_batch, jnp.float32).reshape(
            len(obs_batch), -1)
        pi_out, _ = JAXPolicy.model_out(self.params, obs)
        return np.asarray(self.logp_fn()(pi_out, jnp.asarray(actions)))

    def compute_values(self, obs_batch) -> np.ndarray:
        obs = jnp.asarray(obs_batch, jnp.float32).reshape(
            len(obs_batch), -1)
        _, vf = JAXPolicy.model_out(self.params, obs)
        return np.asarray(vf)

    # Columns losses never read — skipped at host->device transfer time
    # (NEXT_OBS alone would double the obs volume shipped per minibatch).
    _NON_LOSS_COLUMNS = frozenset({
        SampleBatch.EPS_ID, SampleBatch.NEXT_OBS, SampleBatch.DONES,
        "infos",
    })

    def learn_on_batch(self, batch: SampleBatch) -> dict:
        jb = {k: jnp.asarray(v) for k, v in batch.items()
              if k not in self._NON_LOSS_COLUMNS and v.dtype != object}
        self.params, self.opt_state, loss, metrics = self._sgd_step(
            self.params, self.opt_state, jb)
        out = {"total_loss": float(loss)}
        out.update({k: float(v) for k, v in metrics.items()})
        return out

    def compute_gradients(self, batch: SampleBatch):
        """Gradients without applying them (reference:
        rllib/policy/policy.py compute_gradients; used by AsyncGradients
        execution/rollout_ops.py:92). Returns (numpy grad pytree, info)."""
        jb = {k: jnp.asarray(v) for k, v in batch.items()
              if k not in self._NON_LOSS_COLUMNS and v.dtype != object}
        grads, loss, metrics = self._grad_step(self.params, jb)
        info = {"total_loss": float(loss)}
        info.update({k: float(v) for k, v in metrics.items()})
        return jax.tree.map(np.asarray, grads), info

    def apply_gradients(self, grads):
        """reference: rllib/policy/policy.py apply_gradients."""
        self.params, self.opt_state = self._apply_step(
            self.params, self.opt_state, jax.tree.map(jnp.asarray, grads))

    def get_weights(self):
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights):
        self.params = jax.tree.map(jnp.asarray, weights)
