"""Recurrent (LSTM) actor-critic policy — unlocks partially-observable
envs (reference: rllib/models/tf/recurrent_net.py LSTMWrapper +
policy/rnn_sequencing.py chop_into_sequences).

Rollout side: the policy is stateful per env — compute_actions_with_state
threads (h, c) and the RolloutWorker records each step's INPUT state plus
an unroll id. Learn side: rows are regrouped into their original unrolls
(rnn_sequencing's job), chopped to max_seq_len, padded + masked, and the
whole update runs as one jitted lax.scan over time with episode-boundary
resets — truncated BPTT initialized from the sampled states."""

from __future__ import annotations

from typing import Any

import numpy as np

from ray_tpu.rllib.models.catalog import ModelCatalog
from ray_tpu.rllib.policy.jax_policy import (categorical_entropy,
                                             categorical_logp,
                                             gaussian_entropy,
                                             gaussian_logp)
from ray_tpu.rllib.policy.policy import Policy
from ray_tpu.rllib.policy.sample_batch import SampleBatch

RECURRENT_DEFAULTS = {
    "lr": 1e-3,
    "gamma": 0.99,
    "vf_loss_coeff": 0.5,
    "entropy_coeff": 0.01,
    "lstm_cell_size": 64,
    "max_seq_len": 20,
    "fcnet_hiddens": [64],
}

# extra sample columns (reference: rnn_sequencing "state_in_0"... cols)
STATE_H = "state_in_h"
STATE_C = "state_in_c"
UNROLL_ID = "unroll_id"


def chop_sequences(batch, state_sizes, max_t: int,
                   value_cols: dict) -> dict:
    """Chop rows into [S, T]-padded sequences along stored unrolls
    (reference: policy/rnn_sequencing.py chop_into_sequences). Shared by
    every sequence-trained policy (RecurrentPG, R2D2).

    value_cols: {out_name: np.ndarray[rows, ...]} per-step columns to
    sequence alongside obs/actions; outputs also carry resets (episode
    boundaries within a sequence), mask (padding), and the h0/c0 initial
    states sampled at each sequence's first step."""
    obs = batch[SampleBatch.OBS].astype(np.float32)
    obs = obs.reshape(len(obs), -1)
    actions = batch[SampleBatch.ACTIONS]
    eps = batch[SampleBatch.EPS_ID]
    unroll = batch[UNROLL_ID]
    sh = batch[STATE_H].astype(np.float32)
    sc = batch[STATE_C].astype(np.float32)

    seqs = []  # (start, length) within one unroll
    start = 0
    for t in range(1, len(obs) + 1):
        boundary = (t == len(obs) or unroll[t] != unroll[start]
                    or t - start == max_t)
        if boundary:
            seqs.append((start, t - start))
            start = t
    s_n = len(seqs)
    cols = {
        "obs": np.zeros((s_n, max_t, obs.shape[1]), np.float32),
        "actions": np.zeros((s_n, max_t) + actions.shape[1:],
                            actions.dtype),
        "resets": np.zeros((s_n, max_t), np.float32),
        "mask": np.zeros((s_n, max_t), np.float32),
        "h0": np.zeros((s_n, state_sizes[0]), np.float32),
        "c0": np.zeros((s_n, state_sizes[1]), np.float32),
    }
    for name, v in value_cols.items():
        cols[name] = np.zeros((s_n, max_t) + v.shape[1:], v.dtype)
    for si, (s0, ln) in enumerate(seqs):
        sl = slice(s0, s0 + ln)
        cols["obs"][si, :ln] = obs[sl]
        cols["actions"][si, :ln] = actions[sl]
        cols["mask"][si, :ln] = 1.0
        cols["h0"][si] = sh[s0]
        cols["c0"][si] = sc[s0]
        for name, v in value_cols.items():
            cols[name][si, :ln] = v[sl]
        e = eps[sl]
        cols["resets"][si, 1:ln] = (e[1:] != e[:-1]).astype(np.float32)
    return cols


class RecurrentPGPolicy(Policy):
    """LSTM actor-critic trained with an advantage policy gradient
    (A2C-style: whole-batch update, no sequence-breaking minibatches)."""

    is_recurrent = True

    def __init__(self, observation_space, action_space, config: dict):
        import jax
        import optax

        merged = {**RECURRENT_DEFAULTS, **config}
        super().__init__(observation_space, action_space, merged)
        self.discrete = hasattr(action_space, "n")
        if self.discrete:
            act_out = int(action_space.n)
        else:
            act_out = 2 * int(np.prod(action_space.shape))
        # one trunk, two outputs: [pi_out | value]
        self._act_out = act_out
        if merged.get("use_attention"):
            init, step, seq, sizes = ModelCatalog.get_attention_model(
                observation_space, act_out + 1, merged)
        else:
            init, step, seq, cell = ModelCatalog.get_recurrent_model(
                observation_space, act_out + 1, merged)
            sizes = (cell, cell)
        self._step_fn = jax.jit(step)
        self._seq_fn = seq
        # two state arrays per env (LSTM: h/c; attention: memory/valid)
        self.state_sizes = tuple(sizes)
        self.cell_size = sizes[0]
        seed = merged.get("seed") or 0
        self.params = init(jax.random.key(seed))
        self._optimizer = optax.adam(merged["lr"])
        self.opt_state = self._optimizer.init(self.params)
        self._rng = jax.random.key(seed + 1)
        self._build()

    def get_initial_state(self) -> list[np.ndarray]:
        return [np.zeros(s, np.float32) for s in self.state_sizes]

    # -- acting ----------------------------------------------------------

    def _split_out(self, out):
        import jax.numpy as jnp

        return out[..., :self._act_out], out[..., -1]

    def _build(self):
        import jax
        import jax.numpy as jnp

        discrete = self.discrete
        step = self._step_fn
        seq = self._seq_fn
        vf_coeff = self.config["vf_loss_coeff"]
        ent_coeff = self.config["entropy_coeff"]
        optimizer = self._optimizer
        act_out_n = self._act_out

        @jax.jit
        def act(params, obs, h, c, rng):
            out, (h2, c2) = step(params, obs, (h, c))
            pi_out, vf = out[..., :act_out_n], out[..., -1]
            rng, sub = jax.random.split(rng)
            if discrete:
                actions = jax.random.categorical(sub, pi_out, axis=-1)
                logp = categorical_logp(pi_out, actions)
            else:
                mean, log_std = jnp.split(pi_out, 2, axis=-1)
                actions = mean + jnp.exp(log_std) * jax.random.normal(
                    sub, mean.shape)
                logp = gaussian_logp(pi_out, actions)
            return actions, logp, vf, h2, c2, rng

        @jax.jit
        def act_greedy(params, obs, h, c):
            out, (h2, c2) = step(params, obs, (h, c))
            pi_out, vf = out[..., :act_out_n], out[..., -1]
            if discrete:
                actions = jnp.argmax(pi_out, axis=-1)
            else:
                actions, _ = jnp.split(pi_out, 2, axis=-1)
            return actions, vf, h2, c2

        def loss_fn(params, batch):
            # batch: obs [S,T,D], resets [S,T], mask [S,T], h0/c0 [S,cell]
            out, _ = seq(params, batch["obs"], (batch["h0"], batch["c0"]),
                         batch["resets"])
            pi_out, values = out[..., :act_out_n], out[..., -1]
            flat_pi = pi_out.reshape(-1, pi_out.shape[-1])
            flat_act = batch["actions"].reshape(
                -1, *batch["actions"].shape[2:])
            if discrete:
                logp = categorical_logp(flat_pi, flat_act.reshape(-1))
                entropy = categorical_entropy(flat_pi)
            else:
                logp = gaussian_logp(flat_pi, flat_act)
                entropy = gaussian_entropy(flat_pi)
            mask = batch["mask"].reshape(-1)
            n = jnp.maximum(mask.sum(), 1.0)
            returns = batch["returns"].reshape(-1)
            adv = returns - jax.lax.stop_gradient(values.reshape(-1))
            adv = (adv - (adv * mask).sum() / n) * mask
            pi_loss = -(logp * jax.lax.stop_gradient(adv) * mask).sum() / n
            vf_loss = (((values.reshape(-1) - returns) ** 2) * mask
                       ).sum() / n
            ent = (entropy * mask).sum() / n
            total = pi_loss + vf_coeff * vf_loss - ent_coeff * ent
            return total, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                           "entropy": ent}

        @jax.jit
        def sgd_step(params, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = jax.tree.map(lambda p, u: p + u, params, updates)
            return params, opt_state, loss, metrics

        self._act = act
        self._act_greedy = act_greedy
        self._sgd_step = sgd_step

    def compute_actions_with_state(self, obs_batch, states,
                                   explore: bool = True):
        import jax.numpy as jnp

        obs = jnp.asarray(obs_batch, jnp.float32).reshape(
            len(obs_batch), -1)
        h = jnp.asarray(states[0], jnp.float32)
        c = jnp.asarray(states[1], jnp.float32)
        if explore:
            actions, logp, vf, h2, c2, self._rng = self._act(
                self.params, obs, h, c, self._rng)
        else:
            actions, vf, h2, c2 = self._act_greedy(self.params, obs, h, c)
            logp = np.zeros(len(obs_batch))
        extra = {SampleBatch.ACTION_LOGP: np.asarray(logp),
                 SampleBatch.VF_PREDS: np.asarray(vf)}
        return (np.asarray(actions), extra,
                [np.asarray(h2), np.asarray(c2)])

    def compute_actions(self, obs_batch, explore: bool = True):
        # stateless call (evaluate() greedy loops): zero state per call
        states = [np.zeros((len(obs_batch), s), np.float32)
                  for s in self.state_sizes]
        acts, extra, _ = self.compute_actions_with_state(
            obs_batch, states, explore)
        return acts, extra

    def _value_after(self, obs_last, next_obs, h, c):
        """V(next_obs) with the state that follows the fragment's last
        step — the exact bootstrap for truncated episodes."""
        import jax.numpy as jnp

        obs_last = jnp.asarray(obs_last, jnp.float32)[None]
        next_obs = jnp.asarray(next_obs, jnp.float32)[None]
        state = (jnp.asarray(h, jnp.float32)[None],
                 jnp.asarray(c, jnp.float32)[None])
        _, state = self._step_fn(self.params, obs_last, state)
        out, _ = self._step_fn(self.params, next_obs, state)
        return float(out[0, -1])

    # -- learning --------------------------------------------------------

    def postprocess_trajectory(self, batch, other_agent_batches=None,
                               episode=None):
        from ray_tpu.rllib.agents.pg import discounted_returns

        out = []
        for eb in batch.split_by_episode():
            if eb[SampleBatch.DONES][-1]:
                last_value = 0.0
            else:
                last_value = self._value_after(
                    eb[SampleBatch.OBS][-1], eb[SampleBatch.NEXT_OBS][-1],
                    eb[STATE_H][-1], eb[STATE_C][-1])
            eb[SampleBatch.ADVANTAGES] = discounted_returns(
                eb[SampleBatch.REWARDS].astype(np.float64),
                eb[SampleBatch.DONES].astype(np.float64),
                self.config["gamma"], last_value)
            out.append(eb)
        return SampleBatch.concat_samples(out)

    def _sequence(self, batch: SampleBatch) -> dict:
        """Chop rows into [S, T] padded sequences along stored unrolls
        (reference: policy/rnn_sequencing.py chop_into_sequences)."""
        import jax.numpy as jnp

        cols = chop_sequences(
            batch, self.state_sizes, int(self.config["max_seq_len"]),
            {"returns": batch[SampleBatch.ADVANTAGES].astype(np.float32)})
        return {k: jnp.asarray(v) for k, v in cols.items()}

    def learn_on_batch(self, batch: SampleBatch) -> dict:
        jb = self._sequence(batch)
        self.params, self.opt_state, loss, metrics = self._sgd_step(
            self.params, self.opt_state, jb)
        out = {"total_loss": float(loss)}
        out.update({k: float(v) for k, v in metrics.items()})
        return out

    def get_weights(self):
        import jax

        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights):
        import jax.numpy as jnp
        import jax

        self.params = jax.tree.map(jnp.asarray, weights)
