"""Policy base (reference: rllib/policy/policy.py) + the JAX policy the
reference only sketched (rllib/models/jax/fcnet.py, jax_modelv2.py) built
out fully: functional MLP model, jitted act/loss, optax updates.

TPU note: learn_on_batch is one jitted step over stacked minibatches —
on a TPU learner the whole SGD epoch stays on-device; rollout workers
stay CPU actors feeding it (the reference's IMPALA/PPO split)."""

from __future__ import annotations

from typing import Any

import numpy as np


class Policy:
    # Recurrent policies set True, implement get_initial_state() and
    # compute_actions_with_state(); the RolloutWorker threads (h, c)
    # per env (reference: policy/policy.py is_recurrent /
    # get_initial_state)
    is_recurrent = False

    def __init__(self, observation_space, action_space, config: dict):
        self.observation_space = observation_space
        self.action_space = action_space
        self.config = config

    def get_initial_state(self) -> list:
        return []

    def compute_actions(self, obs_batch: np.ndarray, explore: bool = True,
                        ) -> tuple[np.ndarray, dict]:
        """-> (actions, extra_fetches: {action_logp, vf_preds, ...})"""
        raise NotImplementedError

    def learn_on_batch(self, batch) -> dict:
        raise NotImplementedError

    def get_weights(self) -> Any:
        raise NotImplementedError

    def set_weights(self, weights: Any):
        raise NotImplementedError

    def postprocess_trajectory(self, batch, other_agent_batches=None,
                               episode=None):
        return batch
