"""Execution building blocks (reference: rllib/execution/)."""

from ray_tpu.rllib.execution.learner_thread import LearnerThread
from ray_tpu.rllib.execution.replay_buffer import (PrioritizedReplayBuffer,
                                                   ReplayBuffer)

__all__ = ["LearnerThread", "PrioritizedReplayBuffer", "ReplayBuffer"]
