"""Replay buffers (reference: rllib/execution/replay_buffer.py:71
ReplayBuffer, :183 PrioritizedReplayBuffer). Differences by design: flat
numpy ring storage per column instead of per-item pickled samples (one
vectorized gather per sample() — no per-row python loop on the hot path),
and proportional prioritization via a simple cumulative-sum search rather
than a segment tree (sample() is O(batch * log n) with numpy searchsorted;
updates are O(1))."""

from __future__ import annotations

import numpy as np

from ray_tpu.rllib.policy.sample_batch import SampleBatch


class ReplayBuffer:
    """Uniform ring-buffer replay over SampleBatch rows."""

    def __init__(self, capacity: int, seed: int | None = None):
        self.capacity = int(capacity)
        self._cols: dict[str, np.ndarray] = {}
        self._size = 0
        self._next = 0
        self._rng = np.random.RandomState(seed)
        self._added = 0

    def __len__(self) -> int:
        return self._size

    @property
    def added_count(self) -> int:
        return self._added

    def add_batch(self, batch: SampleBatch):
        n = batch.count
        if n == 0:
            return
        for k, v in batch.items():
            if k not in self._cols:
                self._cols[k] = np.zeros((self.capacity,) + v.shape[1:],
                                         dtype=v.dtype)
        idx = (self._next + np.arange(n)) % self.capacity
        for k, v in batch.items():
            self._cols[k][idx] = v[:self.capacity]
        self._next = int((self._next + n) % self.capacity)
        self._size = min(self.capacity, self._size + n)
        self._added += n

    def sample_idx(self, batch_size: int) -> np.ndarray:
        return self._rng.randint(0, self._size, size=batch_size)

    def sample(self, batch_size: int) -> SampleBatch:
        idx = self.sample_idx(batch_size)
        return SampleBatch({k: v[idx] for k, v in self._cols.items()})


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (reference: replay_buffer.py:183;
    Schaul et al. 2015). sample() also returns importance weights and the
    indices to pass back to update_priorities()."""

    def __init__(self, capacity: int, alpha: float = 0.6,
                 seed: int | None = None):
        super().__init__(capacity, seed)
        self.alpha = float(alpha)
        self._prio = np.zeros(capacity, dtype=np.float64)
        self._max_prio = 1.0

    def add_batch(self, batch: SampleBatch):
        n = batch.count
        idx = (self._next + np.arange(n)) % self.capacity
        super().add_batch(batch)
        self._prio[idx] = self._max_prio ** self.alpha

    def sample(self, batch_size: int, beta: float = 0.4):
        p = self._prio[:self._size]
        total = p.sum()
        if total <= 0:
            idx = self.sample_idx(batch_size)
            weights = np.ones(batch_size, np.float32)
        else:
            cum = np.cumsum(p)
            targets = self._rng.random_sample(batch_size) * total
            idx = np.searchsorted(cum, targets).clip(0, self._size - 1)
            probs = p[idx] / total
            weights = (self._size * probs) ** (-beta)
            weights = (weights / weights.max()).astype(np.float32)
        out = SampleBatch({k: v[idx] for k, v in self._cols.items()})
        out["weights"] = weights
        out["batch_indexes"] = idx.astype(np.int64)
        return out

    def update_priorities(self, idx: np.ndarray, priorities: np.ndarray):
        priorities = np.abs(priorities) + 1e-6
        self._prio[idx] = priorities ** self.alpha
        self._max_prio = max(self._max_prio, float(priorities.max()))
