"""LearnerThread — decouple gradient steps from sample collection
(reference: rllib/execution/learner_thread.py:16): rollout actors keep
producing while a background thread drains a bounded queue into
learn_on_batch. On TPU this is what keeps the chip busy: host-side env
stepping and device-side SGD overlap instead of alternating."""

from __future__ import annotations

import queue
import threading
import time


class LearnerThread(threading.Thread):
    def __init__(self, local_worker, max_queue: int = 16):
        super().__init__(daemon=True, name="rllib-learner")
        self.local_worker = local_worker
        self.inqueue: queue.Queue = queue.Queue(maxsize=max_queue)
        self.outqueue: queue.Queue = queue.Queue()
        self.stopped = False
        self.learner_info: dict = {}
        self.num_steps_trained = 0
        self.queue_wait_s = 0.0
        self.grad_time_s = 0.0

    def run(self):
        while not self.stopped:
            t0 = time.perf_counter()
            try:
                batch = self.inqueue.get(timeout=0.5)
            except queue.Empty:
                continue
            t1 = time.perf_counter()
            info = self.local_worker.learn_on_batch(batch)
            t2 = time.perf_counter()
            self.queue_wait_s += t1 - t0
            self.grad_time_s += t2 - t1
            self.learner_info = info
            self.num_steps_trained += batch.count
            self.outqueue.put((batch.count, info))

    def stop(self):
        self.stopped = True

    def stats(self) -> dict:
        return {
            "learner_queue_size": self.inqueue.qsize(),
            "num_steps_trained": self.num_steps_trained,
            "learner_grad_time_s": round(self.grad_time_s, 3),
            "learner_queue_wait_s": round(self.queue_wait_s, 3),
            **{f"learner/{k}": v for k, v in self.learner_info.items()},
        }
