"""Offline RL IO (reference: rllib/offline/ — json_writer.py JsonWriter,
json_reader.py JsonReader, is_estimator.py ImportanceSampling,
wis_estimator.py WeightedImportanceSampling, off_policy_estimator.py).

Batches are stored as JSON lines; numpy columns round-trip via nested
lists + dtype tags so files are greppable and language-neutral."""

from __future__ import annotations

import glob
import json
import os
from typing import Iterator

import numpy as np

from ray_tpu.rllib.policy.sample_batch import SampleBatch

__all__ = ["ImportanceSampling", "JsonReader", "JsonWriter",
           "WeightedImportanceSampling"]


def _encode(batch: SampleBatch) -> str:
    return json.dumps({
        k: {"dtype": str(v.dtype), "data": v.tolist()}
        for k, v in batch.items() if v.dtype != object
    })


def _decode(line: str) -> SampleBatch:
    raw = json.loads(line)
    return SampleBatch({
        k: np.asarray(v["data"], dtype=np.dtype(v["dtype"]))
        for k, v in raw.items()
    })


class JsonWriter:
    """Append SampleBatches to rolling .json files in a directory
    (reference: rllib/offline/json_writer.py:26)."""

    def __init__(self, path: str, max_file_size: int = 64 * 1024 * 1024):
        import uuid

        self.path = path
        self.max_file_size = max_file_size
        os.makedirs(path, exist_ok=True)
        self._file = None
        self._index = 0
        self._uid = uuid.uuid4().hex[:8]

    def _rollover(self):
        if self._file:
            self._file.close()
        # unique per writer instance: pid alone collides across container
        # restarts (pid 1) — uuid suffix makes runs append-safe
        name = os.path.join(
            self.path,
            f"output-{os.getpid()}-{self._uid}-{self._index:05d}.json")
        self._index += 1
        self._file = open(name, "x")

    def write(self, batch: SampleBatch):
        if (self._file is None
                or self._file.tell() >= self.max_file_size):
            self._rollover()
        self._file.write(_encode(batch) + "\n")
        self._file.flush()

    def close(self):
        if self._file:
            self._file.close()
            self._file = None


class JsonReader:
    """Read batches back; next() cycles forever for training loops
    (reference: rllib/offline/json_reader.py:30)."""

    def __init__(self, path: str):
        if os.path.isdir(path):
            self.files = sorted(glob.glob(os.path.join(path, "*.json")))
        else:
            self.files = sorted(glob.glob(path))
        if not self.files:
            raise FileNotFoundError(f"no offline data under {path!r}")
        self._cycle = None

    def read_all(self) -> list[SampleBatch]:
        out = []
        for f in self.files:
            with open(f) as fh:
                out.extend(_decode(l) for l in fh if l.strip())
        return out

    def __iter__(self) -> Iterator[SampleBatch]:
        return iter(self.read_all())

    def next(self) -> SampleBatch:
        if self._cycle is None:
            self._cycle = self.read_all()
            self._pos = 0
        b = self._cycle[self._pos % len(self._cycle)]
        self._pos += 1
        return b


class _OffPolicyEstimator:
    """reference: rllib/offline/off_policy_estimator.py:23. Requires the
    behaviour policy's action_logp in the batch (reference raises the
    same requirement)."""

    def __init__(self, policy, gamma: float = 0.99):
        self.policy = policy
        self.gamma = gamma

    def _episode_ratios(self, episode: SampleBatch):
        if SampleBatch.ACTION_LOGP not in episode:
            raise ValueError(
                "off-policy estimation needs batch['action_logp'] from "
                "the behaviour policy")
        new_logp = self.policy.compute_log_likelihoods(
            episode[SampleBatch.OBS], episode[SampleBatch.ACTIONS])
        ratios = np.exp(new_logp - episode[SampleBatch.ACTION_LOGP])
        return np.cumprod(ratios)

    def _discounted(self, rewards: np.ndarray) -> np.ndarray:
        return rewards * (self.gamma ** np.arange(len(rewards)))


class ImportanceSampling(_OffPolicyEstimator):
    """V^pi estimate: mean over episodes of sum_t gamma^t * p_{0:t} * r_t
    (reference: rllib/offline/is_estimator.py)."""

    def estimate(self, batch: SampleBatch) -> dict:
        vals = []
        behaviour = []
        for ep in batch.split_by_episode():
            p = self._episode_ratios(ep)
            r = self._discounted(
                ep[SampleBatch.REWARDS].astype(np.float64))
            vals.append(float(np.sum(p * r)))
            behaviour.append(float(np.sum(r)))
        return {"v_es": float(np.mean(vals)),
                "v_behaviour": float(np.mean(behaviour)),
                "episodes": len(vals)}


class WeightedImportanceSampling(_OffPolicyEstimator):
    """Self-normalized IS: per-step ratios normalized by their mean over
    episodes — lower variance, slight bias (reference:
    rllib/offline/wis_estimator.py)."""

    def estimate(self, batch: SampleBatch) -> dict:
        episodes = batch.split_by_episode()
        ratios = [self._episode_ratios(ep) for ep in episodes]
        max_t = max(len(p) for p in ratios)
        # mean cumulative ratio at each t across episodes present at t
        norm = np.zeros(max_t)
        counts = np.zeros(max_t)
        for p in ratios:
            norm[:len(p)] += p
            counts[:len(p)] += 1
        norm = norm / np.maximum(counts, 1)
        vals = []
        behaviour = []
        for ep, p in zip(episodes, ratios):
            r = self._discounted(
                ep[SampleBatch.REWARDS].astype(np.float64))
            w = p / np.maximum(norm[:len(p)], 1e-12)
            vals.append(float(np.sum(w * r)))
            behaviour.append(float(np.sum(r)))
        return {"v_es": float(np.mean(vals)),
                "v_behaviour": float(np.mean(behaviour)),
                "episodes": len(vals)}
