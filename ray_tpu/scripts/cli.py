"""ray-tpu CLI — out-of-process cluster lifecycle (reference:
python/ray/scripts/scripts.py — `ray start` :439, `ray stop` :582,
`ray status` :1412, `ray memory` :1389, `ray microbenchmark` :1346).

Two-shell flow:
    shell A:  ray-tpu start --head
    shell B:  RAY_TPU_ADDRESS=<printed addr> python my_driver.py
              (driver calls ray_tpu.init(address="auto"))
    shell A:  ray-tpu stop

Cluster bookkeeping lives in <tmpdir>/cluster.json so stop/status/memory
find the processes without arguments."""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import subprocess
import sys
import time


def _tmpdir() -> str:
    return os.environ.get("RAY_TPU_TMPDIR", "/tmp/ray_tpu")


def _cluster_file() -> str:
    return os.path.join(_tmpdir(), "cluster.json")


def _load_cluster() -> dict | None:
    try:
        with open(_cluster_file()) as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return None


def _save_cluster(rec: dict):
    os.makedirs(_tmpdir(), exist_ok=True)
    tmp = _cluster_file() + ".tmp"
    with open(tmp, "w") as f:
        json.dump(rec, f, indent=1)
    os.rename(tmp, _cluster_file())


def _rpc_call(address: str, method: str, data=None):
    from ray_tpu._private import rpc

    async def _go():
        conn = await rpc.connect(address, name="cli", timeout=5)
        try:
            return await conn.call(method, data or {}, timeout=10)
        finally:
            await conn.close()

    return asyncio.run(_go())


# ---------------------------------------------------------------------------
# start / stop
# ---------------------------------------------------------------------------

def cmd_start(args) -> int:
    from ray_tpu._private.config import Config, set_config
    from ray_tpu._private.node import new_session_dir, start_gcs, start_raylet

    config = Config.load(json.loads(args.system_config)
                         if args.system_config else None)
    set_config(config)
    pids: list[int] = []

    if args.head:
        session_dir = new_session_dir()
        gcs_svc, gcs_address = start_gcs(session_dir, config,
                                         port=args.port or config.gcs_port)
        pids.append(gcs_svc.proc.pid)
    else:
        if not args.address:
            print("error: worker nodes need --address <gcs host:port>",
                  file=sys.stderr)
            return 2
        gcs_address = args.address
        rec = _load_cluster()
        session_dir = (rec or {}).get("session_dir") or new_session_dir()

    raylet_svc, raylet_addr, node_id, _store = start_raylet(
        session_dir, gcs_address, config,
        num_cpus=args.num_cpus, num_tpus=args.num_tpus or 0,
        resources=json.loads(args.resources) if args.resources else None,
        tpu_slice=(json.loads(args.tpu_slice)
                   if getattr(args, "tpu_slice", None) else None),
        is_head=args.head)
    pids.append(raylet_svc.proc.pid)

    client_port = None
    if args.head and args.client_server_port is not None:
        # Ray-Client analog: remote drivers connect here with no local
        # runtime (reference: `ray start --ray-client-server-port`).
        # Spawned like the other services (_spawn: config overrides via
        # child_env, TPU-plugin env stripped) and health-checked via the
        # ready file, which also reports the actual port for --port 0.
        import uuid as _uuid

        from ray_tpu._private.node import _spawn, _wait_ready

        ready = os.path.join(session_dir,
                             f"client_ready_{_uuid.uuid4().hex[:6]}")
        svc = _spawn([
            sys.executable, "-m", "ray_tpu.util.client.server",
            "--address", gcs_address,
            "--port", str(args.client_server_port),
            "--ready-file", ready,
        ], config, "client_server")
        client_port = int(_wait_ready(ready, svc.proc, "client_server",
                                      timeout=60))
        pids.append(svc.proc.pid)

    rec = _load_cluster() if not args.head else None
    if rec is None:
        rec = {"gcs_address": gcs_address, "session_dir": session_dir,
               "pids": []}
    rec["pids"].extend(pids)
    if client_port is not None:
        rec["client_server_port"] = client_port
    _save_cluster(rec)

    role = "head" if args.head else "worker node"
    print(f"started {role}: node {node_id.hex()[:8]} raylet {raylet_addr}")
    print(f"GCS address: {gcs_address}")
    if client_port is not None:
        print(f"client server port: {client_port} "
              f"(ray_tpu.util.client.connect('<host>:{client_port}'))")
    print(f"session dir: {session_dir}")
    print()
    print("connect a driver with:")
    print(f"    export RAY_TPU_ADDRESS={gcs_address}")
    print("    python -c 'import ray_tpu; ray_tpu.init(address=\"auto\")'")
    return 0


def cmd_stop(args) -> int:
    rec = _load_cluster()
    if rec is None:
        print("no cluster record found; nothing to stop")
        return 0
    killed = 0
    for pid in rec.get("pids", []):
        try:
            os.killpg(os.getpgid(pid), signal.SIGTERM)
            killed += 1
        except (ProcessLookupError, PermissionError):
            try:
                os.kill(pid, signal.SIGTERM)
                killed += 1
            except (ProcessLookupError, PermissionError):
                pass
    time.sleep(0.5)
    for pid in rec.get("pids", []):
        try:
            os.killpg(os.getpgid(pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
    try:
        os.unlink(_cluster_file())
    except FileNotFoundError:
        pass
    print(f"stopped {killed} process group(s)")
    return 0


# ---------------------------------------------------------------------------
# status / memory
# ---------------------------------------------------------------------------

def _gcs_address(args) -> str | None:
    if getattr(args, "address", None):
        return args.address
    if os.environ.get("RAY_TPU_ADDRESS"):
        return os.environ["RAY_TPU_ADDRESS"]
    rec = _load_cluster()
    return rec["gcs_address"] if rec else None


def _fmt_resources(raw: dict) -> str:
    from ray_tpu._private.common import ResourceSet

    d = ResourceSet.from_raw(raw).to_dict()
    return ", ".join(f"{k}={v:g}" for k, v in sorted(d.items()))


def cmd_status(args) -> int:
    """reference: scripts.py:1412 `ray status` — node table + resources."""
    addr = _gcs_address(args)
    if not addr:
        print("no cluster found (no --address, RAY_TPU_ADDRESS, or record)",
              file=sys.stderr)
        return 1
    nodes = _rpc_call(addr, "get_all_nodes")
    avail = _rpc_call(addr, "get_available_resources")
    print(f"cluster at {addr}: {len(nodes)} node(s)")
    for n in nodes:
        a = avail.get(n["node_id"], {})
        head = " (head)" if n.get("is_head") else ""
        print(f"  node {n['node_id'].hex()[:8]}{head} @ {n['address']} "
              f"[{n.get('hostname', '')}]")
        print(f"    total:     {_fmt_resources(n['resources'])}")
        print(f"    available: {_fmt_resources(a) if a else '(no heartbeat)'}")
    return 0


def cmd_drain(args) -> int:
    """Graceful scale-down of one node: ALIVE -> DRAINING (stops taking
    leases/spillback, migrates its objects, checkpoints restartable
    actors) -> DRAINED. The node argument is an id prefix (as printed by
    `ray-tpu status`) or a raylet address."""
    addr = _gcs_address(args)
    if not addr:
        print("no cluster found", file=sys.stderr)
        return 1
    nodes = _rpc_call(addr, "get_all_nodes")
    want = args.node.lower()
    matches = [n for n in nodes
               if n["node_id"].hex().startswith(want)
               or n["address"] == args.node]
    if not matches:
        print(f"no node matches {args.node!r}", file=sys.stderr)
        return 1
    if len(matches) > 1:
        print(f"{args.node!r} is ambiguous: "
              + ", ".join(n["node_id"].hex()[:8] for n in matches),
              file=sys.stderr)
        return 1
    node = matches[0]
    if node.get("is_head"):
        print("refusing to drain the head node (use `ray-tpu stop`)",
              file=sys.stderr)
        return 1
    reply = _rpc_call(addr, "drain_node", {
        "node_id": node["node_id"],
        "preempt": bool(args.preempt),
    })
    print(f"node {node['node_id'].hex()[:8]}: {reply.get('state')}")
    if not args.wait:
        return 0
    import time as _time

    deadline = _time.monotonic() + args.timeout
    while _time.monotonic() < deadline:
        left = _rpc_call(addr, "get_all_nodes")
        if all(n["node_id"] != node["node_id"] for n in left):
            print(f"node {node['node_id'].hex()[:8]}: DRAINED")
            return 0
        _time.sleep(0.5)
    print(f"node {node['node_id'].hex()[:8]}: still draining after "
          f"{args.timeout:.0f}s", file=sys.stderr)
    return 1


def cmd_memory(args) -> int:
    """reference: scripts.py:1389 `ray memory` — object store usage."""
    addr = _gcs_address(args)
    if not addr:
        print("no cluster found", file=sys.stderr)
        return 1
    nodes = _rpc_call(addr, "get_all_nodes")
    total_used = total_objects = 0
    for n in nodes:
        try:
            info = _rpc_call(n["address"], "cluster_info")
        except Exception as e:
            print(f"  node {n['node_id'].hex()[:8]}: unreachable ({e})")
            continue
        used = info["store_used"]
        cnt = info["num_local_objects"]
        total_used += used
        total_objects += cnt
        print(f"  node {n['node_id'].hex()[:8]} @ {n['address']}: "
              f"{cnt} object(s), {used / 1e6:.1f} MB in store, "
              f"{info['num_workers']} worker(s)")
    print(f"total: {total_objects} object(s), {total_used / 1e6:.1f} MB")
    return 0


def cmd_metrics(args) -> int:
    """reference: the `ray status -v` / metrics export surface
    (src/ray/stats/metric.h)."""
    addr = _gcs_address(args)
    if not addr:
        print("no cluster found", file=sys.stderr)
        return 1

    def show(title, snap):
        print(title)
        for name in sorted(snap):
            m = snap[name]
            if m["type"] == "histogram":
                print(f"  {name}: n={m['count']} sum={m['sum']:.3f}")
            else:
                print(f"  {name}: {m['value']:g}")

    show("gcs:", _rpc_call(addr, "get_metrics"))
    for n in _rpc_call(addr, "get_all_nodes"):
        try:
            snap = _rpc_call(n["address"], "get_metrics")
        except Exception as e:
            print(f"node {n['node_id'].hex()[:8]}: unreachable ({e})")
            continue
        show(f"node {n['node_id'].hex()[:8]}:", snap)
    return 0


def cmd_timeline(args) -> int:
    """reference: `ray timeline` (scripts.py) — chrome-trace dump."""
    addr = _gcs_address(args)
    if not addr:
        print("no cluster found", file=sys.stderr)
        return 1
    from ray_tpu._private.profiling import to_chrome_trace

    trace = to_chrome_trace(_rpc_call(addr, "get_profile_events"))
    out = args.out or "timeline.json"
    with open(out, "w") as f:
        json.dump(trace, f)
    print(f"wrote {len(trace)} events to {out} "
          f"(open in chrome://tracing or Perfetto)")
    return 0


def cmd_compile_cache(args) -> int:
    """Persistent AOT compile-cache contents: the GCS-mirrored index
    when a cluster is reachable (cluster-wide view), else the local
    on-disk index. --clear drops blobs + index (local and mirror)."""
    import time as _time

    from ray_tpu._private import compile_cache as _cc

    addr = _gcs_address(args)
    index = None
    source = "local"
    if addr:
        try:
            raw = _rpc_call(addr, "kv_get", {"key": _cc.KV_INDEX_KEY})
            if raw:
                index = json.loads(
                    raw.decode() if isinstance(raw, bytes) else raw)
                source = "gcs"
        except Exception:
            pass
    if index is None:
        index = _cc.read_index()
    if args.clear:
        n = _cc.clear()
        if addr:
            try:
                _rpc_call(addr, "kv_del", {"key": _cc.KV_INDEX_KEY})
            except Exception:
                pass
        print(f"cleared {n} cached executable(s) from {_cc.cache_dir()}")
        return 0
    if args.json:
        print(json.dumps({"source": source, "dir": _cc.cache_dir(),
                          "state": _cc.state(), "entries": index}))
        return 0
    if not index:
        print(f"compile cache empty ({_cc.cache_dir()})")
        return 0
    print(f"compile cache ({source} index, {len(index)} entries, "
          f"dir {_cc.cache_dir()}):")
    now = _time.time()
    for key in sorted(index, key=lambda k: -index[k].get("created", 0)):
        e = index[key]
        age = now - e.get("created", now)
        parts = ":".join(e.get("parts", [])) or e.get("seam", "?")
        print(f"  {key}  {e.get('seam', '?')}:{parts}  "
              f"{e.get('size', 0)}B  age={age:.0f}s  "
              f"hits={e.get('hits', 0)}")
    return 0


def cmd_trace(args) -> int:
    """Export the GCS trace table (causally-linked cross-process span
    trees, tracing.py) as Perfetto/chrome-trace JSON — the whole table,
    or one tree via --trace-id."""
    addr = _gcs_address(args)
    if not addr:
        print("no cluster found", file=sys.stderr)
        return 1
    from ray_tpu._private.profiling import spans_to_chrome_trace

    rows = _rpc_call(addr, "get_trace_spans",
                     {"trace_id": args.trace_id})
    if not rows:
        print("(no trace spans recorded — is sampling on? see "
              "RAY_TPU_TRACE_SAMPLE / ray_tpu.set_trace_sampling)")
        return 0
    trace = spans_to_chrome_trace(rows)
    out = args.out or "trace.json"
    with open(out, "w") as f:
        json.dump(trace, f)
    traces = {r["extra_data"].get("tid") for r in rows}
    print(f"wrote {len(rows)} spans across {len(traces)} trace(s) to "
          f"{out} (open in Perfetto / chrome://tracing)")
    return 0


def _top_snapshot(reply, flt=None) -> dict:
    """Structured rate/p99 table off one get_metrics_history reply —
    shared by the live text render and `--json --once` (scripts/CI).
    {"meta", "sources": {source: {metric: {latest, ts, rate?, p99_ms?,
    saturated?, exemplar?}}}}."""
    if isinstance(reply, dict) and "series" in reply:
        hist = reply["series"]
        meta = reply.get("meta") or {}
        exemplars = reply.get("exemplars") or {}
    else:  # pre-meta GCS
        hist, meta, exemplars = reply, {}, {}
    sources: dict = {}
    for source in sorted(hist):
        rings = hist[source]
        rows: dict = {}
        for name in sorted(rings):
            series = rings[name]
            if not series or (flt and flt not in name):
                continue
            if name.endswith(".p99_saturated"):
                continue  # folded into the .p99 row below
            ts, val = series[-1]
            row = {"latest": val, "ts": ts}
            if name.endswith(".p99"):
                row["p99_ms"] = val * 1e3
                sat = rings.get(name + "_saturated")
                row["saturated"] = bool(sat and sat[-1][1])
                base = name[:-len(".p99")]
                ex = (exemplars.get(source) or {}).get(base)
                if ex:
                    row["exemplar"] = ex.get("trace_id")
                    row["exemplar_value_ms"] = ex.get("value", 0) * 1e3
            elif len(series) >= 2 and (name.endswith("_total")
                                       or name.endswith(".count")):
                # rate-over-window is only meaningful for counters —
                # a rising gauge (bytes in use) is a level, not a flow
                (t0, v0), (t1, v1) = series[0], series[-1]
                if t1 > t0 and v1 >= v0:
                    row["rate_per_s"] = (v1 - v0) / (t1 - t0)
            rows[name] = row
        if rows:
            sources[source] = rows
    return {"meta": meta, "sources": sources}


def cmd_top(args) -> int:
    """Live cluster metrics view off the GCS time-series ring (the
    `ray-tpu top` analog of `ray status -v`, refreshed in place).
    Shows, per source, the latest sample plus a rate over the window
    for counters and the current p99 for latency histograms — with a
    `≥` marker when the p99 saturated its top bucket and the p99
    exemplar's trace id (resolve it: `ray-tpu trace --trace-id`).
    `--json --once`: one machine-readable snapshot for scripts/CI."""
    import time as _time

    addr = _gcs_address(args)
    if not addr:
        print("no cluster found", file=sys.stderr)
        return 1
    if getattr(args, "once", False) or getattr(args, "json", False):
        # --json is a one-shot machine-readable snapshot: looping would
        # interleave clear-screen escapes into the JSON stream
        args.iterations = 1

    epoch = [None]  # GCS history epoch across renders (reset marker)

    def render() -> int:
        reply = _rpc_call(addr, "get_metrics_history",
                          {"samples": 0, "meta": True})
        snap = _top_snapshot(reply, args.filter)
        if getattr(args, "json", False):
            snap["collected_at"] = _time.time()
            print(json.dumps(snap, indent=1, default=str))
            return len(snap["sources"])
        started = snap["meta"].get("started_at")
        reset = (epoch[0] is not None and started is not None
                 and started != epoch[0])
        if started is not None:
            epoch[0] = started
        lines = []
        if reset:
            # metrics history + trace rings are director-memory-only
            # (documented lossy-restart contract): a restart resets
            # them — render the discontinuity instead of silently
            # splicing fresh samples onto the old view
            lines.append("  ===== history reset: GCS (re)started — "
                         "rings cleared, rates restart from zero =====")
        for source, rows_d in snap["sources"].items():
            rows = []
            newest = 0.0
            for name, row in rows_d.items():
                newest = max(newest, row["ts"])
                if "p99_ms" in row:
                    sat = "≥" if row.get("saturated") else " "
                    ex = (f"  trace={row['exemplar']}"
                          if row.get("exemplar") else "")
                    rows.append(f"    {name:<44}{sat}"
                                f"{row['p99_ms']:8.2f} ms{ex}")
                    continue
                rate = (f"  ({row['rate_per_s']:8.1f}/s)"
                        if "rate_per_s" in row else "")
                rows.append(f"    {name:<44} {row['latest']:12g}{rate}")
            if rows:
                age = _time.time() - newest
                lines.append(f"  {source}  (sample {age:.1f}s old, "
                             f"{len(rows)} metrics)")
                lines.extend(rows)
        print(f"ray-tpu top — {_time.strftime('%H:%M:%S')} — "
              f"{len(snap['sources'])} sources")
        if lines:
            print("\n".join(lines))
        else:
            print("  (no samples yet — history fills on the ~2s "
                  "heartbeat/flush cadence)")
        return len(lines)

    if args.iterations == 1:
        render()
        return 0
    try:
        n = 0
        while args.iterations <= 0 or n < args.iterations:
            if n:
                print("\x1b[2J\x1b[H", end="")  # clear + home
            render()
            n += 1
            if args.iterations <= 0 or n < args.iterations:
                _time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    return 0


def cmd_profile(args) -> int:
    """Cluster-wide CPU flamegraph off the continuous profiling plane:
    collect `--seconds` of sampler windows from the GCS profile ring
    and write collapsed-stack text (flamegraph.pl / speedscope input),
    optionally Perfetto tracks (--perfetto). `--hz` re-arms the
    cluster sampler rate for the window (restored after)."""
    import time as _time

    from ray_tpu._private import sampling_profiler as _sprof

    addr = _gcs_address(args)
    if not addr:
        print("no cluster found", file=sys.stderr)
        return 1
    prev_hz = None
    if args.hz is not None:
        prev_hz = _rpc_call(addr, "kv_get", {"key": _sprof.KV_KEY})
        _rpc_call(addr, "kv_put", {"key": _sprof.KV_KEY,
                                   "value": repr(float(args.hz)).encode()})
    try:
        since = _time.time()
        _time.sleep(max(0.0, args.seconds))
        batches = _sprof.wait_for_coverage(
            lambda: _rpc_call(addr, "get_profile_samples",
                              {"since": since,
                               "component": args.component}),
            args.component)
        classes = _sprof.components_of(batches)
    finally:
        if args.hz is not None:
            # restore the prior override, or b"default" — every process
            # re-derives ITS OWN env/budget rate (writing this host's
            # number would pin a derated node to the CLI box's default)
            _rpc_call(addr, "kv_put", {
                "key": _sprof.KV_KEY,
                "value": prev_hz or b"default"})
    if not batches:
        print("(no profile samples — is the profiler armed? see "
              "RAY_TPU_PROFILE_HZ / ray_tpu.set_profiling)")
        return 1
    collapsed = _sprof.collapse_text(batches, args.component)
    out = args.out or "profile.collapsed"
    if out == "-":
        print(collapsed)
    else:
        with open(out, "w") as f:
            f.write(collapsed + "\n")
    if args.perfetto:
        with open(args.perfetto, "w") as f:
            json.dump(_sprof.samples_to_chrome_trace(batches), f)
    samples = sum(b.get("samples", 0) for b in batches)
    print(f"{samples} samples across {len(classes)} process class(es) "
          f"({', '.join(classes)}); wrote {len(collapsed.splitlines())} "
          f"collapsed stacks to {out}"
          + (f" + Perfetto tracks to {args.perfetto}"
             if args.perfetto else ""))
    return 0


def _fmt_row(row: dict, drop=("process",)) -> str:
    parts = []
    for k, v in row.items():
        if k in drop or v in ("", None, [], {}):
            continue
        parts.append(f"{k}={v}")
    return "  ".join(parts)


def cmd_state(args) -> int:
    """Live cluster introspection (`ray-tpu state [component]`): every
    process's debug_state() aggregated over the rpc plane — no driver
    runtime needed. Without a component: a per-process summary; with
    one (serve|placement|tasks|actors|objects|leases|transfers|
    collectives): flat rows across the cluster, oldest first."""
    addr = _gcs_address(args)
    if not addr:
        print("no cluster found", file=sys.stderr)
        return 1
    from ray_tpu._private import debug_state

    snap = debug_state.collect_via_rpc(
        addr, include_workers=not args.no_workers, timeout=args.timeout)
    if not args.component:
        for label, proc in debug_state.iter_processes(snap):
            if "error" in proc:
                print(f"{label}: UNREACHABLE ({proc['error']})")
                continue
            bits = [f"pid={proc.get('pid')}"]
            lag = proc.get("event_loop_lag_s")
            if lag is not None:
                bits.append(f"loop_lag={lag * 1e3:.1f}ms")
            for key, fmt in (("tasks", "tasks"), ("executing", "exec"),
                             ("leases", "leases"), ("actors", "actors"),
                             ("pending_leases", "lease_queue"),
                             ("worker_pool", "workers"),
                             ("collectives", "collective_groups")):
                n = len(proc.get(key) or [])
                if n:
                    bits.append(f"{fmt}={n}")
            tr = proc.get("transfers") or {}
            n = len(tr.get("pulls") or []) + len(tr.get("serves") or [])
            if n:
                bits.append(f"transfers={n}")
            print(f"{label}: " + "  ".join(bits))
        return 0
    rows = debug_state.flatten(snap, args.component)
    if args.filter:
        rows = [r for r in rows
                if any(args.filter in str(v) for v in r.values())]
    if not rows:
        print(f"(no live {args.component})")
        return 0
    for row in rows:
        print(f"{row.get('process', '?'):<28} {_fmt_row(row)}")
    return 0


def _find_stack_address(snap, target: str):
    """Resolve a `ray-tpu stack` target (pid | worker/node id prefix |
    address) to (label, rpc address) from a cluster snapshot."""
    from ray_tpu._private import debug_state

    for label, proc in debug_state.iter_processes(snap):
        addr = proc.get("address")
        if str(proc.get("pid")) == target:
            return label, addr
        if target and (target in label
                       or (addr and target in addr)
                       or target == proc.get("worker_id", "")[:len(target)]
                       or target == proc.get("node_id", "")):
            return label, addr
    return None, None


def cmd_stack(args) -> int:
    """All-thread Python stacks of any live runtime process
    (sys._current_frames over rpc): `ray-tpu stack gcs`, a pid, a
    node/worker id prefix, or an rpc address."""
    addr = _gcs_address(args)
    if not addr:
        print("no cluster found", file=sys.stderr)
        return 1
    target = args.target
    if target == "gcs":
        label, stacks = "gcs", _rpc_call(addr, "debug_stacks")
    else:
        from ray_tpu._private import debug_state

        snap = debug_state.collect_via_rpc(addr, timeout=args.timeout)
        label, proc_addr = _find_stack_address(snap, target)
        if proc_addr is None:
            print(f"no live process matches {target!r} (try "
                  f"`ray-tpu state` for pids/ids)", file=sys.stderr)
            return 1
        stacks = _rpc_call(proc_addr, "debug_stacks")
    print(f"=== {label} (pid {stacks.get('pid')}), "
          f"{len(stacks.get('threads', []))} thread(s) ===")
    for t in stacks.get("threads", []):
        daemon = " daemon" if t.get("daemon") else ""
        print(f"\n--- thread {t['name']}{daemon} ---")
        print(t["stack"].rstrip())
    return 0


def cmd_doctor(args) -> int:
    """The stall doctor, out of process: collect cluster_state + the
    per-hop latency histograms, flag anything whose age exceeds
    max(floor, K×p99) for its stage, and print each finding with its
    owning process (+ stacks with --stacks). Exit code 1 when stalls
    were found."""
    addr = _gcs_address(args)
    if not addr:
        print("no cluster found", file=sys.stderr)
        return 1
    from ray_tpu._private import debug_state

    snap = debug_state.collect_via_rpc(addr, timeout=args.timeout)
    metrics = {"raylets": {}}
    try:
        metrics["gcs"] = _rpc_call(addr, "get_metrics")
        for n in _rpc_call(addr, "get_all_nodes"):
            try:
                metrics["raylets"][n["node_id"].hex()[:8]] = _rpc_call(
                    n["address"], "get_metrics")
            except Exception:
                pass
    except Exception:
        pass
    findings = debug_state.diagnose(snap, metrics, floor_s=args.floor,
                                    p99_factor=args.p99_factor)
    if not findings:
        print("doctor: no stalls detected "
              f"(floor {args.floor if args.floor is not None else debug_state.DOCTOR_FLOOR_S}s, "
              f"K={args.p99_factor if args.p99_factor is not None else debug_state.DOCTOR_P99_FACTOR})")
        return 0
    seen_procs = set()
    for f in findings:
        tid = f" trace={f['trace_id']}" if f.get("trace_id") else ""
        print(f"STALLED {f['kind']} {f.get('name') or f.get('id')}: "
              f"stage={f['stage']} age={f['age_s']:.1f}s "
              f"(threshold {f['threshold_s']:.1f}s) on {f['process']}"
              f"{tid}  {f.get('detail', '')}")
        if args.stacks and f["process"] not in seen_procs:
            seen_procs.add(f["process"])
            _, proc_addr = _find_stack_address(snap, f["process"])
            if proc_addr:
                try:
                    stacks = _rpc_call(proc_addr, "debug_stacks")
                    for t in stacks.get("threads", []):
                        print(f"  --- {f['process']} thread "
                              f"{t['name']} ---")
                        for line in t["stack"].rstrip().splitlines():
                            print(f"  {line}")
                except Exception as e:
                    print(f"  (stacks unreachable: {e})")
    print(f"{len(findings)} finding(s)")
    return 1


def cmd_submit(args) -> int:
    """Run a driver script against the recorded cluster (reference:
    `ray submit` — there via the cluster launcher; here the cluster is
    local/recorded, so submit = exec with RAY_TPU_ADDRESS wired)."""
    addr = _gcs_address(args)
    if not addr:
        print("no cluster found", file=sys.stderr)
        return 1
    env = dict(os.environ)
    env["RAY_TPU_ADDRESS"] = addr
    # the driver runs with ITS script dir as sys.path[0]; make the
    # framework importable from anywhere the user submits from
    pkg_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    existing = env.get("PYTHONPATH")
    env["PYTHONPATH"] = (pkg_root + os.pathsep + existing
                         if existing else pkg_root)
    cmd = [sys.executable, args.script, *args.script_args]
    return subprocess.call(cmd, env=env)


def cmd_events(args) -> int:
    """reference: the structured-event surface (RAY_EVENT/event.h; the
    reference ships events to its event log dir + dashboard)."""
    import time as _time

    addr = _gcs_address(args)
    if not addr:
        print("no cluster found", file=sys.stderr)
        return 1
    events = _rpc_call(addr, "get_events",
                       {"severity": args.severity, "limit": args.limit})
    for e in events:
        ts = _time.strftime("%H:%M:%S", _time.localtime(e["timestamp"]))
        print(f"{ts} {e['severity']:<7} {e['label']:<14} "
              f"[{e['source_type']}] {e['message']}")
    if not events:
        print("(no events)")
    return 0


def cmd_dashboard(args) -> int:
    """reference: `ray dashboard` / the dashboard head process."""
    addr = _gcs_address(args)
    if not addr:
        print("no cluster found", file=sys.stderr)
        return 1
    from ray_tpu.dashboard import Dashboard

    dash = Dashboard(addr, args.host, args.port)
    asyncio.run(dash.run(ready_cb=lambda p: print(
        f"dashboard at http://{args.host}:{p}", flush=True)))
    return 0


def cmd_debug(args) -> int:
    """Attach to a live rpdb breakpoint (reference: `ray debug`,
    scripts/scripts.py + util/rpdb.py)."""
    addr = _gcs_address(args)
    if not addr:
        print("no cluster found (no --address, RAY_TPU_ADDRESS, or "
              "record)", file=sys.stderr)
        return 2
    import ray_tpu

    ray_tpu.init(address=addr)
    from ray_tpu.util import rpdb

    sessions = rpdb.active_sessions()
    if not sessions:
        print("no active breakpoints (call ray_tpu.util.rpdb.set_trace()"
              " inside a task/actor)")
        return 0
    for i, s in enumerate(sessions):
        print(f"[{i}] pid {s['pid']} at {s['filename']}:{s['lineno']}")
    idx = args.index
    if idx is None:
        if len(sessions) == 1:
            idx = 0
        else:
            try:
                idx = int(input("attach to which breakpoint? "))
            except (ValueError, EOFError):
                print("not a breakpoint number", file=sys.stderr)
                return 2
    if not 0 <= idx < len(sessions):
        print(f"breakpoint index {idx} out of range "
              f"(0..{len(sessions) - 1})", file=sys.stderr)
        return 2
    print(f"attaching to [{idx}] — pdb commands apply remotely "
          f"(c to continue, q to abort the task)")
    try:
        rpdb.connect(sessions[idx])
    except OSError as e:
        print(f"breakpoint unreachable ({e}); it may have just "
              f"finished — rerun `ray-tpu debug`", file=sys.stderr)
        return 1
    return 0


def cmd_up(args) -> int:
    from ray_tpu.autoscaler import launcher

    state = launcher.up(args.config)
    print(f"cluster {state['cluster_name']!r} up: "
          f"{len(state['nodes'])} nodes")
    print(f"GCS address: {state['gcs_address']}")
    print(f"attach with: ray-tpu attach {state['cluster_name']}")
    return 0


def cmd_down(args) -> int:
    from ray_tpu.autoscaler import launcher

    errors = launcher.down(args.cluster)
    if errors:
        print(f"warning: {errors} node(s) failed to stop cleanly",
              file=sys.stderr)
    print("cluster down")
    return 1 if errors else 0


def cmd_attach(args) -> int:
    from ray_tpu.autoscaler import launcher

    cmdline = launcher.attach_command(args.cluster)
    if args.print_only:
        print(cmdline)
        return 0
    import subprocess

    return subprocess.call(cmdline, shell=True)


def cmd_exec(args) -> int:
    from ray_tpu.autoscaler import launcher

    out = launcher.exec_on_head(args.cluster, args.command)
    print(out, end="")
    return 0


def cmd_microbenchmark(args) -> int:
    from ray_tpu import microbenchmark

    out = microbenchmark.main()
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)
    return 0


def cmd_scalesim(args) -> int:
    """Control-plane scale-sim: spoofed raylets against a real GCS
    (director + store shards) on this box — scheduler decisions/s and
    GCS op throughput, interleaved A/B vs the single-shard legacy arm
    (ray_tpu/scalesim/harness.py). --topology runs the placement arm
    instead: ICI_RING vs PACK over spoofed 4x4-torus raylets
    (ray_tpu/scalesim/topology_sim.py). --elastic runs the membership
    ramp arm: drain-aware vs static vs kill-based scale-down scored on
    node-hours x SLO violations (ray_tpu/scalesim/elastic_sim.py)."""
    from ray_tpu.scalesim import run_scalesim

    if args.elastic:
        from ray_tpu.scalesim import run_elastic_sim

        result = run_elastic_sim(raylets=args.raylets,
                                 windows=args.windows, out=args.out)
        for label, arm in result["arms"].items():
            print(f"{label}: node-hours {arm['node_hours']}  "
                  f"objects lost {arm['objects_lost']}/"
                  f"{arm['objects_departed']}  shortfall "
                  f"{arm['capacity_shortfall']}  score {arm['score']}  "
                  f"recovery {arm['mean_recovery_ms']}ms")
        print(f"score vs drain-aware: kill "
              f"{result['score_ratio_kill_over_drain']}x, static "
              f"{result['score_ratio_static_over_drain']}x; "
              f"{result['bytes_saved_vs_kill']} bytes saved vs kill, "
              f"{result['node_hours_saved_vs_static']} node-hours "
              f"saved vs static")
        if args.out:
            print(f"wrote {args.out}")
        return 0

    if args.topology:
        from ray_tpu.scalesim import run_topology_sim

        result = run_topology_sim(raylets=args.raylets,
                                  windows=args.windows, seed=args.seed,
                                  out=args.out)
        for label, arm in result["arms"].items():
            print(f"{label}: circumference "
                  f"{arm['mean_ring_circumference']}  spillback hops "
                  f"{arm['mean_spillback_hops']}  latency "
                  f"{arm['placement_latency_ms']['mean']}ms  "
                  f"score p99 {arm['score_p99_s'] * 1e3:.2f}ms")
        print(f"PACK/ICI_RING circumference ratio "
              f"{result['circumference_ratio']}x, spillback hops "
              f"{result['spillback_hops_ratio']}x, score p99 ratio "
              f"{result['score_p99_ratio']}")
        if args.out:
            print(f"wrote {args.out}")
        return 0

    result = run_scalesim(
        shards=args.shards, raylets=args.raylets, windows=args.windows,
        window_s=args.window_s, seed=args.seed,
        kill_shard=args.kill_shard, legacy_arm=not args.no_legacy_arm,
        out=args.out)
    for label, arm in result["arms"].items():
        print(f"{label}: gcs ops/s "
              f"{arm['gcs_ops_per_s']['median']:.0f}  "
              f"decisions/s {arm['decisions_per_s']['median']:.0f}")
    if "speedup_gcs_ops" in result:
        print(f"speedup vs shards=1: gcs ops {result['speedup_gcs_ops']}x, "
              f"decisions {result['speedup_decisions']}x")
    if "director_bypass_ratio" in result:
        print(f"director bypass: {result['director_bypass_ratio']}x the "
              f"legacy arm's director CPU per op "
              f"({result['cores']} cores on this box; rates understate "
              f"the sharded arm below shards+2 cores)")
    if result.get("kill"):
        print(f"shard kill: {result['kill']}")
    if args.out:
        print(f"wrote {args.out}")
    return 0


# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="ray-tpu", description="ray_tpu cluster CLI")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("start", help="start a head or worker node")
    p.add_argument("--head", action="store_true")
    p.add_argument("--address", help="GCS address to join (worker nodes)")
    p.add_argument("--port", type=int, default=0, help="GCS port (head)")
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpus", type=float, default=None)
    p.add_argument("--resources", help="JSON dict of custom resources")
    p.add_argument("--tpu-slice",
                   help="JSON TpuSliceDescriptor for this host's ICI "
                        "domain (util/accelerators.py)")
    p.add_argument("--system-config", help="JSON dict of config overrides")
    p.add_argument("--client-server-port", type=int, default=None,
                   help="also serve ray-client connections on this port")
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("stop", help="stop the recorded cluster")
    p.set_defaults(fn=cmd_stop)

    p = sub.add_parser("status", help="node table + resources")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("drain",
                       help="gracefully drain one node out of the "
                            "cluster (migrate objects, checkpoint "
                            "actors, then exit)")
    p.add_argument("node", help="node id prefix (see `ray-tpu status`) "
                                "or raylet address")
    p.add_argument("--address", default=None)
    p.add_argument("--preempt", action="store_true",
                   help="compressed drain: checkpoint gangs first, "
                        "objects best-effort (preemption-notice path)")
    p.add_argument("--wait", action="store_true",
                   help="block until the node reaches DRAINED")
    p.add_argument("--timeout", type=float, default=120.0,
                   help="--wait limit in seconds")
    p.set_defaults(fn=cmd_drain)

    p = sub.add_parser("memory", help="object-store usage per node")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_memory)

    p = sub.add_parser("metrics", help="metric snapshots from gcs + raylets")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_metrics)

    p = sub.add_parser("compile-cache",
                       help="persistent AOT compile-cache contents "
                            "(key, size, age, hit count)")
    p.add_argument("--address", default=None)
    p.add_argument("--clear", action="store_true",
                   help="drop every cached executable + the index")
    p.add_argument("--json", action="store_true",
                   help="machine-readable index + counters")
    p.set_defaults(fn=cmd_compile_cache)

    p = sub.add_parser("trace",
                       help="export distributed-trace span trees "
                            "(Perfetto JSON)")
    p.add_argument("--address", default=None)
    p.add_argument("--trace-id", default=None,
                   help="hex trace id — export one tree only")
    p.add_argument("--out", default=None, help="output path (trace.json)")
    p.set_defaults(fn=cmd_trace)

    p = sub.add_parser("top",
                       help="live metrics view off the GCS time-series")
    p.add_argument("--address", default=None)
    p.add_argument("--interval", type=float, default=2.0)
    p.add_argument("--iterations", type=int, default=0,
                   help="stop after N refreshes (0 = until Ctrl-C)")
    p.add_argument("--filter", default=None,
                   help="only metrics whose name contains this substring")
    p.add_argument("--once", action="store_true",
                   help="render one snapshot and exit (= --iterations 1)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable snapshot (rates, p99s, "
                        "saturation flags, exemplar trace ids) for "
                        "scripts and CI")
    p.set_defaults(fn=cmd_top)

    p = sub.add_parser("profile",
                       help="cluster-wide CPU flamegraph (collapsed "
                            "stacks off the continuous profiler)")
    p.add_argument("--address", default=None)
    p.add_argument("--seconds", type=float, default=2.0,
                   help="collection window (default 2)")
    p.add_argument("--component", default=None,
                   choices=["driver", "worker", "raylet", "gcs",
                            "gcs-shard"],
                   help="one process class only (default: all)")
    p.add_argument("-o", "--out", default=None,
                   help="collapsed-stack output path "
                        "(profile.collapsed; '-' = stdout)")
    p.add_argument("--perfetto", default=None,
                   help="also write merged Perfetto tracks JSON here")
    p.add_argument("--hz", type=float, default=None,
                   help="re-arm the cluster sampler at this rate for "
                        "the window (restored after)")
    p.set_defaults(fn=cmd_profile)

    p = sub.add_parser("state",
                       help="live cluster introspection (debug_state "
                            "of every process)")
    p.add_argument("component", nargs="?", default=None,
                   choices=["serve", "placement", "tasks", "actors",
                            "objects", "leases", "transfers",
                            "collectives"],
                   help="flat rows for one component class "
                        "(omit for a per-process summary; `serve` shows "
                        "per-router queue depth vs bound + shed/admitted "
                        "totals, replica-group state, and per-engine "
                        "decode-batch occupancy / per-session KV page "
                        "counts / stream backlog for streaming backends; "
                        "`placement` shows per-pg bundle→node rows with "
                        "topology coords and the chosen strategy / "
                        "cost-model)")
    p.add_argument("--address", default=None)
    p.add_argument("--filter", default=None,
                   help="only rows containing this substring")
    p.add_argument("--no-workers", action="store_true",
                   help="skip the per-worker fan-out (faster)")
    p.add_argument("--timeout", type=float, default=5.0)
    p.set_defaults(fn=cmd_state)

    p = sub.add_parser("stack",
                       help="all-thread Python stacks of a live "
                            "process (gcs | pid | id prefix | address)")
    p.add_argument("target")
    p.add_argument("--address", default=None)
    p.add_argument("--timeout", type=float, default=5.0)
    p.set_defaults(fn=cmd_stack)

    p = sub.add_parser("doctor",
                       help="stall doctor: flag in-flight work whose "
                            "age exceeds max(floor, K*p99) of its stage")
    p.add_argument("--address", default=None)
    p.add_argument("--floor", type=float, default=None,
                   help="absolute stall floor in seconds (default 1.0 / "
                        "RAY_TPU_DOCTOR_FLOOR_S)")
    p.add_argument("--p99-factor", type=float, default=None,
                   help="K in max(floor, K*p99) (default 3.0 / "
                        "RAY_TPU_DOCTOR_P99_K)")
    p.add_argument("--stacks", action="store_true",
                   help="print the flagged processes' thread stacks")
    p.add_argument("--timeout", type=float, default=5.0)
    p.set_defaults(fn=cmd_doctor)

    p = sub.add_parser("timeline", help="dump chrome-trace profile timeline")
    p.add_argument("--address", default=None)
    p.add_argument("--out", default=None)
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser("submit", help="run a driver script on the cluster")
    p.add_argument("--address", default=None)
    p.add_argument("script")
    # REMAINDER: everything after the script (including --flags) belongs
    # to the driver, not to this parser
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    p.set_defaults(fn=cmd_submit)

    p = sub.add_parser("events", help="structured cluster events")
    p.add_argument("--address", default=None)
    p.add_argument("--severity", default=None,
                   choices=["INFO", "WARNING", "ERROR", "FATAL"])
    p.add_argument("--limit", type=int, default=100)
    p.set_defaults(fn=cmd_events)

    p = sub.add_parser("dashboard", help="serve the cluster dashboard")
    p.add_argument("--address", default=None)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8265)
    p.set_defaults(fn=cmd_dashboard)

    p = sub.add_parser("debug", help="attach to a live rpdb breakpoint")
    p.add_argument("--address", default=None)
    p.add_argument("--index", type=int, default=None,
                   help="breakpoint number (skip the prompt)")
    p.set_defaults(fn=cmd_debug)

    p = sub.add_parser("up", help="launch a cluster from a YAML spec")
    p.add_argument("config", help="cluster YAML (see autoscaler/launcher.py)")
    p.set_defaults(fn=cmd_up)

    p = sub.add_parser("down", help="stop a launched cluster")
    p.add_argument("cluster", help="cluster name or YAML path")
    p.set_defaults(fn=cmd_down)

    p = sub.add_parser("attach", help="open a shell on the head node")
    p.add_argument("cluster", help="cluster name or YAML path")
    p.add_argument("--print-only", action="store_true",
                   help="print the attach command instead of exec'ing it")
    p.set_defaults(fn=cmd_attach)

    p = sub.add_parser("exec", help="run a command on the head node")
    p.add_argument("cluster", help="cluster name or YAML path")
    p.add_argument("command")
    p.set_defaults(fn=cmd_exec)

    p = sub.add_parser("microbenchmark", help="run the core benchmark suite")
    p.add_argument("--out", default=None)
    p.set_defaults(fn=cmd_microbenchmark)

    p = sub.add_parser("scalesim",
                       help="control-plane scale-sim (spoofed raylets "
                            "vs a real sharded GCS)")
    p.add_argument("--shards", type=int, default=4)
    p.add_argument("--raylets", type=int, default=16,
                   help="spoofed raylet clients")
    p.add_argument("--windows", type=int, default=5)
    p.add_argument("--window-s", type=float, default=1.0,
                   help="seconds per measurement slice")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--kill-shard", action="store_true",
                   help="SIGKILL+restart a seeded shard mid-window and "
                        "verify zero lost acked ops")
    p.add_argument("--no-legacy-arm", action="store_true",
                   help="skip the interleaved shards=1 control arm")
    p.add_argument("--topology", action="store_true",
                   help="run the topology placement arm instead: "
                        "ICI_RING vs PACK over spoofed 4x4-torus "
                        "raylets (circumference / spillback hops / "
                        "placement latency)")
    p.add_argument("--elastic", action="store_true",
                   help="run the elastic membership ramp arm instead: "
                        "drain-aware vs static vs kill-based "
                        "scale-down, scored on node-hours x SLO "
                        "violations")
    p.add_argument("--out", default=None, help="write result JSON here")
    p.set_defaults(fn=cmd_scalesim)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
