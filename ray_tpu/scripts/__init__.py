"""CLI entry points (reference: python/ray/scripts/scripts.py)."""
