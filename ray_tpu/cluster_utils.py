"""Multi-node-on-one-machine test cluster (reference:
python/ray/cluster_utils.py:10 class Cluster, add_node :60) — the
load-bearing test idiom: every "node" is a real raylet process with its own
object store, so distributed logic is exercised process-boundary-faithfully
on a single machine."""

from __future__ import annotations

from ray_tpu._private.config import Config, set_config
from ray_tpu._private.node import (
    Node,
    ServiceProcess,
    new_session_dir,
    start_gcs,
    start_gcs_shard,
    start_gcs_shards,
    start_raylet,
)


class ClusterNode:
    def __init__(self, svc: ServiceProcess, address: str, node_id, store_root):
        self.svc = svc
        self.address = address
        self.node_id = node_id
        self.store_root = store_root

    def kill(self):
        self.svc.kill()


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: dict | None = None,
                 _system_config: dict | None = None):
        self.config = Config.load(_system_config)
        set_config(self.config)
        self.session_dir = new_session_dir()
        self.gcs_svc = None
        self.gcs_address = None
        self.shard_procs: list[ServiceProcess] = []
        self.shard_addresses: list[str] = []
        self.nodes: list[ClusterNode] = []
        if initialize_head:
            self.shard_procs, self.shard_addresses = start_gcs_shards(
                self.session_dir, self.config)
            self.gcs_svc, self.gcs_address = start_gcs(
                self.session_dir, self.config,
                shard_addresses=self.shard_addresses)
            self.add_node(is_head=True, **(head_node_args or {}))

    @property
    def address(self) -> str:
        return self.gcs_address

    @property
    def head_node(self) -> ClusterNode:
        return self.nodes[0]

    def add_node(self, *, num_cpus: float | None = None, num_tpus: float = 0,
                 resources: dict | None = None, labels: dict | None = None,
                 is_head: bool = False,
                 tpu_slice: dict | None = None,
                 topology: dict | None = None) -> ClusterNode:
        svc, address, node_id, store_root = start_raylet(
            self.session_dir, self.gcs_address, self.config,
            num_cpus=num_cpus, num_tpus=num_tpus, resources=resources,
            labels=labels, is_head=is_head, tpu_slice=tpu_slice,
            topology=topology)
        node = ClusterNode(svc, address, node_id, store_root)
        self.nodes.append(node)
        return node

    def remove_node(self, node: ClusterNode):
        node.kill()
        self.nodes.remove(node)

    def connect_driver(self):
        """Connect the current process as a driver to the head node."""
        from ray_tpu._private.core_worker import DRIVER, CoreWorker

        return CoreWorker(
            mode=DRIVER,
            raylet_address=self.head_node.address,
            gcs_address=self.gcs_address,
            session_dir=self.session_dir,
            store_root=self.head_node.store_root,
            config=self.config,
        )

    def kill_shard(self, index: int) -> ServiceProcess:
        """Fault injection: kill one store shard. restart_shard() brings
        it back on the same port against its journal."""
        svc = self.shard_procs[index]
        svc.kill()
        return svc

    def restart_shard(self, index: int) -> ServiceProcess:
        old = self.shard_procs[index]
        svc, _addr = start_gcs_shard(self.session_dir, self.config, index,
                                     port=old.shard_port)
        self.shard_procs[index] = svc
        return svc

    def shutdown(self):
        for node in reversed(self.nodes):
            node.kill()
        self.nodes.clear()
        if self.gcs_svc is not None:
            self.gcs_svc.kill()
            self.gcs_svc = None
        for svc in self.shard_procs:
            svc.kill()
        self.shard_procs.clear()
