"""Continuous profiling plane: an always-on wall-clock sampler in every
runtime process class (reference analog: `py-spy record` / Google-wide
profiling — here in-process, zero-dependency, riding the existing
`sys._current_frames` machinery behind `debug_stacks`).

Each process (driver/worker core worker, raylet, GCS director + store
shards) runs ONE daemon sampler thread ("ray-tpu-profiler") at a low
rate (`RAY_TPU_PROFILE_HZ`, default ~67 Hz), walking every thread's
Python stack and aggregating COLLAPSED stacks (root-first,
';'-separated, Brendan-Gregg flamegraph format) into a bounded
per-(thread, stack) count table. The table drains on the existing ~2 s
profile-flush cadence into a bounded GCS **profile ring**
(`add_profile_samples` / `get_profile_samples`); a failed flush merges
the batch back (bounded, drops counted in
`profiling.flush_dropped_total`) and retries next cycle — the same
lossy-but-typed degradation contract as the span flush.

Export surfaces: `ray_tpu.profile()`, `ray-tpu profile [--component
--seconds -o]`, dashboard `/api/profile` — all emit cluster-wide
collapsed-stack text (feed it to flamegraph.pl / speedscope / any
flamegraph viewer) plus merged Perfetto tracks
(`samples_to_chrome_trace`: one slice per flush window per thread,
top stacks in args).

Live arming: `ray_tpu.set_profiling(hz)` rides the internal KV
(KV_KEY) + pubsub (CHANNEL) plane exactly like failpoint arming and
trace-sampling overrides — running processes flip within a beat,
later-spawned ones read the KV at bootstrap; hz=0 stops the sampler
thread everywhere.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import weakref

from ray_tpu._private import stats as _stats

KV_KEY = "ray_tpu:profiling"
CHANNEL = "profiling_config"

DEFAULT_HZ = 67.0  # full rate (~50-100 Hz band); odd, avoids 10ms lockstep
MIN_DEFAULT_HZ = 7.0  # always-on floor on the most oversubscribed boxes
MAX_HZ = 1000.0
MAX_STACK_DEPTH = 48
MAX_STACKS = 4000  # bound on distinct (thread, stack) keys per window


def default_hz() -> float:
    """The always-on default rate: an overhead BUDGET, not a fixed
    number. Each sampler pays a fixed per-wakeup cost (one sample is
    ~30µs + the wakeup syscall tax), and every runtime process runs
    one — so a box where a dozen processes share 1-2 cores derates
    toward MIN_DEFAULT_HZ to keep the whole plane inside the tier-1
    ≤5% overhead gate, while an 8+-core box runs the full ~67 Hz.
    RAY_TPU_PROFILE_HZ pins an explicit rate; investigation bumps the
    cluster live (`ray_tpu.set_profiling` / `ray-tpu profile --hz`)."""
    cores = (len(os.sched_getaffinity(0))
             if hasattr(os, "sched_getaffinity")
             else (os.cpu_count() or 1))
    if cores >= 8:
        return DEFAULT_HZ
    return max(MIN_DEFAULT_HZ, DEFAULT_HZ * cores / 8.0)


# (the 1-2 core tier lands on the floor: a dozen runtime processes'
# wakeups share one core with the workload, and the ≤5% tier-1 gate
# prices every wakeup; `ray-tpu profile --hz 100` bumps a window live)

THREAD_NAME = "ray-tpu-profiler"

# sentinel stack for counts folded past the distinct-stack bound
OVERFLOW_STACK = "(other)"

M_SAMPLES = _stats.Count(
    "profiling.samples_total",
    "thread-stack samples captured by the continuous wall-clock sampler")
M_FLUSH_DROPPED = _stats.Count(
    "profiling.flush_dropped_total",
    "sampled stacks dropped past the bounded table (flush-failure "
    "merge-back overflow or distinct-stack cap)")


def _env_hz() -> float:
    raw = os.environ.get("RAY_TPU_PROFILE_HZ", "")
    if not raw:
        return default_hz()
    try:
        return min(MAX_HZ, max(0.0, float(raw)))
    except ValueError:
        return default_hz()


# code object -> collapsed-frame label. The sampler's hot path never
# formats strings: labels memoize per code object (function identity —
# co_firstlineno, not the live line, so stacks aggregate across
# samples), and the count table keys on code-object tuples until drain.
# Holding code refs keeps the memo valid (ids can't be recycled).
_label_memo: dict = {}


def _frame_label(code) -> str:
    label = _label_memo.get(code)
    if label is None:
        fname = os.path.basename(code.co_filename)
        # ';' is the collapsed-format frame separator so it can never
        # appear inside a label
        label = f"{code.co_name} ({fname}:{code.co_firstlineno})".replace(
            ";", ",")
        if len(_label_memo) > 50_000:  # leak guard for pathological eval
            _label_memo.clear()
        _label_memo[code] = label
    return label


class SamplingProfiler:
    """Bounded collapsed-stack aggregator + its sampler thread."""

    def __init__(self, role: str, max_stacks: int = MAX_STACKS):
        _instances.add(self)
        self.role = role or "process"
        self.max_stacks = max_stacks
        self.hz = 0.0
        # (thread name, tuple-of-code-objects | collapsed str) -> count
        self._table: dict[tuple, int] = {}
        self._thread_names: dict[int, str] = {}
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._window_start = time.time()
        self._samples = 0  # samples in the current window

    # -- sampling ---------------------------------------------------------

    def sample_once(self) -> int:
        """Capture one sample of every thread's stack into the table
        (public for tests and for single-shot collection). Returns the
        number of thread-stacks recorded.

        Hot-path discipline: no string work here — the table keys on
        (thread name, tuple-of-code-objects); labels/joins happen once
        per DISTINCT stack at drain(). One sample costs a frame walk
        plus a dict upsert per thread."""
        me = threading.get_ident()
        names = self._thread_names
        n = 0
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue  # never profile the profiler
            name = names.get(tid)
            if name is None:
                # new thread since the cached enumerate: refresh once.
                # Threads invisible to threading.enumerate (C-spawned
                # with a thread state) get their fallback name CACHED,
                # or every later sample would rebuild this dict.
                names = self._thread_names = {
                    t.ident: t.name for t in threading.enumerate()}
                name = names.setdefault(tid, f"tid-{tid}")
            codes: list = []
            depth = 0
            while frame is not None and depth < MAX_STACK_DEPTH:
                codes.append(frame.f_code)
                frame = frame.f_back
                depth += 1
            if not codes:
                continue
            key = (name, tuple(codes))  # leaf-first; reversed at drain
            with self._lock:
                cur = self._table.get(key)
                if cur is None and len(self._table) >= self.max_stacks:
                    # keep counts honest past the distinct-stack bound:
                    # fold into a per-thread overflow bucket
                    key = (name, OVERFLOW_STACK)
                    cur = self._table.get(key)
                    M_FLUSH_DROPPED.inc()
                self._table[key] = (cur or 0) + 1
                self._samples += 1
            n += 1
        if n:
            M_SAMPLES.inc(n)
        return n

    def _run(self):
        while not self._stop.is_set():
            period = 1.0 / self.hz if self.hz > 0 else 0.5
            if self._stop.wait(period):
                return
            if self.hz <= 0:
                continue
            try:
                self.sample_once()
            except Exception:
                # a torn frame walk must never kill the sampler; the
                # next tick resamples
                pass

    def set_rate(self, hz: float) -> None:
        """Arm/re-rate/disarm the sampler thread. hz<=0 stops it (the
        thread exits; a later arm starts a fresh one)."""
        hz = min(MAX_HZ, max(0.0, float(hz)))
        self.hz = hz
        if hz <= 0:
            self.stop()
            return
        if self._thread is None or not self._thread.is_alive():
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._run, name=THREAD_NAME, daemon=True)
            self._thread.start()

    def stop(self, join_timeout: float = 1.0) -> None:
        self.hz = 0.0
        t = self._thread
        if t is not None:
            self._stop.set()
            if t.is_alive():
                t.join(timeout=join_timeout)
            self._thread = None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # -- flush ------------------------------------------------------------

    def drain(self) -> dict | None:
        """Drain the window into one wire batch (None when empty):
        {"role", "t_start", "t_end", "hz", "samples",
         "stacks": [{"thread", "stack", "count"}, ...]}."""
        with self._lock:
            if not self._table:
                return None
            table, self._table = self._table, {}
            samples, self._samples = self._samples, 0
            t_start, self._window_start = self._window_start, time.time()
        # string work happens HERE, once per distinct stack per window —
        # never on the sampling hot path. Code tuples are leaf-first;
        # the collapsed format is root-first. Distinct code tuples can
        # format to one string (same name/file/line) — merge counts.
        merged: dict[tuple[str, str], int] = {}
        for (thread, stack), count in table.items():
            if not isinstance(stack, str):
                stack = ";".join(_frame_label(c) for c in reversed(stack))
            key = (thread, stack)
            merged[key] = merged.get(key, 0) + count
        return {
            "role": self.role,
            "t_start": t_start,
            "t_end": time.time(),
            "hz": self.hz,
            "samples": samples,
            "stacks": [{"thread": thread, "stack": stack, "count": count}
                       for (thread, stack), count in merged.items()],
        }

    def merge_back(self, batch: dict | None) -> int:
        """Re-merge a drained-but-unflushed batch (failed GCS flush)
        so the next cycle retries it. Bounded: stacks past the cap fold
        into the per-thread overflow bucket and count as dropped.
        Returns how many stack rows were folded."""
        if not batch:
            return 0
        dropped = 0
        with self._lock:
            self._window_start = min(self._window_start,
                                     batch.get("t_start", time.time()))
            for row in batch.get("stacks", ()):
                key = (row["thread"], row["stack"])
                cur = self._table.get(key)
                if cur is None and len(self._table) >= self.max_stacks:
                    key = (row["thread"], OVERFLOW_STACK)
                    cur = self._table.get(key)
                    dropped += 1
                self._table[key] = (cur or 0) + row["count"]
                self._samples += row["count"]
        if dropped:
            M_FLUSH_DROPPED.inc(dropped)
        return dropped

    def __len__(self):
        with self._lock:
            return len(self._table)


# ---------------------------------------------------------------------------
# per-process singleton + live arming (KV/pubsub plane)
# ---------------------------------------------------------------------------

_profiler: SamplingProfiler | None = None
_lock = threading.Lock()
# every live profiler (the singleton AND direct instances): module-level
# stop() must be able to stop all of them, or a leaked instance thread
# would be unkillable from the outside (conftest's leak remediation)
_instances: weakref.WeakSet = weakref.WeakSet()
# a live KV/pubsub override (ray_tpu.set_profiling) outranks the env
# default for any later start() (e.g. a GCS applying a restored KV
# before its run loop arms the sampler)
_override_hz: float | None = None


def get_profiler(role: str | None = None) -> SamplingProfiler:
    global _profiler
    if _profiler is None:
        with _lock:
            if _profiler is None:
                if role is None:
                    from ray_tpu._private import failpoints as _fp

                    role = _fp.get_role() or "process"
                _profiler = SamplingProfiler(role)
    return _profiler


def start(role: str, hz: float | None = None) -> SamplingProfiler:
    """Bootstrap hook: start this process's sampler at `hz` (default: a
    live KV override when one was already applied, else
    RAY_TPU_PROFILE_HZ — the always-on default rate). Idempotent."""
    prof = get_profiler(role)
    prof.role = role or prof.role
    if hz is None:
        hz = _override_hz if _override_hz is not None else _env_hz()
    prof.set_rate(hz)
    return prof


def stop() -> None:
    """Process shutdown: stop EVERY live sampler thread — the singleton
    and any directly-constructed instances (conftest's leak check names
    any 'ray-tpu-profiler' thread that outlives its runtime, then calls
    this to actually kill it) — and drop any live KV override: it was
    cluster-scoped, and a process that later joins a NEW cluster must
    start from the env default."""
    global _override_hz
    _override_hz = None
    for prof in list(_instances):
        prof.stop()


def rate() -> float:
    prof = _profiler
    return prof.hz if prof is not None else 0.0


def set_rate(hz: float) -> None:
    get_profiler().set_rate(hz)


def apply_kv_value(value) -> None:
    """Apply a live override arriving via the GCS KV/pubsub: the rate in
    Hz as a string (e.g. b"100"), or b"default" — drop the override and
    return to each process's OWN env/budget default (`ray-tpu profile
    --hz` restores through this, so a 2-core node keeps its derated
    floor instead of inheriting the CLI host's default)."""
    global _override_hz
    if value is None:
        return
    if isinstance(value, (bytes, bytearray)):
        value = bytes(value).decode(errors="replace")
    if value == "default":
        _override_hz = None
        set_rate(_env_hz())
        return
    try:
        hz = float(value)
    except (TypeError, ValueError):
        return
    _override_hz = min(MAX_HZ, max(0.0, hz))
    set_rate(_override_hz)


def drain_batch(component_type: str, component_id: int | None = None,
                node_id: bytes | None = None) -> dict | None:
    """Drain this process's sampler into one GCS-wire batch (None when
    there is nothing to flush)."""
    prof = _profiler
    if prof is None:
        return None
    batch = prof.drain()
    if batch is None:
        return None
    batch["component_type"] = component_type
    batch["component_id"] = (os.getpid() if component_id is None
                             else component_id)
    if node_id is not None:
        batch["node_id"] = node_id
    return batch


def merge_back(batch: dict | None) -> None:
    prof = _profiler
    if prof is not None and batch:
        prof.merge_back(batch)


async def flush_to(gcs, component_type: str,
                   node_id: bytes | None = None) -> None:
    """Drain this process's sampler window and notify it into the GCS
    profile ring — the ONE flush contract every process class shares:
    the `profile.flush` failpoint seam models an unreachable GCS, and a
    failed notify merges the window back into the bounded table
    (drops counted) for the next cycle."""
    from ray_tpu._private import failpoints as _fp

    if gcs is None:
        return
    batch = drain_batch(component_type, node_id=node_id)
    if batch is None:
        return
    try:
        if _fp.ARMED:
            _fp.fire_strict("profile.flush")
        await gcs.notify("add_profile_samples", batch)
    except Exception:
        merge_back(batch)


def wait_for_coverage(fetch, component: str | None = None,
                      deadline_s: float = 3.0,
                      poll_s: float = 0.3) -> list[dict]:
    """Poll `fetch()` (returns profile-ring batches) until the expected
    process-class coverage lands — one class when filtered, else the
    driver/raylet/GCS trio a cluster flamegraph must span — or the
    deadline passes (windows flush on the ~2s cadence, so a short
    collection needs this tail-wait). Returns the last fetch."""
    deadline = time.monotonic() + deadline_s
    want = 1 if component else 3
    while True:
        batches = fetch()
        if (len(components_of(batches)) >= want
                or time.monotonic() > deadline):
            return batches
        time.sleep(poll_s)


# ---------------------------------------------------------------------------
# export: collapsed-stack text + merged Perfetto tracks
# ---------------------------------------------------------------------------


def collapse(batches: list[dict], component: str | None = None) -> dict:
    """Merge GCS profile-ring batches into one cluster-wide collapsed
    table: {"<component>;<thread>;<frame>;...": count}. Identical
    stacks from every process of a component class merge (that IS the
    cluster flamegraph); `component` filters to one class."""
    merged: dict[str, int] = {}
    for b in batches:
        ctype = b.get("component_type") or b.get("role") or "?"
        if component and ctype != component:
            continue
        for row in b.get("stacks", ()):
            key = f"{ctype};{row['thread']};{row['stack']}"
            merged[key] = merged.get(key, 0) + int(row["count"])
    return merged


def collapse_text(batches: list[dict], component: str | None = None) -> str:
    """Flamegraph-ready collapsed text, hottest stacks first."""
    merged = collapse(batches, component)
    lines = [f"{stack} {count}" for stack, count in
             sorted(merged.items(), key=lambda kv: -kv[1])]
    return "\n".join(lines)


def components_of(batches: list[dict]) -> list[str]:
    return sorted({b.get("component_type") or b.get("role") or "?"
                   for b in batches if b.get("stacks")})


def samples_to_chrome_trace(batches: list[dict]) -> list[dict]:
    """Merged Perfetto tracks: each flush window becomes one 'X' slice
    per (process, thread) track, named by its hottest stack leaf, with
    the top stacks in args — profile windows line up beside the span
    timeline in Perfetto / chrome://tracing."""
    trace: list[dict] = []
    for b in batches:
        ctype = b.get("component_type") or b.get("role") or "?"
        nid = b.get("node_id")
        pid = (f"{ctype}-prof "
               f"{nid.hex()[:8] if isinstance(nid, bytes) else ''}").strip()
        by_thread: dict[str, list] = {}
        for row in b.get("stacks", ()):
            by_thread.setdefault(row["thread"], []).append(row)
        for thread, rows in by_thread.items():
            rows.sort(key=lambda r: -r["count"])
            top = rows[0]
            leaf = top["stack"].rsplit(";", 1)[-1]
            trace.append({
                "cat": "profile.samples",
                "name": f"{leaf} ({top['count']} samples)",
                "ph": "X",
                "ts": b.get("t_start", 0.0) * 1e6,
                "dur": max(0.0, (b.get("t_end", 0.0)
                                 - b.get("t_start", 0.0))) * 1e6,
                "pid": pid,
                "tid": f"{thread}/{b.get('component_id', '')}",
                "args": {
                    "hz": b.get("hz"),
                    "samples": sum(r["count"] for r in rows),
                    "top_stacks": [
                        {"stack": r["stack"], "count": r["count"]}
                        for r in rows[:5]],
                },
            })
    return trace
