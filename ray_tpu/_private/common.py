"""Shared wire/value types: task specs, resource sets, addresses.

Parity targets: TaskSpecification (reference: src/ray/common/task/task_spec.h),
ResourceSet with fractional resources (reference:
src/ray/common/task/scheduling_resources.h FixedPoint), Address
(reference: src/ray/protobuf/common.proto Address). Everything here is
msgpack-plain (dicts/lists/bytes) so specs travel over the RPC layer without
a pickling step in the hot path.
"""

from __future__ import annotations

import hashlib
from typing import Any

# Task types
NORMAL_TASK = 0
ACTOR_CREATION_TASK = 1
ACTOR_TASK = 2

# Fractional resource precision — mirror the reference's FixedPoint(1/10000)
# (reference: src/ray/raylet/scheduling/fixed_point.h).
RESOURCE_QUANTUM = 10000


class InsufficientResources(RuntimeError):
    """Raylet-side admission miss: the GCS's availability snapshot raced a
    lease grant. Travels pickled inside rpc.RemoteError so the GCS can
    distinguish a benign scheduling bounce from a real actor-creation
    failure by type, not by matching error text (reference analog: the
    SCHEDULING_FAILED status on CreateActorReply,
    src/ray/protobuf/gcs_service.proto)."""


def quantize(value: float) -> int:
    return int(round(value * RESOURCE_QUANTUM))


def dequantize(value: int) -> float:
    return value / RESOURCE_QUANTUM


class ResourceSet:
    """Integer-quantized resource amounts keyed by name ("CPU", "TPU", ...)."""

    __slots__ = ("_amounts",)

    def __init__(self, amounts: dict[str, float] | None = None, _raw=None):
        if _raw is not None:
            self._amounts = dict(_raw)
        else:
            self._amounts = {
                k: quantize(v) for k, v in (amounts or {}).items() if v != 0
            }

    @classmethod
    def from_raw(cls, raw: dict[str, int]) -> "ResourceSet":
        return cls(_raw=raw)

    def raw(self) -> dict[str, int]:
        return dict(self._amounts)

    def to_dict(self) -> dict[str, float]:
        return {k: dequantize(v) for k, v in self._amounts.items()}

    def is_subset_of(self, other: "ResourceSet") -> bool:
        return all(
            other._amounts.get(k, 0) >= v for k, v in self._amounts.items()
        )

    def subtract(self, other: "ResourceSet") -> None:
        for k, v in other._amounts.items():
            self._amounts[k] = self._amounts.get(k, 0) - v

    def add(self, other: "ResourceSet") -> None:
        for k, v in other._amounts.items():
            self._amounts[k] = self._amounts.get(k, 0) + v

    def get(self, key: str) -> float:
        return dequantize(self._amounts.get(key, 0))

    def is_empty(self) -> bool:
        return not any(self._amounts.values())

    def copy(self) -> "ResourceSet":
        return ResourceSet(_raw=self._amounts)

    def __repr__(self):
        return f"ResourceSet({self.to_dict()})"

    def __eq__(self, other):
        return isinstance(other, ResourceSet) and {
            k: v for k, v in self._amounts.items() if v
        } == {k: v for k, v in other._amounts.items() if v}


def function_id(pickled_function: bytes) -> bytes:
    return hashlib.sha1(pickled_function).digest()[:16]


def make_task_spec(
    *,
    task_id: bytes,
    job_id: bytes,
    name: str,
    fn_id: bytes,
    task_type: int = NORMAL_TASK,
    actor_id: bytes | None = None,
    method_name: str = "",
    seq_no: int = -1,
    owner_addr: str = "",
    owner_worker_id: bytes = b"",
    args: list[dict] | None = None,
    num_returns: int = 1,
    resources: dict[str, float] | None = None,
    max_retries: int = 0,
    actor_creation: dict | None = None,
    placement_group_id: bytes | None = None,
    bundle_index: int = -1,
    scheduling_strategy: dict | None = None,
    trace: list | None = None,
) -> dict[str, Any]:
    """TaskSpec as a msgpack-plain dict. `trace` is the sampled trace
    context [trace_id, span_id, parent_span_id, sampled] (tracing.py
    wire format), set per-call AFTER the cached template copy — absent
    (None) on the unsampled hot path."""
    return {
        "task_id": task_id,
        "job_id": job_id,
        "name": name,
        "fn_id": fn_id,
        "type": task_type,
        "actor_id": actor_id,
        "method_name": method_name,
        "seq_no": seq_no,
        "owner_addr": owner_addr,
        "owner_worker_id": owner_worker_id,
        "args": args or [],
        "num_returns": num_returns,
        "resources": ResourceSet(resources or {}).raw(),
        "max_retries": max_retries,
        "actor_creation": actor_creation,
        "pg_id": placement_group_id,
        "bundle_index": bundle_index,
        "strategy": scheduling_strategy,
        "trace": trace,
    }


def scheduling_key(spec: dict) -> tuple:
    """Tasks with equal keys can reuse the same leased worker
    (reference: direct_task_transport.h:40-49 SchedulingKey)."""
    return (
        spec["fn_id"],
        tuple(sorted(spec["resources"].items())),
        spec.get("pg_id"),
        spec.get("bundle_index", -1),
    )


# --- arg descriptors -------------------------------------------------------

def inline_arg(data: bytes) -> dict:
    return {"kind": "inline", "data": data}


def ref_arg(object_id: bytes, owner_addr: str, in_plasma: bool) -> dict:
    return {
        "kind": "ref",
        "id": object_id,
        "owner": owner_addr,
        "plasma": in_plasma,
    }
