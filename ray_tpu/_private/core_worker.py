"""CoreWorker — the in-process runtime in every driver and worker.

Capability parity with the reference core worker (reference:
src/ray/core_worker/core_worker.h:321 and core_worker.cc — Put :903,
Get :1024, Wait :1157, SubmitTask :1390, CreateActor :1435,
SubmitActorTask :1595, CancelTask :1644, KillActor :1684, ExecuteTask
:1863), the direct task submitter with lease reuse + pipelining
(direct_task_transport.h:52), the direct actor submitter with per-caller
sequence numbers and RESTARTING queues (direct_actor_transport.h:62), and a
simplified distributed reference counter (reference_count.h:59: local refs +
borrows + in-flight submission pins; lineage kept while references exist).

Threading model: synchronous public API on the caller's thread; all network
IO on one asyncio event-loop thread (the analog of the reference's
io_service threads); task execution (worker mode) on a dedicated dispatcher
thread, with async actor methods running on their own loop.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import contextlib
import contextvars
import itertools
import logging
import os
import queue as queue_mod
import sys
import threading
import time
import traceback
from typing import Any

import cloudpickle

from ray_tpu import exceptions as exc
from ray_tpu._private import common, global_state, rpc, serialization
from ray_tpu._private import debug_state as _debug
from ray_tpu._private import failpoints as _fp
from ray_tpu._private import sampling_profiler as _sprof
from ray_tpu._private import tracing
from ray_tpu._private.config import Config
from ray_tpu._private.ids import ActorID, JobID, NodeID, ObjectID, TaskID, WorkerID
from ray_tpu._private.memstore import IN_PLASMA, MemoryStore
from ray_tpu._private.object_store import make_store
from ray_tpu.object_ref import ObjectRef

logger = logging.getLogger("ray_tpu.core_worker")

DRIVER = "driver"
WORKER = "worker"

# Churn instrumentation for the task fast path. Together with
# rpc.loop_wakeups_total these feed the tier-1 hop-count guard
# (tests/test_task_pipelining.py): per completed task, wakeups + executor
# hops must stay below a fixed bound so per-call churn can't silently
# regrow.
from ray_tpu._private import stats as _stats

M_TASKS_SUBMITTED = _stats.Count(
    "core.tasks_submitted_total", "tasks submitted by this process")
M_TASKS_COMPLETED = _stats.Count(
    "core.tasks_completed_total", "task replies handled by this process")
M_TASKS_EXECUTED = _stats.Count(
    "core.tasks_executed_total", "tasks executed by this process")
M_EXEC_HOPS = _stats.Count(
    "core.exec_hops_total", "dispatcher/executor thread handoffs")
M_LEASE_REQUESTS = _stats.Count(
    "core.lease_requests_total", "worker-lease request RPCs issued")
M_LEASE_RPCS = _stats.Count(
    "core.lease_rpcs_total",
    "owner-issued request_worker_lease RPCs, counting every spillback "
    "redial (the raylet->raylet forwarding win shows up here)")

# Per-hop latency histograms derived from the task path (always on —
# these, via the raylet's metric merge, are the feed the serve replica
# autoscaler consumes; trace SPANS ride head sampling, the histograms
# do not).
M_QUEUE_WAIT_S = _stats.Histogram(
    "core.task_queue_wait_s", _stats.LATENCY_BOUNDARIES_S,
    "submit -> pushed to a leased worker")
M_LEASE_WAIT_S = _stats.Histogram(
    "core.task_lease_wait_s", _stats.LATENCY_BOUNDARIES_S,
    "worker-lease request round trip")
M_EXEC_S = _stats.Histogram(
    "core.task_exec_s", _stats.LATENCY_BOUNDARIES_S,
    "task execution (worker side)")
M_REPLY_OVERHEAD_S = _stats.Histogram(
    "core.task_reply_overhead_s", _stats.LATENCY_BOUNDARIES_S,
    "push round trip minus worker-held time (wire + loop overhead)")
M_E2E_S = _stats.Histogram(
    "core.task_e2e_s", _stats.LATENCY_BOUNDARIES_S,
    "submit -> reply handled (owner side)")


def _legacy_task_path() -> bool:
    """RAY_TPU_TASK_LEGACY=1 re-enables the round-7 task path (per-reply
    call_soon_threadsafe, per-task profile-flush submit, one-at-a-time
    hard lease requests, per-push lease-return timers, uncached specs) —
    the control arm of the microbenchmark's interleaved A/B."""
    return os.environ.get("RAY_TPU_TASK_LEGACY", "") not in ("", "0")

def _collective_debug() -> list[dict]:
    """Debug rows for this process's live collective groups — only when
    the collective layer was actually imported (a snapshot must never be
    the thing that pays the numpy/backends import)."""
    mod = sys.modules.get("ray_tpu.collective.collective")
    if mod is None:
        return []
    try:
        return mod._manager.debug_state()
    except Exception:
        return []


def _serve_router_debug() -> list[dict]:
    """Live serve routers in this process (driver handles, proxy
    actors): same only-if-imported discipline as the collective hook."""
    mod = sys.modules.get("ray_tpu.serve.router")
    if mod is None:
        return []
    try:
        return mod.debug_routers()
    except Exception:
        return []


# Task id of the async-actor coroutine currently running on the actor's
# event loop (asyncio snapshots the context per scheduled coroutine).
_ASYNC_TASK_ID: contextvars.ContextVar = contextvars.ContextVar(
    "ray_tpu_async_task_id", default=None)


class _Lease:
    __slots__ = ("lease_id", "worker_id", "address", "conn", "inflight",
                 "raylet_conn", "last_used", "task_conn", "burst_channel")

    def __init__(self, lease_id, worker_id, address, conn, raylet_conn,
                 task_conn=None):
        self.lease_id = lease_id
        self.worker_id = worker_id
        self.address = address
        self.conn = conn
        self.inflight = 0
        self.raylet_conn = raylet_conn
        self.last_used = time.monotonic()
        # Same-node direct task channel (blocking UDS served by the
        # worker's executor thread itself); None for remote leases.
        self.task_conn = task_conn
        self.burst_channel = True

    @property
    def push_conn(self):
        """Latency/throughput hybrid, chosen ONCE per burst (when
        inflight rises from 0, see _drain_pending): shallow bursts ride
        the direct channel (no asyncio hops worker-side), deep bursts
        ride the rpc conn, whose replies overlap execution on the
        worker's io loop instead of sendall()ing from the executor.
        Sticky per burst so every in-flight push for this lease shares
        ONE FIFO connection — mixing conns would let later tasks reach
        the worker's queue first (order matters to queued-task
        cancellation and to wait()-style first-come expectations)."""
        conn = self.task_conn
        if conn is not None and not conn.closed and self.burst_channel:
            return conn
        return self.conn


class _ActorClient:
    """Owner-side state for one actor (per-handle ordering + restart queue)."""

    def __init__(self, actor_id: bytes):
        self.actor_id = actor_id
        self.address = ""
        self.state = "PENDING_CREATION"
        self.conn: rpc.Connection | None = None
        self.seq = 0
        # Reorder-lane epoch: bumped on a connection loss to a
        # still-ALIVE actor. The worker cannot tell whether the seq
        # numbers lost with the connection were consumed, so the lane is
        # poisoned — callers and the worker restart matching (epoch,
        # seq=0) lanes instead of wedging every later call behind a seq
        # hole nothing will ever fill.
        self.epoch = 0
        self.queued: list[tuple[dict, list[ObjectID]]] = []
        self.subscribed = False
        self.death_cause = ""
        self.flush_scheduled = False
        self.poll_scheduled = False
        self.inflight = 0
        self.burst_channel = True
        # same-node direct task channel of the hosting worker
        self.task_channel = ""
        self.task_conn: rpc.Connection | None = None


class _OwnedRef:
    __slots__ = ("local", "borrows", "pins", "plasma", "lineage_task")

    def __init__(self):
        self.local = 0
        self.borrows = 0
        self.pins = 0
        self.plasma = False
        self.lineage_task = None

    def total(self):
        return self.local + self.borrows + self.pins


class CoreWorker:
    def __init__(self, *, mode: str, raylet_address: str, gcs_address: str,
                 session_dir: str, store_root: str, config: Config,
                 job_id: JobID | None = None, worker_id: WorkerID | None = None):
        self.mode = mode
        self.config = config
        # worker/main.py sets "worker" before us; drivers land here
        _fp.set_role(mode, only_if_unset=True)
        self.session_dir = session_dir
        self.worker_id = worker_id or WorkerID.from_random()
        self.job_id = job_id or JobID.from_int(0)
        self.node_id: NodeID | None = None

        self.memstore = MemoryStore()
        self.store = make_store(store_root, config)
        self._io = rpc.EventLoopThread()
        self._lock = threading.RLock()

        # reference counting
        self.owned: dict[ObjectID, _OwnedRef] = {}
        self.borrowed: dict[ObjectID, dict] = {}  # oid -> {count, owner}

        # task management
        self._task_counter = 0
        self._put_counter = 0
        self.current_task_id = TaskID.for_driver(self.job_id)
        self._task_ctx = threading.local()
        self.submitted: dict[bytes, dict] = {}  # task_id -> record
        self.leases: dict[tuple, list[_Lease]] = {}
        self._lease_requests: dict[tuple, int] = {}
        self._pending_by_key: dict[tuple, list] = {}
        # lease pre-warm bookkeeping (all io-loop-confined): when a key's
        # queue became non-empty (hard-escalation clock) and until when
        # soft prewarm is suppressed after a miss
        self._pending_since: dict[tuple, float] = {}
        self._soft_backoff: dict[tuple, float] = {}
        self._lease_reaper_running = False
        self._legacy = _legacy_task_path()

        # actors
        self.actor_clients: dict[bytes, _ActorClient] = {}

        # placement-group waiters parked on the pg pubsub channel
        # (io-loop-confined): pg_id -> [future resolved with the record]
        self._pg_waiters: dict[bytes, list] = {}

        # function registry
        self._fn_cache: dict[bytes, Any] = {}
        self._exported: set[bytes] = set()

        # execution (worker mode)
        self._exec_queue: queue_mod.Queue = queue_mod.Queue()
        self._cancelled_tasks: set[bytes] = set()
        self.task_channel_address = ""
        self._actor_instance = None
        self._actor_id: ActorID | None = None
        self._actor_reorder: dict[bytes, dict] = {}  # caller -> {next, heap}
        self._async_loop: rpc.EventLoopThread | None = None
        self._exec_pool = None  # ThreadPoolExecutor when max_concurrency>1
        # live-execution registry (debug_state): tasks currently inside
        # _exec_scope on any execution lane, keyed by a per-entry token
        # (GIL-atomic dict ops; no lock on the execution hot path)
        self._executing: dict[int, dict] = {}
        self._exec_seq = itertools.count(1)
        self._shutdown = False
        self._exiting = False

        # profiling (reference: core_worker profiling.h:28 — spans batched
        # to the GCS profile table; api.timeline() renders them)
        from ray_tpu._private.profiling import ProfileBuffer

        self._profile = ProfileBuffer(component_type=mode)
        self._last_profile_flush = 0.0
        # Trace spans (tracing.py) share this buffer/flush pipeline.
        tracing.bind_buffer(self._profile)
        # Continuous profiling plane: the always-on wall-clock sampler
        # (sampling_profiler.py); its window flushes on the same ~2s
        # cadence below. A KV-armed rate override lands via pubsub.
        _sprof.start(mode)
        # exemplar trace ids resolve against THIS cluster's trace table:
        # drop any kept by a previous connection in this process
        from ray_tpu._private import stats as _stats_mod

        _stats_mod.reset_exemplars()

        # connections
        self.raylet: rpc.Connection | None = None
        # tcp form, as raylets advertise each other (grant `granted_by`
        # addresses compare against this to spot remote-granted leases)
        self.raylet_address = raylet_address
        self.gcs: rpc.Connection | None = None
        self._peer_conns: dict[str, rpc.Connection] = {}
        # io-loop-confined per-address dial locks: without them a burst
        # of concurrent _peer() callers (arg fetches + borrow syncs of
        # one arriving task) each dial, and the losers' connections are
        # silently dropped from the cache while still carrying in-flight
        # calls — the orphaned conn+task cycles then get GC'd mid-await
        # and the calls neither complete NOR error (observed as a
        # permanent arg-fetch hang under the chaos sweep, seed 102)
        self._peer_dial_locks: dict[str, asyncio.Lock] = {}
        self.server = rpc.Server(self._handlers(), name=f"cw-{mode}")
        self.address = ""

        if mode == WORKER and not self._legacy:
            self._start_task_channel()
        self._connect(raylet_address, gcs_address)
        serialization.set_context(None, None)
        global_state.set_core_worker(self)
        self._io.submit(self._profile_flush_loop())

    # ------------------------------------------------------------------
    # bootstrap
    # ------------------------------------------------------------------

    def _handlers(self):
        if self._legacy:
            push_task = self.h_push_task_legacy
            push_actor_task = self.h_push_actor_task_legacy
        else:
            push_task = self.h_push_task
            push_actor_task = self.h_push_actor_task
        return {
            "push_task": push_task,
            "create_actor": self.h_create_actor,
            "push_actor_task": push_actor_task,
            "get_object": self.h_get_object,
            "recover_object": self.h_recover_object,
            "add_borrow": self.h_add_borrow,
            "remove_borrow": self.h_remove_borrow,
            "exit": self.h_exit,
            "checkpoint_actor": self.h_checkpoint_actor,
            "cancel_task": self.h_cancel_task,
            "get_stats": self.h_get_stats,
            "debug_state": self.h_debug_state,
            "debug_stacks": lambda conn, d: _debug.collect_stacks(),
            "ping": lambda conn, d: "pong",
        }

    def h_debug_state(self, conn, d):
        """Live-state snapshot of this process (sync handler: runs inline
        on the read loop — a wedged dispatcher/executor can't block it)."""
        return self.debug_state()

    async def h_get_stats(self, conn, d):
        """Process-local metrics snapshot — the raylet aggregates these
        into its own get_metrics reply so user-defined metrics
        (util/metrics.py) surface in cluster_metrics()."""
        from ray_tpu._private import stats

        return stats.snapshot()

    def _uds_dir(self) -> str:
        return os.path.join(self.session_dir, "sock")

    def _maybe_uds(self, address: str) -> str:
        """Same-node peers dial the sibling UDS listener (rpc.prefer_uds):
        loopback TCP costs ~0.25ms more per round trip on this class of
        kernel — a fifth of a small-task RTT."""
        return rpc.prefer_uds(
            address, self._uds_dir(),
            local_ips=("127.0.0.1", self.config.node_ip_address))

    def _connect(self, raylet_address: str, gcs_address: str):
        async def setup():
            _debug.start_loop_lag_monitor()
            port = await self.server.start_tcp(host=self.config.bind_host,
                                               uds_dir=self._uds_dir())
            self.address = f"{self.config.node_ip_address}:{port}"
            # GCS connection survives GCS restarts: on redial, re-subscribe
            # every actor channel and resync state missed while down
            # (reference: service_based_gcs_client.h reconnection).
            async def _gcs_reconnected(conn):
                await conn.call("subscribe", {"channel": _fp.CHANNEL})
                # a spec armed while we were disconnected was published
                # to nobody-here — resync from the KV like bootstrap does
                armed = await conn.call("kv_get", {"key": _fp.KV_KEY})
                if armed is not None:
                    _fp.apply_kv_value(armed)
                await conn.call("subscribe", {"channel": tracing.CHANNEL})
                rate = await conn.call("kv_get", {"key": tracing.KV_KEY})
                if rate is not None:
                    tracing.apply_kv_value(rate)
                await conn.call("subscribe", {"channel": _sprof.CHANNEL})
                hz = await conn.call("kv_get", {"key": _sprof.KV_KEY})
                if hz is not None:
                    _sprof.apply_kv_value(hz)
                if self.mode == DRIVER:
                    await conn.call("subscribe",
                                    {"channel": "worker_logs"})
                for client in list(self.actor_clients.values()):
                    if not client.subscribed:
                        continue
                    await conn.call("subscribe", {
                        "channel": f"actor:{client.actor_id.hex()}"})
                    info = await conn.call("get_actor",
                                           {"actor_id": client.actor_id})
                    if info:
                        self._apply_actor_update(info)
                        await self._flush_actor_queue(client)

            from ray_tpu.gcs.client import GcsClient

            director = rpc.ReconnectingConnection(
                self._maybe_uds(gcs_address),
                name="cw->gcs", on_reconnect=_gcs_reconnected,
                retry_timeout=self.config.gcs_reconnect_timeout_s,
                # a worker is spawned into a RUNNING cluster: a dead GCS
                # at bootstrap means the cluster is gone — die fast
                # (the raylet respawns workers if it's actually alive)
                # instead of lingering 10s as an un-registered orphan
                dial_timeout=(3.0 if self.mode == WORKER else 10.0))
            # Sharded control plane: key-partitioned table ops (KV,
            # object directory, actor/pg reads) route shard-direct; the
            # director keeps membership/pubsub/scheduling. With
            # gcs_shards=1 (default) this is a pure passthrough.
            self.gcs = GcsClient(director, self.config,
                                 uds_dir=self._uds_dir())
            self.gcs.set_push_handler(self._on_gcs_push)
            await self.gcs.ensure_connected()
            # Live fault-injection plane: failpoints armed through the
            # internal KV reach this process via pubsub, and a process
            # spawned AFTER the arming picks the spec up from the KV now.
            await self.gcs.call("subscribe", {"channel": _fp.CHANNEL})
            armed = await self.gcs.call("kv_get", {"key": _fp.KV_KEY})
            if armed:
                _fp.apply_kv_value(armed)
            # Live trace-sampling override: same KV+pubsub plane as the
            # failpoints, so a process spawned after the override picks
            # it up here.
            await self.gcs.call("subscribe", {"channel": tracing.CHANNEL})
            rate = await self.gcs.call("kv_get", {"key": tracing.KV_KEY})
            if rate:
                tracing.apply_kv_value(rate)
            # Live profiler arming (ray_tpu.set_profiling): same plane.
            await self.gcs.call("subscribe", {"channel": _sprof.CHANNEL})
            hz = await self.gcs.call("kv_get", {"key": _sprof.KV_KEY})
            if hz:
                _sprof.apply_kv_value(hz)
            # Duplex: the raylet sends actor-creation/kill requests back
            # over this same connection. A worker cannot function without
            # its raylet — it dies with it (reference: worker exits when
            # the raylet socket closes).
            async def _raylet_lost(conn):
                if self.mode == WORKER and not self._shutdown:
                    logger.warning("raylet connection lost; worker exiting")
                    os._exit(1)

            # Workers are spawned BY a raylet that is already listening:
            # a refused dial here means the raylet died — fail fast
            # (die) instead of retrying 10s as a bootstrap zombie that
            # outlives its whole node (drivers keep the longer budget:
            # they may race a node that is still coming up).
            self.raylet = await rpc.connect(self._maybe_uds(raylet_address),
                                            handlers=self._handlers(),
                                            on_disconnect=_raylet_lost,
                                            name="cw->raylet",
                                            timeout=(2.0
                                                     if self.mode == WORKER
                                                     else 10.0))
            reply = await self.raylet.call("register_client", {
                "kind": self.mode,
                "worker_id": self.worker_id.binary(),
                "address": self.address,
                "pid": os.getpid(),
                "flavor": os.environ.get("RAY_TPU_WORKER_FLAVOR", "cpu"),
                "task_channel": self.task_channel_address,
            })
            self.node_id = NodeID(reply["node_id"])
            if self.mode == DRIVER:
                job = await self.gcs.call(
                    "register_job",
                    {"driver_addr": self.address,
                     "token": self.worker_id.hex()})
                self.job_id = JobID(job["job_id"])
                # Worker print()/stderr lines stream to this console
                # (reference: log_monitor.py:48).
                await self.gcs.call("subscribe",
                                    {"channel": "worker_logs"})
                self.current_task_id = TaskID.for_driver(self.job_id)

        self._io.run(setup(), timeout=30)

    # ------------------------------------------------------------------
    # reference counting
    # ------------------------------------------------------------------

    def register_ref(self, ref: ObjectRef):
        with self._lock:
            rec = self.owned.get(ref.id())
            if rec is not None:
                rec.local += 1
            else:
                b = self.borrowed.get(ref.id())
                if b is not None:
                    b["count"] += 1
                # refs neither owned nor borrowed (e.g. freshly created by
                # submit) are registered explicitly by their creators.

    def _register_owned(self, object_id: ObjectID, plasma=False) -> _OwnedRef:
        with self._lock:
            rec = self.owned.get(object_id)
            if rec is None:
                rec = self.owned[object_id] = _OwnedRef()
            rec.plasma = rec.plasma or plasma
            return rec

    def release_ref(self, object_id: ObjectID):
        if self._shutdown:
            return
        with self._lock:
            rec = self.owned.get(object_id)
            if rec is not None:
                rec.local -= 1
                if rec.total() <= 0:
                    self._delete_owned(object_id, rec)
                return
            b = self.borrowed.get(object_id)
            if b is not None:
                b["count"] -= 1
                if b["count"] <= 0:
                    self.borrowed.pop(object_id, None)
                    self.memstore.delete(object_id)
                    owner = b["owner"]
                    if owner and owner != self.address:
                        self._io.submit(self._notify_owner(
                            owner, "remove_borrow",
                            {"object_id": object_id.binary()}))

    async def _notify_owner(self, owner_addr, method, data):
        try:
            conn = await self._peer(owner_addr)
            await conn.notify(method, data)
        except Exception:
            pass

    def _delete_owned(self, object_id: ObjectID, rec: _OwnedRef):
        self.owned.pop(object_id, None)
        self.memstore.delete(object_id)
        if rec.plasma:
            self._io.submit(self._free_plasma([object_id.binary()]))

    async def _free_plasma(self, oids):
        try:
            await self.raylet.call("free_objects", {"object_ids": oids})
        except Exception:
            pass

    def serialize_ref(self, ref: ObjectRef) -> dict:
        """Called from ObjectRef.__reduce__. Pins the object until the
        receiving side registers its borrow (released on task reply or
        explicitly)."""
        object_id = ref.id()
        with self._lock:
            rec = self.owned.get(object_id)
            if rec is not None:
                rec.pins += 1
                owner = self.address
                plasma = rec.plasma
            else:
                b = self.borrowed.get(object_id)
                owner = b["owner"] if b else ref.owner_address
                plasma = ref.is_plasma()
                if b is not None and owner:
                    self._io.submit(self._notify_owner(
                        owner, "add_borrow",
                        {"object_id": object_id.binary(), "transit": True}))
        ctx = getattr(self._task_ctx, "serialized_refs", None)
        if ctx is not None:
            ctx.append(object_id)
        return {"id": object_id.binary(), "owner": owner, "plasma": plasma}

    def deserialize_ref(self, desc: dict) -> ObjectRef:
        object_id = ObjectID(desc["id"])
        owner = desc.get("owner", "")
        with self._lock:
            if object_id in self.owned:
                ref = ObjectRef(object_id, self.address,
                                self.owned[object_id].plasma)
                return ref
            b = self.borrowed.get(object_id)
            if b is None:
                self.borrowed[object_id] = {"count": 0, "owner": owner}
                if owner and owner != self.address:
                    self._io.submit(self._borrow_sync(owner, object_id))
        return ObjectRef(object_id, owner, desc.get("plasma", False))

    async def _borrow_sync(self, owner, object_id):
        try:
            conn = await self._peer(owner)
            await conn.call("add_borrow", {"object_id": object_id.binary()})
        except Exception:
            pass

    # handlers (owner side)
    async def h_add_borrow(self, conn, d):
        object_id = ObjectID(d["object_id"])
        with self._lock:
            rec = self.owned.get(object_id)
            if rec is not None:
                rec.borrows += 1
        return True

    async def h_remove_borrow(self, conn, d):
        object_id = ObjectID(d["object_id"])
        with self._lock:
            rec = self.owned.get(object_id)
            if rec is not None:
                rec.borrows -= 1
                if rec.total() <= 0:
                    self._delete_owned(object_id, rec)
        return True

    # ------------------------------------------------------------------
    # put / get / wait
    # ------------------------------------------------------------------

    def put(self, value: Any) -> ObjectRef:
        self._put_counter += 1
        object_id = ObjectID.for_put(self._current_task_id(), self._put_counter)
        header, buffers = serialization.serialize(value)
        size = serialization.total_size(header, buffers)
        rec = self._register_owned(object_id)
        if size <= self.config.max_direct_call_object_size:
            payload = b"".join([header, *[bytes(b) for b in buffers]])
            self.memstore.put(object_id, payload)
        else:
            rec.plasma = True
            try:
                self.store.put_serialized(object_id, header, buffers)
            except MemoryError:
                # store full: the raylet spills asynchronously after
                # seals — force a synchronous spill pass and retry once
                # (reference: plasma create retries after SpillObjects)
                self._io.run(self.raylet.call(
                    "spill_now", {"need_bytes": size}))
                self.store.put_serialized(object_id, header, buffers)
            self._io.run(self.raylet.call("notify_object_sealed", {
                "object_id": object_id.binary(), "size": size}))
            self.memstore.put(object_id, IN_PLASMA)
        return ObjectRef(object_id, self.address, rec.plasma)

    def get(self, refs: list[ObjectRef], timeout: float | None = None):
        deadline = time.monotonic() + timeout if timeout is not None else None
        results: list[Any] = [None] * len(refs)
        for i, ref in enumerate(refs):
            remaining = None
            if deadline is not None:
                remaining = max(0.0, deadline - time.monotonic())
            results[i] = self._get_one(ref, remaining)
        return results

    def _get_one(self, ref: ObjectRef, timeout: float | None):
        object_id = ref.id()
        found, value, is_exc = self.memstore.get_if_ready(object_id)
        if not found:
            self._ensure_fetch(ref)
            ready = self.memstore.wait([object_id], 1, timeout)
            if object_id not in ready:
                raise exc.GetTimeoutError(
                    f"get() timed out waiting for {object_id.hex()[:12]}")
            found, value, is_exc = self.memstore.get_if_ready(object_id)
        if value is IN_PLASMA:
            return self._read_plasma(object_id, timeout,
                                     owner=ref.owner_address)
        result = serialization.deserialize(value)
        if is_exc:
            raise result
        return result

    def _read_plasma(self, object_id: ObjectID, timeout: float | None,
                     owner: str = ""):
        """Resolve a plasma-resident object, pulling from remote nodes and
        — when every copy is gone — reconstructing it from lineage
        (reference: object_recovery_manager.h:87-103: pin existing copy →
        else re-submit the creating task)."""
        deadline = (time.monotonic() + timeout) if timeout is not None else None
        while True:
            buf = self.store.get(object_id)
            if buf is not None:
                break
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise exc.GetTimeoutError(
                        f"timed out pulling {object_id.hex()[:12]}")
            # Bounded probe so total loss is *detected* instead of blocking
            # in the pull forever.
            probe = 2.0 if remaining is None else max(0.05, min(2.0, remaining))
            ok = self._io.run(self.raylet.call(
                "wait_object_local",
                {"object_id": object_id.binary(), "timeout": probe}))
            if ok is True:
                continue
            # ok is False (probe timeout) or "lost" (the raylet's pull
            # saw an EMPTY directory past its deadline and propagated
            # typed loss — skip further probe cycles and go straight to
            # the location re-check + lineage recovery below)
            try:
                locations = self._io.run(self.gcs.call(
                    "get_object_locations",
                    {"object_id": object_id.binary()}))
            except Exception:
                locations = None
            if locations:
                continue  # a copy exists somewhere; keep pulling
            if not self._recover_object(object_id, owner):
                raise exc.ObjectLostError(object_id.hex())
            # Reconstruction resubmitted the creating task; wait for the
            # fresh value (memstore flips back to ready on task reply for
            # the owner; borrowers just keep probing the pull path).
            if object_id in self.owned:
                self.memstore.wait([object_id], 1,
                                   remaining if remaining is not None else 30.0)
        try:
            value = serialization.deserialize(buf.view)
        finally:
            # Note: zero-copy numpy views keep the mmap alive via memoryview.
            buf.close()
        if isinstance(value, exc.RayTpuError):
            raise value
        return value

    # ---- object reconstruction (reference: object_recovery_manager.h) ----

    def _recover_object(self, object_id: ObjectID, owner: str = "") -> bool:
        """Every copy of a plasma object is gone: re-execute the task that
        created it (owner-side, bounded by the task's max_retries), or ask
        the owner to if we're a borrower. Returns True if recovery is in
        flight."""
        with self._lock:
            rec = self.owned.get(object_id)
        if rec is not None:
            return self._try_reconstruct(object_id)
        if owner and owner != self.address:
            try:
                return bool(self._io.run(self._ask_owner_recover(
                    object_id, owner)))
            except Exception as e:
                logger.warning("owner %s unreachable for recovery of %s: %s",
                               owner, object_id.hex()[:12], e)
                return False
        return False

    async def _ask_owner_recover(self, object_id: ObjectID, owner: str):
        conn = await self._peer(owner)
        return await conn.call("recover_object",
                               {"object_id": object_id.binary()})

    async def h_recover_object(self, conn, d):
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, self._try_reconstruct, ObjectID(d["object_id"]))

    def _try_reconstruct(self, object_id: ObjectID) -> bool:
        """Re-submit the lineage task for a lost object. Idempotent while a
        reconstruction is already in flight; the whole check-then-insert is
        under the lock (a get()ing user thread and a borrower's RPC both
        race into here)."""
        with self._lock:
            rec = self.owned.get(object_id)
            lineage = rec.lineage_task if rec is not None else None
            if lineage is None:
                return False
            spec = lineage["spec"]
            task_id = spec["task_id"]
            if task_id in self.submitted:
                return True  # already reconstructing
            if lineage["retries"] <= 0:
                return False
            lineage["retries"] -= 1
            self.submitted[task_id] = {
                "spec": spec, "pinned": [],
                "retries": lineage["retries"], "cancelled": False,
            }
        logger.warning("object %s lost; reconstructing via task %s "
                       "(%d lineage retries left)", object_id.hex()[:12],
                       spec["name"], lineage["retries"])
        for i in range(spec["num_returns"]):
            rid = ObjectID.for_return(TaskID(task_id), i)
            self.memstore.reset(rid)
        self._io.submit(self._submit_async(spec))
        return True

    def _ensure_fetch(self, ref: ObjectRef):
        """Make sure something will eventually fill the memstore entry."""
        object_id = ref.id()
        with self._lock:
            if object_id in self.owned:
                return  # reply path will fill it
            b = self.borrowed.get(object_id)
            owner = (b or {}).get("owner") or ref.owner_address
        if not owner or owner == self.address:
            return
        self.memstore.open(object_id)
        self._io.submit(self._fetch_from_owner(object_id, owner))

    async def _fetch_from_owner(self, object_id: ObjectID, owner: str):
        try:
            conn = await self._peer(owner)
            reply = await conn.call("get_object",
                                    {"object_id": object_id.binary()})
            if reply["kind"] == "plasma":
                self.memstore.put(object_id, IN_PLASMA)
            else:
                self.memstore.put(object_id, reply["data"],
                                  is_exception=reply.get("err", False))
        except Exception as e:
            header, bufs = serialization.serialize(
                exc.ObjectLostError(object_id.hex()))
            payload = b"".join([header, *[bytes(b) for b in bufs]])
            logger.debug("fetch from owner %s failed: %s", owner, e)
            self.memstore.put(object_id, payload, is_exception=True)
            # A dead owner must not leak the `open`ed slot: if nothing on
            # this process tracks the ref (so no release will ever delete
            # the entry), drop it once current waiters have observed the
            # error — the grace covers sync memstore.wait()ers woken by
            # the put above; future gets re-open + re-fetch + re-fail.
            with self._lock:
                tracked = (object_id in self.owned
                           or object_id in self.borrowed)
            if not tracked:
                asyncio.get_running_loop().call_later(
                    1.0, self.memstore.delete, object_id)

    async def h_get_object(self, conn, d):
        """Owner service: long-poll for a small object's value
        (reference: core_worker.proto GetObjectStatus).

        One ready-callback registration per waiter. The previous
        implementation parked an executor THREAD per waiter, re-polling
        `memstore.wait` in 5s slices — N borrowers of a slow object cost
        N blocked threads plus a wake-per-slice churn loop. Now a result
        arriving wakes exactly one coalesced loop callback, and an owner
        dropping the entry (every ref released) fires the same callback
        so the waiter sees loss instead of hanging."""
        object_id = ObjectID(d["object_id"])
        found, value, is_exc = self.memstore.get_if_ready(object_id)
        if not found:
            with self._lock:
                known = object_id in self.owned
            if not known:
                raise exc.ObjectLostError(object_id.hex())
            loop = asyncio.get_running_loop()
            caller = rpc.loop_call_queue(loop)
            fut = loop.create_future()

            def on_ready():
                try:
                    caller.call(lambda: fut.done() or fut.set_result(None))
                except RuntimeError:
                    pass  # loop closed: the waiter is gone

            # create=False: the owner may have released the object between
            # the check and the registration — re-creating the entry would
            # leave a pending slot nothing will ever fill.
            if not self.memstore.add_ready_callback(object_id, on_ready,
                                                    create=False):
                raise exc.ObjectLostError(object_id.hex())
            try:
                await fut
            finally:
                # waiter cancelled (loop teardown, client gone) before
                # the object resolved: don't leave the callback — and
                # the future it closes over — parked in the entry
                if not fut.done():
                    self.memstore.remove_ready_callback(object_id,
                                                        on_ready)
            found, value, is_exc = self.memstore.get_if_ready(object_id)
            if not found:
                # entry deleted under the waiter: object was released
                raise exc.ObjectLostError(object_id.hex())
        if value is IN_PLASMA:
            return {"kind": "plasma"}
        return {"kind": "bytes", "data": value, "err": is_exc}

    def wait(self, refs: list[ObjectRef], num_returns=1,
             timeout: float | None = None, fetch_local=True):
        for ref in refs:
            self._ensure_fetch(ref)
        ids = [r.id() for r in refs]
        ready_ids = self.memstore.wait(ids, num_returns, timeout)
        ready, not_ready = [], []
        for ref in refs:
            if ref.id() in ready_ids and len(ready) < max(num_returns,
                                                          len(ready_ids)):
                ready.append(ref)
            else:
                not_ready.append(ref)
        # cap ready at num_returns preserving order
        if len(ready) > num_returns:
            overflow = ready[num_returns:]
            ready = ready[:num_returns]
            not_ready = overflow + not_ready
        return ready, not_ready

    # ------------------------------------------------------------------
    # function registry (reference: python/ray/function_manager.py)
    # ------------------------------------------------------------------

    def export_function(self, pickled: bytes, kind="fn") -> bytes:
        fn_id = common.function_id(pickled)
        if fn_id not in self._exported:
            key = f"{kind}:{self.job_id.hex()}:{fn_id.hex()}"
            self._io.run(self.gcs.call("kv_put", {
                "key": key, "value": pickled, "overwrite": False}))
            self._exported.add(fn_id)
        return fn_id

    def fetch_function(self, fn_id: bytes, job_id: bytes, kind="fn"):
        if fn_id in self._fn_cache:
            return self._fn_cache[fn_id]
        key = f"{kind}:{JobID(job_id).hex()}:{fn_id.hex()}"
        deadline = time.monotonic() + 30
        while True:
            data = self._io.run(self.gcs.call("kv_get", {"key": key}))
            if data is not None:
                break
            if time.monotonic() > deadline:
                raise RuntimeError(f"function {fn_id.hex()} never exported")
            time.sleep(0.05)
        fn = cloudpickle.loads(data)
        self._fn_cache[fn_id] = fn
        return fn

    # ------------------------------------------------------------------
    # task submission (reference: direct_task_transport.cc)
    # ------------------------------------------------------------------

    def _current_task_id(self) -> TaskID:
        # Async-actor coroutines carry their task id in a contextvar (they
        # all share the loop thread); sync tasks use the thread-local.
        return (_ASYNC_TASK_ID.get()
                or getattr(self._task_ctx, "task_id", None)
                or self.current_task_id)

    def _serialize_args(self, args, kwargs) -> tuple[list[dict], list[ObjectID]]:
        """Returns (arg descriptors, pinned object ids)."""
        if not args and not kwargs:
            return [], []
        self._task_ctx.serialized_refs = []
        descs = []
        try:
            for value in args:
                descs.append(self._serialize_one_arg(value))
            if kwargs:
                descs.append({"kind": "kwargs",
                              "data": serialization.dumps(kwargs)})
            pinned = list(self._task_ctx.serialized_refs)
        finally:
            self._task_ctx.serialized_refs = None
        return descs, pinned

    def _serialize_one_arg(self, value) -> dict:
        if isinstance(value, ObjectRef):
            desc = self.serialize_ref(value)
            return {"kind": "ref", **desc}
        data = serialization.dumps(value)
        if len(data) > self.config.max_direct_call_object_size:
            # Large pass-by-value arg: promote to a put (owner = caller).
            ref = self.put(value)
            desc = self.serialize_ref(ref)
            # keep the ref alive until pinning is recorded
            return {"kind": "ref", **desc}
        return {"kind": "inline", "data": data}

    def _make_return_refs(self, task_id: TaskID, num_returns: int):
        """Register + open a task's return set with two lock hops total
        (the per-return _register_owned/register_ref/open triple cost three
        lock round-trips EACH — pure bookkeeping churn on the serve request
        path where every query is a return slot)."""
        return_ids = [ObjectID.for_return(task_id, i)
                      for i in range(num_returns)]
        with self._lock:
            for return_id in return_ids:
                rec = self.owned.get(return_id)
                if rec is None:
                    rec = self.owned[return_id] = _OwnedRef()
                rec.local += 1
        self.memstore.open_many(return_ids)
        refs = []
        for return_id in return_ids:
            ref = ObjectRef(return_id, self.address, False, _register=False)
            ref._registered = True  # owned count bumped above
            refs.append(ref)
        return refs

    def _release_pins(self, pinned: list[ObjectID]):
        with self._lock:
            for object_id in pinned:
                rec = self.owned.get(object_id)
                if rec is not None:
                    rec.pins -= 1
                    if rec.total() <= 0:
                        self._delete_owned(object_id, rec)
                    continue
                b = self.borrowed.get(object_id)
                if b is not None and b["owner"]:
                    self._io.submit(self._notify_owner(
                        b["owner"], "remove_borrow",
                        {"object_id": object_id.binary()}))

    def make_task_template(self, *, fn_id: bytes, name: str, num_returns=1,
                           resources=None, max_retries=None,
                           placement_group=None, bundle_index=-1) -> dict:
        """Pre-build the static prefix of a task spec (descriptor, owner
        address, quantized resources) so `fn.remote()` pays one dict copy
        per call instead of re-quantizing and re-assembling the whole spec
        (reference analog: the cached TaskSpecBuilder prefix in
        direct_task_transport). Cached per RemoteFunction."""
        return common.make_task_spec(
            task_id=b"",
            job_id=self.job_id.binary(),
            name=name,
            fn_id=fn_id,
            owner_addr=self.address,
            owner_worker_id=self.worker_id.binary(),
            args=None,
            num_returns=num_returns,
            resources=resources or {"CPU": 1},
            max_retries=(self.config.task_max_retries
                         if max_retries is None else max_retries),
            placement_group_id=placement_group,
            bundle_index=bundle_index,
        )

    def submit_task(self, *, fn_id: bytes = b"", name: str = "", args=(),
                    kwargs=None, num_returns=1, resources=None,
                    max_retries=None, placement_group=None, bundle_index=-1,
                    template: dict | None = None) -> list[ObjectRef]:
        task_id = TaskID.for_task(self.job_id)
        descs, pinned = self._serialize_args(args, kwargs)
        if template is not None:
            spec = dict(template)
            spec["task_id"] = task_id.binary()
            spec["args"] = descs
            num_returns = spec["num_returns"]
        else:
            spec = common.make_task_spec(
                task_id=task_id.binary(),
                job_id=self.job_id.binary(),
                name=name,
                fn_id=fn_id,
                owner_addr=self.address,
                owner_worker_id=self.worker_id.binary(),
                args=descs,
                num_returns=num_returns,
                resources=resources or {"CPU": 1},
                max_retries=(self.config.task_max_retries
                             if max_retries is None else max_retries),
                placement_group_id=placement_group,
                bundle_index=bundle_index,
            )
        # Trace entry point: continues an ambient trace (nested submit
        # from a traced task) or head-samples a fresh root. The sampled
        # wire context travels IN the spec through lease request ->
        # raylet -> worker exec (tracing.py).
        ctx = tracing.maybe_trace()
        if ctx is not None:
            spec["trace"] = tracing.to_wire(ctx)
        refs = self._make_return_refs(task_id, num_returns)
        self.submitted[task_id.binary()] = {
            "spec": spec, "pinned": pinned,
            "retries": spec["max_retries"], "cancelled": False,
            "t0": time.time(), "trace": ctx,
        }
        M_TASKS_SUBMITTED.inc()
        self._io.submit_nowait(self._submit_async(spec))
        return refs

    async def _submit_async(self, spec):
        key = common.scheduling_key(spec)
        rec = self.submitted.get(spec["task_id"])
        if rec is None or rec["cancelled"]:
            self._fail_task(spec, exc.TaskCancelledError(
                spec["task_id"].hex()), release=True)
            return
        pending = self._pending_by_key.setdefault(key, [])
        if not pending:
            self._pending_since[key] = time.monotonic()
        pending.append(spec)
        await self._drain_pending(key)

    def _find_lease(self, key) -> _Lease | None:
        """Least-loaded live lease with pipeline capacity — tasks fan
        across every live lease instead of filling lease 0 to the cap
        before lease 1 sees any work."""
        best = None
        for lease in self.leases.get(key, []):
            if (not lease.conn.closed
                    and lease.inflight < self.config.max_tasks_in_flight_per_worker
                    and (best is None or lease.inflight < best.inflight)):
                best = lease
        return best

    def _live_leases(self, key) -> list[_Lease]:
        return [lease for lease in self.leases.get(key, [])
                if not lease.conn.closed]

    def _maybe_request_leases(self, key):
        """Request leases ahead of demand, up to a soft target of
        ceil(outstanding work / max_tasks_in_flight_per_worker) leases —
        the round-7 path requested exactly one lease at a time, each
        granted only after the previous grant's drain, which serialized
        burst ramp-up behind worker-spawn latency. One batched request
        RPC is outstanding per key at a time; while ≥1 lease is already
        working the request is SOFT (the raylet grants only from idle
        workers, never spawning), escalating to a hard request when the
        queue has waited past lease_escalation_s — so a burst of tiny
        tasks can't spawn-storm the node while long tasks still scale
        out (reference: direct_task_transport.h pipelined lease
        requests)."""
        if self._lease_requests.get(key, 0) > 0:
            return
        pending = self._pending_by_key.get(key)
        if not pending:
            return
        live = self._live_leases(key)
        cap = max(1, self.config.max_tasks_in_flight_per_worker)
        inflight = sum(lease.inflight for lease in live)
        target = -(-(len(pending) + inflight) // cap)  # ceil
        count = min(target - len(live), self.config.max_lease_batch)
        if count <= 0:
            return
        now = time.monotonic()
        soft = bool(live) and (now - self._pending_since.get(key, now)
                               < self.config.lease_escalation_s)
        if soft and now < self._soft_backoff.get(key, 0.0):
            return
        if not soft and live and _fp.ARMED:
            # escalation seam (soft prewarm -> hard, may-spawn request):
            # `raise` models a lost escalation — skip this round; the
            # retry timer re-evaluates, so liveness must survive it
            try:
                _fp.fire_strict("lease.escalate")
            except _fp.FailpointError:
                return
        self._lease_requests[key] = 1
        asyncio.ensure_future(
            self._request_leases(key, pending[0], count, soft))

    async def _request_leases(self, key, spec, count: int, soft: bool):
        M_LEASE_REQUESTS.inc()
        lease_t0 = time.time()
        try:
            if _fp.ARMED:
                # lease-request seam: `raise` exercises the typed failure
                # path (queued tasks -> WorkerCrashedError / backoff)
                await _fp.fire_async_strict("lease.request")
            target = self.raylet
            target_addr = None  # None = local raylet
            hops = 0
            while True:
                M_LEASE_RPCS.inc()
                reply = await target.call("request_worker_lease",
                                          {"spec": spec, "hops": hops,
                                           "count": count, "soft": soft})
                if reply.get("spillback"):
                    target_addr = reply["spillback"]
                    target = await self._peer(target_addr)
                    hops = int(reply.get("hops", hops + 1))
                    continue
                break
            grants = reply.get("grants")
            if grants is None:
                grants = [reply] if reply.get("granted") else []
            grants = await self._claim_forwarded_grants(grants)
            for grant in grants:
                conn = await self._peer(grant["worker_address"])
                lease = _Lease(grant["lease_id"], grant["worker_id"],
                               grant["worker_address"], conn,
                               grant.pop("_raylet_conn", None) or target,
                               task_conn=await self._task_channel_conn(
                                   grant.get("task_channel")))
                self.leases.setdefault(key, []).append(lease)
            if grants:
                now = time.time()
                root = tracing.from_wire(spec.get("trace"))
                M_LEASE_WAIT_S.observe(now - lease_t0,
                                       exemplar=tracing.exemplar_of(root))
                if root is not None:
                    tracing.record_span("task.lease_wait", lease_t0, now,
                                        tracing.child(root),
                                        {"name": spec.get("name", "?"),
                                         "count": len(grants)})
            if not grants:
                # soft miss: the idle pool is dry; stop re-asking for a
                # beat so the raylet isn't hammered with no-op requests.
                # The retry timer matters for liveness, not just pacing:
                # if every in-flight task is blocked (e.g. nested
                # ray.get on a producer still queued behind them), no
                # push ever completes, so no drain would re-evaluate the
                # request — and the escalation clock (lease_escalation_s
                # → hard, may-spawn request) must keep being consulted.
                self._soft_backoff[key] = time.monotonic() + 0.2
                asyncio.get_running_loop().call_later(
                    0.25, self._maybe_request_leases, key)
            remote_granters = {g.get("granted_by") for g in grants
                               if g.get("granted_by")}
            remote_granters.discard(self.raylet_address)
            if target_addr is not None and any(
                    not g.get("granted_by") for g in grants):
                # granted_by names the true executor; only fall back to
                # the redial target for replies that predate the field —
                # a raylet that merely FORWARDED the request must not
                # receive arg pushes for a task it will never run
                remote_granters.add(target_addr)
            if grants and remote_granters and self.raylet is not None:
                # Spilled-back lease (owner redial OR a raylet→raylet
                # forwarded grant — `granted_by` names the true node):
                # the task will run on a remote node while its plasma
                # args live here. Hint our raylet to start pushing them
                # so the transfer overlaps with task dispatch
                # (PushManager parity, reference: push_manager.h:29 —
                # dedup happens receiver-side). Purely an optimization:
                # a hint failure must never fail the granted lease.
                try:
                    arg_ids = [a["id"] for a in spec.get("args", [])
                               if a.get("kind") == "ref"
                               and a.get("plasma")]
                    for addr in remote_granters if arg_ids else ():
                        self._io.submit(self.raylet.notify(
                            "push_objects_to",
                            {"object_ids": arg_ids, "target": addr}))
                except Exception:
                    pass
        except Exception as e:
            if self._live_leases(key):
                # queued work is still draining on live leases: a failed
                # PRE-WARM must not fail tasks that never needed it
                self._soft_backoff[key] = time.monotonic() + 0.5
                asyncio.get_running_loop().call_later(
                    0.6, self._maybe_request_leases, key)
            else:
                pending = self._pending_by_key.pop(key, [])
                for p in pending:
                    self._fail_task(p, exc.WorkerCrashedError(
                        f"lease request failed: {e}"), release=True)
                return
        finally:
            self._lease_requests[key] = 0
            self._ensure_lease_reaper()
        await self._drain_pending(key)

    async def _claim_forwarded_grants(self, grants: list[dict]) -> list[dict]:
        """Adopt leases granted by a REMOTE raylet for a forwarded
        (spillback-chain) request. Such grants arrive over the chain
        holder-less — the granting raylet parks them in its unadopted
        set; claiming them over OUR connection (adopt_leases) re-arms
        holder-death reclaim exactly as for a direct grant, and pins the
        connection return_worker must use (`_raylet_conn`). A grant the
        granting raylet already reaped (we took longer than its adoption
        deadline) is dropped here; the lease retry timer re-requests."""
        claim: dict[str, list[dict]] = {}
        out = []
        for g in grants:
            if g.pop("adopt", False):
                claim.setdefault(g["granted_by"], []).append(g)
            else:
                out.append(g)
        for addr, gs in claim.items():
            try:
                conn = await self._peer(addr)
                reply = await conn.call(
                    "adopt_leases",
                    {"lease_ids": [g["lease_id"] for g in gs]})
                adopted = set(reply.get("adopted") or ())
            except Exception as e:
                logger.warning("adopting %d spillback lease(s) at %s "
                               "failed (%s); dropping them", len(gs),
                               addr, e)
                continue
            for g in gs:
                if g["lease_id"] in adopted:
                    g["_raylet_conn"] = conn
                    out.append(g)
        return out

    async def _maybe_request_lease(self, key, spec):
        # Round-7 control arm (RAY_TPU_TASK_LEGACY): one outstanding
        # single-lease hard request per scheduling key at a time.
        if self._lease_requests.get(key, 0) > 0:
            return
        self._lease_requests[key] = 1
        try:
            target = self.raylet
            hops = 0
            attempts = 0
            while True:
                M_LEASE_RPCS.inc()
                reply = await target.call("request_worker_lease",
                                          {"spec": spec, "hops": hops})
                if reply.get("spillback"):
                    target = await self._peer(reply["spillback"])
                    hops = int(reply.get("hops", hops + 1))
                    continue
                claimed = await self._claim_forwarded_grants([reply])
                if claimed:
                    reply = claimed[0]
                    break
                # adoption raced the granting raylet's unadopted deadline
                # (or its dial transiently failed): the lease is back in
                # that raylet's idle pool — re-request instead of failing
                # a healthy cluster's tasks
                attempts += 1
                if attempts >= 3:
                    raise exc.WorkerCrashedError(
                        "spillback lease reclaimed before adoption "
                        f"({attempts} attempts)")
                target = self.raylet
                hops = 0
                await asyncio.sleep(0.1 * attempts)
            conn = await self._peer(reply["worker_address"])
            lease = _Lease(reply["lease_id"], reply["worker_id"],
                           reply["worker_address"], conn,
                           reply.pop("_raylet_conn", None) or target)
            self.leases.setdefault(key, []).append(lease)
        except Exception as e:
            pending = self._pending_by_key.pop(key, [])
            for p in pending:
                self._fail_task(p, exc.WorkerCrashedError(
                    f"lease request failed: {e}"), release=True)
            return
        finally:
            self._lease_requests[key] = 0
        await self._drain_pending(key)

    async def _drain_pending(self, key, inline_ok=True):
        pending = self._pending_by_key.get(key, [])
        while pending:
            lease = self._find_lease(key)
            if lease is None:
                if self._legacy:
                    await self._maybe_request_lease(key, pending[0])
                    return
                break
            spec = pending.pop(0)
            # Reserve the in-flight slot synchronously so concurrent drains
            # see correct pipelining capacity, then push without blocking
            # the drain loop (lease pipelining, reference:
            # direct_task_transport.h max_tasks_in_flight_per_worker).
            lease.inflight += 1
            if lease.inflight == 1:
                # burst boundary: pick this burst's connection by the
                # queue depth behind the task being pushed
                lease.burst_channel = len(pending) < 2
            lease.last_used = time.monotonic()
            if inline_ok and not pending and not self._legacy:
                # SOLE task of this drain (the sync-call pattern): run the
                # push in THIS coroutine instead of spawning a Task for
                # it. Only when nothing else was popped in this drain —
                # an ensure_future'd sibling starts on the NEXT loop
                # tick, so sending inline here would invert frame order
                # within the burst. A push's own tail drain passes
                # inline_ok=False, so the await chain push→drain→push
                # can never grow beyond one level.
                await self._push_to_lease(lease, spec, key)
                pending = self._pending_by_key.get(key, [])
                continue
            inline_ok = False  # later pops must queue behind this one
            asyncio.ensure_future(self._push_to_lease(lease, spec, key))
        if not pending:
            self._pending_since.pop(key, None)
        if not self._legacy:
            self._maybe_request_leases(key)

    async def _task_channel_conn(self, address) -> rpc.Connection | None:
        """Dial a lease's direct task channel when its socket file is
        reachable from this node (a remote lease's path never is)."""
        if not address or not address.startswith("unix:"):
            return None
        if not os.path.exists(address[len("unix:"):]):
            return None
        conn = self._peer_conns.get(address)
        if conn is not None and not conn.closed:
            return conn
        lock = self._peer_dial_locks.setdefault(address, asyncio.Lock())
        async with lock:
            conn = self._peer_conns.get(address)
            if conn is None or conn.closed:
                try:
                    conn = await rpc.connect(address,
                                             name="cw->task-channel")
                except Exception as e:
                    logger.debug("task channel dial failed (%s); rpc path",
                                 e)
                    return None
                self._cache_peer(address, conn)
        return conn

    def _note_pushed(self, rec, spec):
        """Queue-wait hop closes when the push leaves the owner: observe
        the histogram always, record the span when the task is traced."""
        now = time.time()
        t0 = rec.get("t0")
        if t0 is not None and "t_push" not in rec:
            ctx = rec.get("trace")
            M_QUEUE_WAIT_S.observe(now - t0,
                                   exemplar=tracing.exemplar_of(ctx))
            if ctx is not None:
                tracing.record_span("task.queue_wait", t0, now,
                                    tracing.child(ctx),
                                    {"name": spec.get("name", "?")})
        rec["t_push"] = now

    async def _push_to_lease(self, lease: _Lease, spec, key):
        rec = self.submitted.get(spec["task_id"])
        if rec is None or rec["cancelled"]:
            lease.inflight -= 1
            self._fail_task(spec, exc.TaskCancelledError(""), release=True)
            return
        rec["lease"] = lease
        self._note_pushed(rec, spec)
        try:
            reply = await lease.push_conn.call("push_task", {"spec": spec})
            self._handle_task_reply(spec, reply)
        except (rpc.ConnectionLost, rpc.RemoteError,
                _fp.FailpointError) as e:
            # FailpointError: an armed `rpc.send=raise` fires in OUR send
            # path — the push never left; route it through the same
            # retry/fail machinery (letting it escape would leak the
            # inflight slot and hang the caller)
            lease.inflight -= 1
            await self._handle_push_failure(spec, key, lease, e)
            return
        lease.inflight -= 1
        lease.last_used = time.monotonic()
        if self._legacy:
            await self._maybe_return_lease(key, lease)
        await self._drain_pending(key, inline_ok=False)

    async def _maybe_return_lease(self, key, lease: _Lease):
        # Round-7 control arm: per-push grace timer (one asyncio.sleep
        # coroutine + loop timer PER completed task — the optimized path
        # runs one shared reaper instead, _lease_reaper).
        if lease.inflight > 0 or self._pending_by_key.get(key):
            return
        # grace period for bursty submission patterns
        await asyncio.sleep(0.25)
        if (lease.inflight > 0 or self._pending_by_key.get(key)
                or lease not in self.leases.get(key, [])):
            return
        self.leases[key].remove(lease)
        try:
            await lease.raylet_conn.call(
                "return_worker", {"lease_id": lease.lease_id,
                                  "worker_exiting": lease.conn.closed})
        except Exception:
            pass

    async def _return_all_leases(self):
        """Hand every idle lease back to its raylet now (arm switches in
        the microbenchmark A/B, tests): a lease built by one arm must not
        leak into the other's window (legacy leases lack the direct task
        channel)."""
        for key, leases in list(self.leases.items()):
            for lease in list(leases):
                if lease.inflight > 0:
                    continue
                leases.remove(lease)
                try:
                    await lease.raylet_conn.call(
                        "return_worker",
                        {"lease_id": lease.lease_id,
                         "worker_exiting": lease.conn.closed})
                except Exception:
                    pass
            if not leases:
                self.leases.pop(key, None)

    def _ensure_lease_reaper(self):
        if self._lease_reaper_running or self._legacy or self._shutdown:
            return
        self._lease_reaper_running = True
        asyncio.ensure_future(self._lease_reaper())

    async def _lease_reaper(self):
        """ONE periodic sweep returns idle leases after a grace period —
        replacing the per-push asyncio.sleep(0.25) grace coroutine (at
        240 tasks/s that was ~60 live loop timers at any instant, each a
        wakeup). Also how pre-warmed leases that arrived after the queue
        drained get handed back, so prewarm can't strand workers. Exits
        when no leases remain; restarted on the next grant."""
        grace = self.config.lease_idle_grace_s
        try:
            while not self._shutdown:
                await asyncio.sleep(grace)
                now = time.monotonic()
                for key, leases in list(self.leases.items()):
                    busy = bool(self._pending_by_key.get(key))
                    for lease in list(leases):
                        if lease.inflight > 0 or busy:
                            continue
                        if (not lease.conn.closed
                                and now - lease.last_used < grace):
                            continue
                        if lease not in leases:
                            # removed by a concurrent push-failure
                            # handler while we awaited a return_worker
                            continue
                        leases.remove(lease)
                        try:
                            await lease.raylet_conn.call(
                                "return_worker",
                                {"lease_id": lease.lease_id,
                                 "worker_exiting": lease.conn.closed})
                        except Exception:
                            pass
                    if not leases:
                        self.leases.pop(key, None)
                if not self.leases:
                    return
        finally:
            self._lease_reaper_running = False

    async def _handle_push_failure(self, spec, key, lease, error):
        if lease in self.leases.get(key, []):
            self.leases[key].remove(lease)
            try:
                await lease.raylet_conn.call(
                    "return_worker", {"lease_id": lease.lease_id,
                                      "worker_exiting": True})
            except Exception:
                pass
        rec = self.submitted.get(spec["task_id"])
        if isinstance(error, rpc.RemoteError):
            # The worker raised outside user code (system error) — retry.
            pass
        if rec is not None and rec["retries"] > 0 and not rec["cancelled"]:
            rec["retries"] -= 1
            logger.info("retrying task %s (%d retries left)",
                        spec["name"], rec["retries"])
            await self._submit_async(spec)
        else:
            self._fail_task(spec, exc.WorkerCrashedError(
                f"task {spec['name']} failed: worker died ({error})"),
                release=True)

    def _handle_task_reply(self, spec, reply):
        task_id = spec["task_id"]
        rec = self.submitted.pop(task_id, None)
        M_TASKS_COMPLETED.inc()
        if rec is not None:
            now = time.time()
            t0 = rec.get("t0")
            exemplar = tracing.exemplar_of(rec.get("trace"))
            if t0 is not None:
                M_E2E_S.observe(now - t0, exemplar=exemplar)
            t_push = rec.get("t_push")
            held_s = (reply.get("held_s", reply.get("exec_s"))
                      if isinstance(reply, dict) else None)
            if t_push is not None and held_s is not None:
                # durations only — clock-skew-free wire+loop overhead.
                # held_s (not exec_s): worker-side queueing behind other
                # in-flight pushes must not read as reply overhead.
                M_REPLY_OVERHEAD_S.observe(max(0.0, now - t_push - held_s),
                                           exemplar=exemplar)
            ctx = rec.get("trace")
            if ctx is not None and t0 is not None:
                # the ROOT span of this task's tree (children: queue_wait,
                # lease_wait, raylet.lease, worker-side exec)
                tracing.record_span("task.e2e", t0, now, ctx,
                                    {"name": spec.get("name", "?")})
        if rec is not None and rec["pinned"]:
            self._release_pins(rec["pinned"])
        # Lineage shared by all plasma returns of this task: enough to
        # re-execute it if every copy is later lost (reference:
        # object_recovery_manager.h:87-103; lineage retained while the
        # refs live, task_manager.h lineage pinning). Built lazily: the
        # common all-inline reply never needs it.
        lineage = None
        inline_puts = []
        for i, ret in enumerate(reply["returns"]):
            return_id = ObjectID.for_return(TaskID(task_id), i)
            if ret["kind"] == "inline":
                inline_puts.append((return_id, ret["data"],
                                    ret.get("err", False)))
            else:  # plasma
                if lineage is None:
                    lineage = {"spec": spec,
                               "retries": rec["retries"] if rec else 0}
                with self._lock:
                    owned = self.owned.get(return_id)
                    if owned is not None:
                        owned.plasma = True
                        # A stray duplicate reply (rec already popped) must
                        # not clobber live lineage with retries=0.
                        if rec is not None or owned.lineage_task is None:
                            owned.lineage_task = lineage
                self.memstore.put(return_id, IN_PLASMA)
        if inline_puts:
            # one lock/notify for the whole return set (a serve batch is
            # num_returns inline values landing together)
            self.memstore.put_many(inline_puts)

    def _fail_task(self, spec, error: Exception, release=False):
        task_id = spec["task_id"]
        rec = self.submitted.pop(task_id, None)
        if rec is not None and release:
            self._release_pins(rec["pinned"])
        payload = serialization.dumps(error)
        for i in range(spec["num_returns"]):
            return_id = ObjectID.for_return(TaskID(task_id), i)
            self.memstore.put(return_id, payload, is_exception=True)

    def cancel_task(self, ref: ObjectRef, force=False, recursive=True):
        task_id = ref.task_id().binary()
        rec = self.submitted.get(task_id)
        if rec is None:
            return
        rec["cancelled"] = True
        lease = rec.get("lease")

        async def _do_cancel():
            if lease is not None and not lease.conn.closed:
                try:
                    await lease.conn.call("cancel_task", {
                        "task_id": task_id, "force": force})
                except Exception:
                    pass

        self._io.submit(_do_cancel())

    # ------------------------------------------------------------------
    # actors — owner side (reference: direct_actor_transport.h:62)
    # ------------------------------------------------------------------

    def create_actor(self, *, cls_id: bytes, name: str, args, kwargs,
                     num_returns=0, resources=None, max_restarts=0,
                     max_concurrency=1, actor_name="", namespace="",
                     lifetime="", placement_group=None, bundle_index=-1,
                     runtime_env=None) -> bytes:
        actor_id = ActorID.of(self.job_id)
        task_id = TaskID.for_task(self.job_id)
        descs, pinned = self._serialize_args(args, kwargs)
        spec = common.make_task_spec(
            task_id=task_id.binary(),
            job_id=self.job_id.binary(),
            name=name,
            fn_id=cls_id,
            task_type=common.ACTOR_CREATION_TASK,
            actor_id=actor_id.binary(),
            owner_addr=self.address,
            owner_worker_id=self.worker_id.binary(),
            args=descs,
            num_returns=0,
            resources=resources or {"CPU": 1},
            actor_creation={
                "max_restarts": max_restarts,
                "max_concurrency": max_concurrency,
                "name": actor_name,
                "namespace": namespace,
                "lifetime": lifetime,
            },
            placement_group_id=placement_group,
            bundle_index=bundle_index,
        )
        client = _ActorClient(actor_id.binary())
        self.actor_clients[actor_id.binary()] = client

        async def _register():
            try:
                info = await self.gcs.call("register_actor", {"spec": spec})
                await self._subscribe_actor(actor_id.binary())
                self._apply_actor_update(info)
                # flush calls queued while registration was in flight:
                # the ALIVE state just arrived via this REPLY — if the
                # pubsub publish was lost (GCS crash/drop between table
                # apply and publish), no push will ever flush them
                await self._flush_actor_queue(client)
            except Exception as e:
                client.state = "DEAD"
                client.death_cause = f"registration failed: {e}"
                await self._flush_actor_queue(client)
            finally:
                self._release_pins(pinned)

        self._io.submit(_register())
        return actor_id.binary()

    async def _subscribe_actor(self, actor_id: bytes):
        client = self.actor_clients.get(actor_id)
        if client is None or client.subscribed:
            return
        client.subscribed = True
        await self.gcs.call("subscribe", {"channel": f"actor:{actor_id.hex()}"})

    async def _flush_profile_now(self, force: bool = False):
        # Rate-limited: thousands of tiny tasks/s must not turn into
        # thousands of GCS notifies/s (the 2s loop catches the rest).
        now = time.monotonic()
        if not force and now - self._last_profile_flush < 0.25:
            return
        self._last_profile_flush = now
        events = self._profile.drain()
        if not events or self.gcs is None:
            return
        try:
            if _fp.ARMED:
                # flush seam: `raise` models an unreachable GCS — the
                # drained batch must requeue (bounded), never vanish
                _fp.fire_strict("trace.flush")
            await self.gcs.notify("add_profile_events", {
                "component_type": self._profile.component_type,
                "component_id": self._profile.component_id,
                "node_id": (self.node_id.binary()
                            if self.node_id else None),
                "events": events,
            })
        except Exception:
            # GCS unreachable: keep the batch for the next flush cycle.
            # The deque bound caps memory; overflow is counted in
            # profiling.events_dropped_total instead of lost silently.
            self._profile.requeue(events)

    async def _flush_profile_samples(self):
        """Flush the continuous-profiler window into the GCS profile
        ring on the 2s cadence (sampling_profiler.flush_to: the shared
        drain + `profile.flush` seam + bounded merge-back contract)."""
        if self._shutdown:
            return
        await _sprof.flush_to(
            self.gcs, self._profile.component_type,
            node_id=self.node_id.binary() if self.node_id else None)

    async def _push_metrics_now(self):
        """Push this process's metric snapshot to the GCS time-series
        ring (heartbeat-piggyback analog for workers/drivers, which
        don't heartbeat — they ride the profile flush cadence)."""
        if self.gcs is None or self.node_id is None or self._shutdown:
            return
        try:
            if _fp.ARMED:
                _fp.fire_strict("metrics.push")
            from ray_tpu._private import stats

            await self.gcs.notify("push_metrics", {
                "source": (f"{self.node_id.hex()[:8]}/"
                           f"{self.mode}-{os.getpid()}"),
                "metrics": stats.snapshot(),
            })
        except Exception:
            pass  # history just misses a sample; next tick retries

    async def _profile_flush_loop(self):
        """Batch-push recorded spans to the GCS profile table (reference:
        profiling.h Profiler flush thread). The periodic tick is the
        fallback; task completion schedules an immediate flush so
        timeline() right after a run sees the tail. Also the metrics-
        history push cadence for this process."""
        while not self._shutdown:
            await asyncio.sleep(2.0)
            await self._flush_profile_now(force=True)
            await self._flush_profile_samples()
            await self._push_metrics_now()

    def get_cluster_events(self, severity: str | None = None) -> list[dict]:
        """Structured events ring from the GCS (RAY_EVENT analog)."""
        return self._io.run(self.gcs.call(
            "get_events", {"severity": severity}))

    def get_profile_events(self) -> list[dict]:
        """All profile batches recorded cluster-wide (driver surface)."""
        return self._io.run(self.gcs.call("get_profile_events", {}))

    def get_trace_spans(self, trace_id: str | None = None) -> list[dict]:
        """Span batches from the GCS trace table, optionally filtered to
        one trace (hex trace id)."""
        return self._io.run(self.gcs.call(
            "get_trace_spans", {"trace_id": trace_id}))

    def get_profile_samples(self, since: float | None = None,
                            component: str | None = None) -> list[dict]:
        """Collapsed-stack sample batches from the GCS profile ring
        (sampling_profiler.py), optionally filtered to one component
        class and/or to windows ending at/after `since`."""
        return self._io.run(self.gcs.call(
            "get_profile_samples",
            {"since": since, "component": component}))

    def get_metrics_history(self, samples: int = 0) -> dict:
        """Per-source metric time series from the GCS ring buffers:
        {source: {metric: [[ts, value], ...]}}."""
        return self._io.run(self.gcs.call(
            "get_metrics_history", {"samples": samples}))

    def set_resource(self, resource_name: str, capacity: float,
                     node_id: bytes | None = None):
        """Dynamic resource resize, routed through the GCS to the target
        raylet (reference: experimental/dynamic_resources.py)."""
        return self._io.run(self.gcs.call("set_resource", {
            "resource_name": resource_name,
            "capacity": capacity,
            "node_id": node_id,
        }))

    def get_cluster_metrics(self) -> dict:
        """GCS (+ store shards) + per-raylet metric snapshots, merged."""
        async def _gcs_and_shards():
            return await asyncio.gather(self.gcs.call("get_metrics", {}),
                                        self.gcs.shard_metrics())

        gcs_snap, shards = self._io.run(_gcs_and_shards())
        out = {"gcs": gcs_snap}
        if shards:
            out["gcs_shards"] = shards

        async def _node_metrics():
            nodes = await self.gcs.call("get_all_nodes", {})

            async def one(n):
                try:
                    conn = await self._peer(n["address"])
                    return n["node_id"].hex()[:8], await conn.call(
                        "get_metrics", {})
                except Exception:
                    return None

            got = await asyncio.gather(*(one(n) for n in nodes))
            return dict(p for p in got if p is not None)

        out["raylets"] = self._io.run(_node_metrics())
        return out

    # ------------------------------------------------------------------
    # live state introspection (debug_state.py; the flight recorder)
    # ------------------------------------------------------------------

    def debug_state(self) -> dict:
        """Cheap snapshot of every in-flight thing this process owns or
        executes: task stages with age, lease tables, actor clients,
        live executions, ref counts, rpc conn depth, collective groups.
        Lock discipline: GIL-atomic dict copies plus one short _lock hop
        for the ref counters — safe to serve inline on the read loop
        even while the dispatcher is wedged."""
        t_start = time.monotonic()
        now = time.time()
        pending_ids = set()
        for specs in list(self._pending_by_key.values()):
            for s in list(specs):
                pending_ids.add(s.get("task_id"))
        tasks = []
        for tid, rec in list(self.submitted.items()):
            spec = rec.get("spec") or {}
            t0 = rec.get("t0")
            t_push = rec.get("t_push")
            if t_push is not None:
                stage, since = "executing", t_push
            elif tid in pending_ids:
                stage, since = "lease_wait", t0
            elif rec.get("lease") is not None:
                stage, since = "queued", t0
            else:
                stage, since = "submit", t0
            ctx = rec.get("trace")
            lease = rec.get("lease")
            tasks.append({
                "task_id": tid.hex()[:16],
                "name": spec.get("name", "?"),
                "stage": stage,
                "age_s": (round(now - since, 3)
                          if since is not None else None),
                "total_age_s": (round(now - t0, 3)
                                if t0 is not None else None),
                "trace_id": ctx.trace_id.hex() if ctx is not None else "",
                "lease_worker": lease.address if lease is not None else "",
                "retries_left": rec.get("retries", 0),
            })
        executing = []
        for info in list(self._executing.values()):
            executing.append({
                "task_id": info["task_id"], "name": info["name"],
                "age_s": round(now - info["t0"], 3),
                "thread": info["thread"], "trace_id": info["trace_id"],
            })
        leases = []
        mono = time.monotonic()
        for key, ls in list(self.leases.items()):
            for lease in list(ls):
                leases.append({
                    "lease_id": lease.lease_id.hex(),
                    "worker": lease.address,
                    "inflight": lease.inflight,
                    "idle_s": round(mono - lease.last_used, 3),
                    "conn_closed": lease.conn.closed,
                })
        actors = []
        for aid, client in list(self.actor_clients.items()):
            actors.append({
                "actor_id": aid.hex()[:16],
                "state": client.state,
                "address": client.address,
                "queued": len(client.queued),
                "inflight": client.inflight,
                "epoch": client.epoch,
            })
        with self._lock:
            owned, borrowed = len(self.owned), len(self.borrowed)
        conns = {}
        for addr, conn in list(self._peer_conns.items()):
            depth = _debug.conn_depth(conn)
            if depth:
                conns[addr] = depth
        snap = {
            "role": self.mode,
            "worker_id": self.worker_id.hex()[:16],
            "node_id": self.node_id.hex()[:8] if self.node_id else "",
            "address": self.address,
            "tasks": tasks,
            "executing": executing,
            "exec_queue_depth": self._exec_queue.qsize(),
            "leases": leases,
            "actors": actors,
            "objects": {"memstore_entries": self.memstore.size(),
                        "owned_refs": owned, "borrowed_refs": borrowed},
            "rpc": {"peer_conn_depth": conns,
                    "raylet_depth": (_debug.conn_depth(self.raylet)
                                     if self.raylet is not None else 0),
                    "server_conns": len(self.server.connections)},
            "collectives": _collective_debug(),
        }
        from ray_tpu._private import profiling as _profiling

        compiles = _profiling.compile_state()
        if compiles["total"]:
            # jit-compile activity (profiling.record_compile seams): the
            # stall doctor's compile-storm signal rides this snapshot
            snap["jax_compiles"] = compiles
        from ray_tpu._private import compile_cache as _cc

        cache = _cc.state()
        if cache["hits"] or cache["misses"] or cache["errors"]:
            # persistent AOT compile-cache activity: the doctor's
            # compile_cache_cold finding (restart re-traced despite a
            # warm cache) reads this
            snap["compile_cache"] = cache
        routers = _serve_router_debug()
        if routers:
            snap["routers"] = routers
            snap["router_queues"] = [q for r in routers
                                     for q in r.get("queries", [])]
        inst = self._actor_instance
        if inst is not None:
            # hosted-actor component hook: serve controller/proxy/replica
            # expose their own state through the __ray_debug_state__
            # protocol (cheap, read-only — plain dict reads under GIL)
            snap["actor_class"] = type(inst).__name__
            hook = getattr(inst, "__ray_debug_state__", None)
            if callable(hook):
                try:
                    snap["component"] = hook()
                except Exception as e:
                    snap["component"] = {"error": repr(e)}
                comp = snap.get("component")
                if isinstance(comp, dict) and "router_queues" in comp:
                    # surfaced top-level so the doctor sees serve queue
                    # waiters without knowing the component layout
                    snap["router_queues"] = comp["router_queues"]
        return _debug.finish_snapshot(snap, t_start)

    def get_cluster_state(self, include_workers: bool = True,
                          timeout: float = 5.0) -> dict:
        """Aggregate debug_state across the whole cluster (GCS director
        + shards, every raylet and its workers, this driver)."""
        async def _collect():
            async def gcs_call(method, data):
                return await self.gcs.call(method, data)

            out = await _debug.collect_cluster_state_async(
                gcs_call, self._peer, include_workers=include_workers,
                timeout=timeout)
            out["driver"] = self.debug_state()
            # the raylet fan-out also reaches connected drivers — drop
            # THIS process from its node's list so flatten()/doctor
            # don't see our tasks twice
            me = str(os.getpid())
            for node in out.get("nodes", {}).values():
                if isinstance(node, dict):
                    (node.get("drivers") or {}).pop(me, None)
            return out

        return self._io.run(_collect(), timeout=timeout * 4)

    def get_debug_stacks(self, address: str | None = None,
                         timeout: float = 5.0) -> dict:
        """All-thread stacks of this process, or of the process serving
        rpc at `address` (worker/raylet/gcs — they all carry the
        debug_stacks handler)."""
        if address is None:
            return _debug.collect_stacks()

        async def _fetch():
            conn = await self._peer(address)
            return await conn.call("debug_stacks", {}, timeout=timeout)

        return self._io.run(_fetch(), timeout=timeout * 2)

    def publish_log(self, line: str, stream: str):
        """Worker-side: forward one output line to subscribed drivers
        (reference: log_monitor.py:48 republishing, worker stdout/stderr
        streaming to the driver console). Tagged with the job that ran the
        producing task so each driver prints only its own workers."""
        if self.gcs is None or self._shutdown:
            return
        self._io.submit(self.gcs.notify("publish", {
            "channel": "worker_logs",
            "data": {"pid": os.getpid(),
                     "worker_id": self.worker_id.binary(),
                     "job_id": getattr(self, "_exec_job_id", None),
                     "stream": stream, "line": line},
        }))

    async def _on_gcs_push(self, channel: str, data):
        if channel == _fp.CHANNEL:
            _fp.apply_kv_value(data)
            return
        if channel == tracing.CHANNEL:
            tracing.apply_kv_value(data)
            return
        if channel == _sprof.CHANNEL:
            _sprof.apply_kv_value(data)
            return
        if channel.startswith("pg:"):
            # placement-group transition (CREATED / REMOVED): wake every
            # parked wait_placement_group with the published record
            pg_id = data.get("pg_id")
            for fut in self._pg_waiters.get(pg_id, []):
                if not fut.done():
                    fut.set_result(data)
            return
        if channel.startswith("actor:"):
            self._apply_actor_update(data)
            client = self.actor_clients.get(data["actor_id"])
            if client is not None:
                await self._flush_actor_queue(client)
        elif channel == "worker_logs" and self.mode == DRIVER:
            # Print worker output on the driver console (stderr: driver
            # stdout often carries machine-readable output). Lines from
            # other drivers' jobs are dropped.
            job = data.get("job_id")
            if job is not None and job != self.job_id.binary():
                return
            print(f"(pid={data['pid']}, {data['stream']}) {data['line']}",
                  file=sys.__stderr__)

    def _apply_actor_update(self, info):
        client = self.actor_clients.get(info["actor_id"])
        if client is None:
            client = _ActorClient(info["actor_id"])
            self.actor_clients[info["actor_id"]] = client
        client.state = info["state"]
        client.death_cause = info.get("death_cause", "")
        if info["state"] == "ALIVE":
            client.task_channel = info.get("task_channel", "") or ""
            if client.address != info["address"]:
                client.address = info["address"]
                client.conn = None
                client.task_conn = None
                client.seq = 0  # fresh incarnation expects seq 0
        else:
            client.address = info.get("address", "") or ""
            client.conn = None
            client.task_conn = None

    def make_actor_task_template(self, actor_id: bytes, *, fn_id: bytes,
                                 name: str, method_name: str,
                                 num_returns=1) -> dict:
        """Static spec prefix for one actor method — cached per
        (handle, method) so each call pays a dict copy, not a full spec
        assembly (same trick as make_task_template)."""
        return common.make_task_spec(
            task_id=b"",
            job_id=self.job_id.binary(),
            name=name,
            fn_id=fn_id,
            task_type=common.ACTOR_TASK,
            actor_id=actor_id,
            method_name=method_name,
            owner_addr=self.address,
            owner_worker_id=self.worker_id.binary(),
            args=None,
            num_returns=num_returns,
        )

    def submit_actor_task(self, actor_id: bytes, *, fn_id: bytes = b"",
                          name: str = "", method_name: str = "",
                          args=(), kwargs=None, num_returns=1,
                          template: dict | None = None) -> list[ObjectRef]:
        task_id = TaskID.for_task(self.job_id)
        descs, pinned = self._serialize_args(args, kwargs)
        client = self.actor_clients.get(actor_id)
        if client is None:
            client = _ActorClient(actor_id)
            self.actor_clients[actor_id] = client
        if template is not None:
            spec = dict(template)
            spec["task_id"] = task_id.binary()
            spec["args"] = descs
            num_returns = spec["num_returns"]
        else:
            spec = common.make_task_spec(
                task_id=task_id.binary(),
                job_id=self.job_id.binary(),
                name=name,
                fn_id=fn_id,
                task_type=common.ACTOR_TASK,
                actor_id=actor_id,
                method_name=method_name,
                owner_addr=self.address,
                owner_worker_id=self.worker_id.binary(),
                args=descs,
                num_returns=num_returns,
            )
        ctx = tracing.maybe_trace()
        if ctx is not None:
            spec["trace"] = tracing.to_wire(ctx)
        refs = self._make_return_refs(task_id, num_returns)
        self.submitted[task_id.binary()] = {
            "spec": spec, "pinned": pinned, "retries": 0,
            "cancelled": False, "t0": time.time(), "trace": ctx}

        # seq_no is assigned at push time (not here) so a restarted actor —
        # whose reorder buffer starts from 0 again — sees a contiguous
        # sequence (reference: direct_actor_transport resend/reset
        # semantics). The append happens on the CALLER thread (GIL-atomic)
        # and a single flush coroutine is scheduled per burst: N rapid
        # submits cost one io-loop wakeup, not N (the wakeup write was the
        # top cost in the actor-call microbenchmark).
        client.queued.append((spec, pinned))
        if not client.flush_scheduled:
            client.flush_scheduled = True
            self._io.submit_nowait(self._submit_flush(client))
        return refs

    async def _submit_flush(self, client: _ActorClient):
        client.flush_scheduled = False  # appends after this get this flush
        await self._ensure_actor_ready(client)
        await self._flush_actor_queue(client)

    async def _ensure_actor_ready(self, client: _ActorClient):
        if client.state == "ALIVE" and client.address:
            return
        if not client.subscribed:
            await self._subscribe_actor(client.actor_id)
            info = await self.gcs.call("get_actor",
                                       {"actor_id": client.actor_id})
            if info is not None:
                self._apply_actor_update(info)

    async def _flush_actor_queue(self, client: _ActorClient):
        if client.state == "DEAD":
            for spec, pinned in client.queued:
                self._fail_task(spec, exc.ActorDiedError(
                    client.actor_id.hex(), client.death_cause), release=True)
            client.queued.clear()
            return
        if client.state != "ALIVE" or not client.address:
            # Pubsub is the fast path, but a LOST publish (GCS dying
            # between table apply and publish, a dropped subscriber conn)
            # must not wedge the queued calls forever — poll as backstop.
            self._schedule_actor_poll(client)
            return  # wait for pubsub update (or the poll)
        if client.conn is None or client.conn.closed:
            try:
                # NOT fresh: a live cached peer conn is shareable (actor
                # ordering comes from the seq/epoch reorder lanes, not
                # the conn), and a fresh dial would close() the cached
                # conn under whoever else is using it (_cache_peer)
                client.conn = await self._peer(client.address)
            except Exception:
                # undialable while believed-ALIVE (worker died, DEAD
                # publish possibly lost): the poll re-queries the GCS
                # and re-drives this flush — without it nothing would
                self._schedule_actor_poll(client)
                return
            client.task_conn = None
        if (client.task_conn is None and client.task_channel
                and not self._legacy):
            client.task_conn = await self._task_channel_conn(
                client.task_channel)
        # swap-drain: pop(0) per task is O(n²) on a deep queue, and the
        # queue can only grow behind this loop from the caller thread
        # (GIL-atomic append) — those appends get the next flush
        queued, client.queued = client.queued, []
        if queued and client.inflight == 0:
            # burst boundary (same rule as _Lease.push_conn): pick ONE
            # conn for the whole burst — actor calls are seq-ordered by
            # the reorder buffer either way, but a single FIFO conn keeps
            # arrival order matching seq order (no buffer stalls)
            client.burst_channel = len(queued) < 2
        for spec, pinned in queued:
            spec["seq_no"] = client.seq
            spec["caller_epoch"] = client.epoch
            client.seq += 1
            asyncio.ensure_future(self._push_actor_task(client, spec))

    def _schedule_actor_poll(self, client: _ActorClient):
        """Bounded (1/s, one in flight per actor) get_actor poll while
        calls are queued on an unresolved actor state: recovers from a
        lost ALIVE/DEAD publish instead of hanging the callers. Re-armed
        by _flush_actor_queue until the state resolves or the queue
        drains."""
        if client.poll_scheduled or not client.queued or self._shutdown:
            return
        client.poll_scheduled = True

        async def _poll():
            await asyncio.sleep(1.0)
            client.poll_scheduled = False
            if self._shutdown or not client.queued:
                return
            # ALWAYS re-query: a believed-ALIVE state can be stale (the
            # worker died and the DEAD publish was lost) — re-flushing
            # against a stale address alone would dial-fail forever
            try:
                info = await self.gcs.call("get_actor",
                                           {"actor_id": client.actor_id})
            except rpc.ConnectionGaveUp as e:
                # the control plane is PERMANENTLY gone: a 1/s poll
                # forever would hang the queued calls — fail them typed
                for spec, _pinned in client.queued:
                    self._fail_task(spec, exc.ActorDiedError(
                        ActorID(client.actor_id).hex(),
                        f"control plane unreachable: {e}"), release=True)
                client.queued.clear()
                return
            except Exception:
                info = None
            if info is not None:
                self._apply_actor_update(info)
            await self._flush_actor_queue(client)

        asyncio.ensure_future(_poll())

    async def _push_actor_task(self, client: _ActorClient, spec):
        # same hybrid as _Lease.push_conn: channel for shallow bursts,
        # rpc conn for deep ones (reply IO overlaps execution there);
        # sticky per burst so arrival order matches seq order
        conn = client.task_conn
        client.inflight += 1
        if conn is None or conn.closed or not client.burst_channel:
            conn = client.conn
        rec = self.submitted.get(spec["task_id"])
        if rec is not None:
            self._note_pushed(rec, spec)
        try:
            if conn is None or conn.closed:
                # a sibling push's failure handler nulled the conns (the
                # epoch bump) before this scheduled push first ran: take
                # the same typed failure path, never an AttributeError
                # that would leak the inflight slot and hang the caller
                raise rpc.ConnectionLost(
                    "actor connection lost before push")
            reply = await conn.call("push_actor_task", {"spec": spec})
            client.inflight -= 1
            self._handle_task_reply(spec, reply)
        except (rpc.ConnectionLost, rpc.RemoteError,
                _fp.FailpointError) as e:
            client.inflight -= 1
            if isinstance(e, rpc.RemoteError) and isinstance(
                    e.exc, exc.TaskCancelledError):
                self._fail_task(spec, e.exc, release=True)
                return
            if (isinstance(e, (rpc.ConnectionLost, _fp.FailpointError))
                    and spec.get("caller_epoch", 0) == client.epoch):
                # FailpointError (injected rpc.send=raise) also means the
                # seq was never delivered — the lane has a hole either way
                # First failure of this epoch: the connection died with
                # seq numbers possibly undelivered, so the worker's
                # reorder lane may hold a hole forever. Open a fresh
                # (epoch, seq=0) lane — one bump per loss event (sibling
                # in-flight failures carry the old epoch and skip this)
                # — and drop the conns so the flush redials.
                client.epoch += 1
                client.seq = 0
                client.conn = None
                client.task_conn = None
            # Connection lost mid-flight: the task may or may not have run —
            # fail it (reference default: max_task_retries=0; in-flight
            # tasks get RayActorError on actor death). Tasks still queued
            # owner-side are preserved for the next incarnation.
            try:
                info = await self.gcs.call("get_actor",
                                           {"actor_id": client.actor_id})
                if info is not None:
                    self._apply_actor_update(info)
            except Exception:
                # GCS itself unreachable (shutdown teardown) — nothing to
                # learn; fall through and fail the task locally.
                pass
            self._fail_task(spec, exc.ActorDiedError(
                client.actor_id.hex(),
                client.death_cause or f"task in flight when actor died ({e})"),
                release=True)
            await self._flush_actor_queue(client)

    def kill_actor(self, actor_id: bytes, no_restart=True):
        self._io.run(self.gcs.call("kill_actor", {
            "actor_id": actor_id, "no_restart": no_restart}))

    def get_actor_info(self, actor_id: bytes):
        return self._io.run(self.gcs.call("get_actor", {"actor_id": actor_id}))

    def get_named_actor(self, name: str, namespace: str = ""):
        return self._io.run(self.gcs.call("get_named_actor", {
            "name": name, "namespace": namespace or "default"}))

    # ------------------------------------------------------------------
    # placement groups (reference: core_worker.cc:1524 CreatePlacementGroup)
    # ------------------------------------------------------------------

    def create_placement_group(self, pg_id: bytes, bundles, strategy,
                               name="", cost_model=""):
        # Quantize at the boundary: everything on the wire is FixedPoint
        # ints, same as task-spec resources (reference: fixed_point.h).
        return self._io.run(self.gcs.call("create_placement_group", {
            "pg_id": pg_id,
            "bundles": [{"resources": common.ResourceSet(dict(b)).raw()}
                        for b in bundles],
            "strategy": strategy,
            "name": name,
            "cost_model": cost_model or "",
        }))

    def remove_placement_group(self, pg_id: bytes):
        return self._io.run(self.gcs.call("remove_placement_group",
                                          {"pg_id": pg_id}))

    def get_placement_group(self, pg_id: bytes):
        return self._io.run(self.gcs.call("get_placement_group",
                                          {"pg_id": pg_id}))

    def wait_placement_group(self, pg_id: bytes,
                             timeout: float | None = None):
        """Park until the placement group reaches a terminal-ish state
        (CREATED, or removal) — event-driven on the GCS `pg:<hex>`
        pubsub channel instead of the old 20ms client busy-poll. The
        publish payload carries the full public record (mirror-then-
        publish ordering, gcs/server.py), so the common path never even
        reads back. A slow exponential re-poll (0.1s -> 1s) backstops a
        publish lost to a GCS restart. Returns the record, None if
        `timeout` elapsed first, or raises ValueError if removed."""
        async def _wait():
            channel = f"pg:{pg_id.hex()}"
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            self._pg_waiters.setdefault(pg_id, []).append(fut)
            await self.gcs.call("subscribe", {"channel": channel})
            deadline = (None if timeout is None
                        else time.monotonic() + timeout)
            poll = 0.1
            try:
                # subscribe raced the transition: read once up front
                # (shard-routed; mirrors are pushed before the publish)
                info = await self.gcs.call("get_placement_group",
                                           {"pg_id": pg_id})
                while True:
                    if info is None or info.get("state") == "REMOVED":
                        raise ValueError(
                            f"placement group {pg_id.hex()} was removed")
                    if info.get("state") in ("CREATED", "INFEASIBLE"):
                        # INFEASIBLE is terminal-for-now: the caller
                        # (PlacementGroup.ready) raises it typed rather
                        # than parking until the fleet grows
                        return info
                    remaining = poll
                    if deadline is not None:
                        remaining = min(remaining,
                                        deadline - time.monotonic())
                        if remaining <= 0:
                            return None
                    try:
                        info = await asyncio.wait_for(
                            asyncio.shield(fut), remaining)
                    except asyncio.TimeoutError:
                        # backstop re-poll for a lost publish
                        poll = min(poll * 2, 1.0)
                        info = await self.gcs.call("get_placement_group",
                                                   {"pg_id": pg_id})
                        continue
                    if fut.done():
                        # drop the consumed future BEFORE re-arming, or
                        # every event-driven wakeup would leak it in the
                        # waiter list (and the finally below would never
                        # see the list empty -> never unsubscribe)
                        stale = self._pg_waiters.get(pg_id, [])
                        if fut in stale:
                            stale.remove(fut)
                        fut = asyncio.get_running_loop().create_future()
                        self._pg_waiters.setdefault(pg_id, []).append(fut)
            finally:
                waiters = self._pg_waiters.get(pg_id)
                if waiters is not None:
                    if fut in waiters:
                        waiters.remove(fut)
                    if not waiters:
                        self._pg_waiters.pop(pg_id, None)
                        try:
                            await self.gcs.call("unsubscribe",
                                                {"channel": channel})
                        except Exception:
                            pass

        return self._io.run(_wait())

    def get_named_placement_group(self, name: str):
        return self._io.run(self.gcs.call("get_named_placement_group",
                                          {"name": name}))

    def list_placement_groups(self):
        return self._io.run(self.gcs.call("list_placement_groups", {}))

    # ------------------------------------------------------------------
    # execution side (worker mode; reference: core_worker.cc ExecuteTask +
    # _raylet.pyx:347 execute_task)
    # ------------------------------------------------------------------

    def h_push_task(self, conn, d, msgid):
        """Deferred-reply push: no asyncio future/task per pushed task —
        the dispatcher thread completes the RPC straight through the
        connection loop's coalesced call queue (rpc.deferred)."""
        self._dispatch_exec(
            d["spec"],
            lambda reply: conn.reply_deferred(msgid, "push_task", reply))

    h_push_task._rpc_deferred = True

    async def h_push_task_legacy(self, conn, d):
        # Round-7 control arm (RAY_TPU_TASK_LEGACY in the worker's env):
        # future + task + coroutine resume per pushed task.
        return await self._enqueue_exec(d["spec"])

    async def h_create_actor(self, conn, d):
        return await self._enqueue_exec(d["spec"])

    def _actor_push_common(self, spec, complete):
        """Per-caller seq reorder, then hand to the single execution lane
        (the dispatcher queue — actor tasks must serialize regardless of
        which connection delivered them). Safe from the io loop AND from
        a task-channel thread: reorder state is per-caller and each
        caller pushes over exactly one path."""
        spec.setdefault("_arrived", time.time())
        caller = spec["owner_worker_id"]
        epoch = spec.get("caller_epoch", 0)
        state = self._actor_reorder.get(caller)
        if state is None or state.get("epoch", 0) < epoch:
            # new caller, or the caller reopened its lane after a
            # connection loss (its old seq numbers may have died with
            # the conn — waiting for them would wedge the lane forever)
            if state is not None:
                # entries buffered behind the lost seq still owe their
                # (possibly live rpc-conn) callers a reply — error them
                # rather than dropping the completions on the floor
                for old_spec, old_complete in state["buffer"].values():
                    try:
                        old_complete(self._pack_error(
                            old_spec, exc.ActorUnavailableError(
                                "superseded by a newer connection epoch")))
                    except Exception:
                        pass
            state = self._actor_reorder[caller] = {
                "next": 0, "buffer": {}, "epoch": epoch}
        elif state.get("epoch", 0) > epoch:
            # straggler from a pre-loss epoch (the owner already failed
            # it as ActorDied): don't poison the fresh lane with it
            complete(self._pack_error(spec, exc.ActorUnavailableError(
                "stale actor push from a superseded connection epoch")))
            return
        state["buffer"][spec["seq_no"]] = (spec, complete)
        while state["next"] in state["buffer"]:
            next_spec, next_complete = state["buffer"].pop(state["next"])
            state["next"] += 1
            self._dispatch_exec(next_spec, next_complete)

    def h_push_actor_task(self, conn, d, msgid):
        self._actor_push_common(
            d["spec"],
            lambda reply, m=msgid, c=conn: c.reply_deferred(
                m, "push_actor_task", reply))

    h_push_actor_task._rpc_deferred = True

    async def h_push_actor_task_legacy(self, conn, d):
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._actor_push_common(d["spec"], self._fut_completer(fut, loop))
        return await fut

    async def _enqueue_exec(self, spec):
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._dispatch_exec(spec, self._fut_completer(fut, loop))
        return await fut

    def _fut_completer(self, fut, loop):
        def complete(reply):
            self._deliver_reply(reply, fut, loop)

        return complete

    def _dispatch_exec(self, spec, complete):
        # Worker-side arrival stamp (_exec_scope pops it): held_s in the
        # reply spans arrival -> reply built, so the owner's reply-
        # overhead histogram excludes dispatcher/arg-wait queueing even
        # with many pushes in flight on one lease.
        spec.setdefault("_arrived", time.time())
        if spec["type"] == common.NORMAL_TASK:
            # Resolve ref args BEFORE entering the execution lane
            # (reference: dependencies are made local before dispatch).
            # Blocking the single dispatcher inside _resolve_args used to
            # rely on producers always arriving before consumers — true
            # on one FIFO connection, NOT true now that pushes ride two
            # conns (rpc + direct channel): a consumer that started first
            # would deadlock against its producer queued behind it.
            self._dispatch_when_args_ready(spec, complete)
            return
        # actor tasks keep strict seq order even when args are pending
        M_EXEC_HOPS.inc()
        self._exec_queue.put((spec, complete))

    def _dispatch_when_args_ready(self, spec, complete):
        waiting = []
        for desc in spec["args"]:
            if desc.get("kind") != "ref":
                continue
            object_id = ObjectID(desc["id"])
            found, _, _ = self.memstore.get_if_ready(object_id)
            if not found:
                waiting.append((object_id, desc))
        if not waiting:
            M_EXEC_HOPS.inc()
            self._exec_queue.put((spec, complete))
            return
        state = {"remaining": len(waiting)}
        state_lock = threading.Lock()
        # deserialize_ref registers the borrow and _ensure_fetch starts
        # the owner fetch; the refs are kept alive by the callback
        # closures until every arg is ready (release then rides GC —
        # _resolve_args re-registers its own refs during execution)
        refs = [self.deserialize_ref(desc) for _, desc in waiting]

        def on_ready(refs=refs):
            with state_lock:
                state["remaining"] -= 1
                if state["remaining"]:
                    return
            M_EXEC_HOPS.inc()
            self._exec_queue.put((spec, complete))

        for (object_id, _desc), ref in zip(waiting, refs):
            self._ensure_fetch(ref)
            self.memstore.add_ready_callback(object_id, on_ready)

    # ---- direct task channel (same-node fast path) -------------------

    def _start_task_channel(self):
        """Blocking UDS endpoint for plain-task pushes where the serving
        thread IS the executor. The worker-side round trip becomes
        kernel-wake → execute → sendall: zero asyncio machinery, zero
        thread handoffs (the rpc-loop path pays a dispatcher futex hop
        plus a coalesced loop wakeup per reply). Speaks the normal frame
        protocol, so the owner dials it with a stock rpc.Connection; it
        carries ONLY push_task/ping — actor tasks (reorder + concurrency
        routing) and every control message stay on the rpc connection.
        Remote (cross-node) owners can't reach the socket file and fall
        back to the rpc path automatically."""
        import socket as socket_mod

        uds_dir = self._uds_dir()
        os.makedirs(uds_dir, exist_ok=True)
        path = os.path.join(uds_dir, f"task-{self.worker_id.hex()[:16]}.sock")
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass
        listener = socket_mod.socket(socket_mod.AF_UNIX,
                                     socket_mod.SOCK_STREAM)
        try:
            listener.bind(path)
        except OSError as e:
            logger.warning("task channel disabled (%s)", e)
            return
        listener.listen(8)
        self.task_channel_address = "unix:" + path
        threading.Thread(target=self._task_channel_accept, args=(listener,),
                         name="task-channel", daemon=True).start()

    def _task_channel_accept(self, listener):
        while not self._shutdown:
            try:
                sock, _ = listener.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_task_channel, args=(sock,),
                             name="task-channel-serve", daemon=True).start()

    def _serve_task_channel(self, sock):
        import pickle
        import struct as struct_mod

        import msgpack

        from ray_tpu._private import rpc as rpc_mod

        send_lock = threading.Lock()

        def recv_exact(n):
            buf = bytearray()
            while len(buf) < n:
                chunk = sock.recv(n - len(buf))
                if not chunk:
                    raise ConnectionError("task channel closed")
                buf.extend(chunk)
            return bytes(buf)

        def send_msg(msg):
            data = rpc_mod._pack(msg)
            try:
                if _fp.ARMED:
                    # channel reply-writer seam: raise/drop_conn model
                    # the completing thread dying mid-reply
                    try:
                        if _fp.fire("channel.reply") == "drop_conn":
                            raise ConnectionError("channel.reply failpoint")
                    except _fp.FailpointError as e:
                        raise ConnectionError(str(e)) from e
                with send_lock:
                    sock.sendall(data)
            except OSError:
                # A reply that cannot be delivered must not strand the
                # owner on a half-dead channel: shutdown() THEN close —
                # plain close() with the serve thread concurrently
                # blocked in recv() on the same fd defers the real close
                # (no FIN reaches the owner, observed on gVisor), which
                # left in-flight pushes hanging on a reply that will
                # never come. shutdown() sends the FIN immediately, so
                # the owner gets ConnectionLost and fails over.
                import socket as socket_mod

                try:
                    sock.shutdown(socket_mod.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    sock.close()
                except OSError:
                    pass
                raise

        try:
            while not self._shutdown:
                (length,) = struct_mod.unpack(">I", recv_exact(4))
                msg = msgpack.unpackb(recv_exact(length), raw=False)
                if _fp.ARMED:
                    # channel reader seam: drop_conn/raise kill this
                    # serve thread (socket closes; owner fails over to
                    # the rpc conn), exit kills the whole worker
                    if _fp.fire("channel.read") == "drop_conn":
                        raise ConnectionError("channel.read failpoint")
                _msgtype, msgid, method, data = msg
                if method == "ping":
                    send_msg([rpc_mod.REPLY_OK, msgid, method, "pong"])
                    continue
                if method == "push_actor_task":
                    # actor tasks reorder, then ride the single execution
                    # lane; only the reply skips the asyncio machinery
                    def complete(reply, m=msgid):
                        try:
                            send_msg([rpc_mod.REPLY_OK, m,
                                      "push_actor_task", reply])
                        except OSError:
                            pass

                    self._actor_push_common(data["spec"], complete)
                    continue
                if method != "push_task":
                    err = rpc_mod.RpcError(
                        f"task channel carries push_task/push_actor_task "
                        f"only, not {method!r}")
                    send_msg([rpc_mod.REPLY_ERR, msgid, method,
                              [pickle.dumps(err), ""]])
                    continue
                spec = data["spec"]
                if spec["task_id"] in self._cancelled_tasks:
                    self._cancelled_tasks.discard(spec["task_id"])
                    reply = self._pack_error(spec, exc.TaskCancelledError(
                        spec["task_id"].hex()))
                    if msgid is not None:
                        send_msg([rpc_mod.REPLY_OK, msgid, "push_task",
                                  reply])
                    continue

                # Hand to the dispatcher queue rather than executing on
                # this thread: pushed-but-not-started tasks stay visible
                # to h_cancel_task's queue scan, and execution keeps its
                # single lane. Only the reply bypasses asyncio (direct
                # sendall from the completing thread).
                def complete_task(reply, m=msgid):
                    if m is None:
                        return
                    try:
                        send_msg([rpc_mod.REPLY_OK, m, "push_task", reply])
                    except OSError:
                        pass

                self._dispatch_exec(spec, complete_task)
        except (ConnectionError, OSError, _fp.FailpointError):
            pass
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def run_task_execution_loop(self):
        """Main loop of worker processes (reference:
        CoreWorkerProcess::RunTaskExecutionLoop, core_worker.h:193).

        The dispatcher thread pops tasks in arrival order (so actor tasks
        *start* in order) but does not necessarily run them itself:
        coroutine methods are scheduled onto the actor's asyncio loop and
        interleave (reference: asyncio actors, _raylet.pyx:377-424), and
        when the actor declared max_concurrency>1, sync methods run on a
        thread pool (reference: fiber.h:30-45)."""
        while not self._shutdown:
            try:
                item = self._exec_queue.get(timeout=0.1)
            except queue_mod.Empty:
                continue
            spec, complete = item
            if spec["task_id"] in self._cancelled_tasks:
                self._cancelled_tasks.discard(spec["task_id"])
                complete(self._pack_error(spec, exc.TaskCancelledError(
                    spec["task_id"].hex())))
                continue
            try:
                if not self._dispatch_concurrent(spec, complete):
                    complete(self._execute_task(spec))
            except BaseException as e:
                # The dispatcher is the worker's single execution lane; it
                # must never die with a reply still owed (a deferred-reply
                # push whose completing thread vanished would hang its
                # caller FOREVER — no timeout fires on a live connection).
                # Error the request first, then fail-stop on fatal errors
                # so the owner's next recourse is ConnectionLost -> retry,
                # never a half-alive worker that accepts-and-drops tasks.
                try:
                    complete(self._pack_error(spec, exc.TaskError(
                        type(e).__name__, repr(e),
                        traceback.format_exc())))
                except Exception:
                    pass
                if not isinstance(e, Exception):
                    # SystemExit/KeyboardInterrupt from task code
                    logger.error("dispatcher hit fatal %r; worker "
                                 "fail-stops", e)
                    os._exit(1)

    def _deliver_reply(self, reply, fut, loop):
        """Resolve a push handler's future from the dispatcher thread.
        Delivery rides the loop's coalesced call queue: a burst of task
        completions costs one self-pipe wakeup, not one syscall per reply
        (call_soon_threadsafe — the round-7 path, kept as the legacy
        control arm — writes the pipe every call)."""
        if loop.is_closed():
            return
        if self._legacy:
            loop.call_soon_threadsafe(
                lambda f=fut, r=reply: f.done() or f.set_result(r))
            return

        def _set(f=fut, r=reply):
            f.done() or f.set_result(r)

        try:
            rpc.loop_call_queue(loop).call(_set)
        except RuntimeError:
            pass  # loop closed under us: nobody is waiting for the reply

    def _dispatch_concurrent(self, spec, complete) -> bool:
        """Route an actor task to the async loop or the thread pool.
        Returns False if the task should run inline on the dispatcher."""
        if spec["type"] != common.ACTOR_TASK or self._actor_instance is None:
            return False
        import inspect

        method = getattr(self._actor_instance, spec["method_name"], None)
        if inspect.iscoroutinefunction(method):
            if self._async_loop is None:
                self._async_loop = rpc.EventLoopThread(name="actor-async")
            # Resolve args on the dispatcher thread: _resolve_args may block
            # on remote refs, and blocking the actor's event loop would
            # freeze every interleaved coroutine (and deadlock if the ref
            # is produced by this very actor).
            try:
                args, kwargs = self._resolve_args(spec["args"])
            except BaseException as e:
                complete(self._pack_error(spec, exc.TaskError(
                    type(e).__name__, repr(e), traceback.format_exc())))
                return True
            cfut = self._async_loop.submit(
                self._execute_coro_task(spec, method, args, kwargs))

            def _done(cf, spec=spec, complete=complete):
                try:
                    reply = cf.result()
                except BaseException as e:
                    # Cancelled loop / SystemExit from the method: still
                    # resolve the caller's future instead of hanging it.
                    reply = self._pack_error(spec, exc.TaskError(
                        type(e).__name__, repr(e), ""))
                complete(reply)

            cfut.add_done_callback(_done)
            return True
        if self._exec_pool is not None:
            self._exec_pool.submit(
                lambda: complete(self._execute_task(spec)))
            return True
        return False

    async def _execute_coro_task(self, spec, method, args, kwargs):
        """Async-actor path: await the coroutine method on the actor's
        event loop so concurrent calls interleave at await points.

        The current task id lives in a contextvar (not the thread-local
        _task_ctx): every interleaved coroutine shares the loop thread, and
        asyncio gives each scheduled coroutine its own context copy, so
        puts/nested submits inside the method attribute to the right task.
        """
        token = _ASYNC_TASK_ID.set(TaskID(spec["task_id"]))
        try:
            with self._exec_scope(spec) as scope:
                try:
                    result = await method(*args, **kwargs)
                    reply = self._pack_returns(spec, result)
                except BaseException as e:
                    if isinstance(e, (SystemExit, KeyboardInterrupt)):
                        raise
                    if (isinstance(e, exc.RayTpuError)
                            and not isinstance(e, exc.GetTimeoutError)):
                        # typed runtime errors cross the task boundary
                        # untranslated (same contract as the sync path)
                        reply = self._pack_error(spec, e)
                    else:
                        error = exc.TaskError(type(e).__name__, repr(e),
                                              traceback.format_exc())
                        reply = self._pack_error(spec, error)
        finally:
            _ASYNC_TASK_ID.reset(token)
            self._cancelled_tasks.discard(spec["task_id"])
        reply["exec_s"] = scope["exec_s"]
        reply["held_s"] = scope["held_s"]
        return reply

    @contextlib.contextmanager
    def _exec_scope(self, spec):
        """Exec span + timing shared by the sync and async execution
        paths. The span is the unconditional per-task profile event
        (pre-trace behavior), upgraded to a trace-tree node when the
        spec carries a sampled context — AMBIENT during execution so
        anything the task submits joins the same tree. Fills
        scope["exec_s"] (user code only) and scope["held_s"] (worker
        arrival -> reply built, one clock — what the owner subtracts
        from the push round trip so dispatcher queueing under pipelined
        pushes never counts as reply-wire overhead)."""
        sender = tracing.from_wire(spec.get("trace"))
        exec_ctx = tracing.child(sender) if sender is not None else None
        token = tracing.push(exec_ctx)
        arrived = spec.pop("_arrived", None)
        start = time.time()
        scope = {}
        exec_token = next(self._exec_seq)
        self._executing[exec_token] = {
            "task_id": spec["task_id"].hex()[:16],
            "name": spec.get("name", "?"),
            "t0": start,
            "thread": threading.current_thread().name,
            "trace_id": (sender.trace_id.hex()
                         if sender is not None else ""),
        }
        try:
            yield scope
        finally:
            self._executing.pop(exec_token, None)
            end = time.time()
            tracing.pop(token)
            tracing.record_span("task", start, end, exec_ctx,
                                {"name": spec.get("name", "?")})
            M_EXEC_S.observe(end - start,
                             exemplar=tracing.exemplar_of(exec_ctx))
            scope["exec_s"] = end - start
            scope["held_s"] = end - (arrived if arrived is not None
                                     else start)

    def _execute_task(self, spec) -> dict:
        with self._exec_scope(spec) as scope:
            reply = self._execute_task_inner(spec)
        if isinstance(reply, dict):
            # lets the owner derive the reply-hop overhead from the push
            # round trip without comparing cross-process clocks
            reply["exec_s"] = scope["exec_s"]
            reply["held_s"] = scope["held_s"]
        # a cancel that raced this execution leaves a marker nothing else
        # will ever consume — drop it so the set stays bounded
        self._cancelled_tasks.discard(spec["task_id"])
        M_TASKS_EXECUTED.inc()
        # The flush coroutine is rate-limited internally, but submitting
        # it at all costs a concurrent.Future + a loop wakeup — gate the
        # submit itself on the same 0.25s limiter so a 1000-task/s worker
        # schedules ~4 flushes/s, not 1000 (the 2s periodic loop
        # guarantees the tail is flushed either way).
        if (self._legacy or time.monotonic() - self._last_profile_flush
                >= 0.25):
            self._io.submit(self._flush_profile_now())
        return reply

    def _execute_task_inner(self, spec) -> dict:
        task_id = TaskID(spec["task_id"])
        self._task_ctx.task_id = task_id
        # Sticky (not reset in finally): output from background threads the
        # task spawned is still attributed to the last job this worker ran.
        self._exec_job_id = spec.get("job_id")
        self._cancel_flag = False
        try:
            if _fp.ARMED:
                # execution seam: `raise` surfaces as a TaskError to the
                # owner, `exit` kills this worker mid-task (owner sees
                # ConnectionLost -> retry or WorkerCrashedError)
                _fp.fire_strict("worker.exec")
            args, kwargs = self._resolve_args(spec["args"])
            if spec["type"] == common.ACTOR_CREATION_TASK:
                cls = self.fetch_function(spec["fn_id"], spec["job_id"],
                                          kind="cls")
                self._actor_instance = cls(*args, **kwargs)
                self._actor_id = ActorID(spec["actor_id"])
                if spec.get("restore"):
                    # relocated/restarted incarnation: a drained-away
                    # checkpoint may be waiting in the GCS KV (written by
                    # the departing raylet) — restore it before the
                    # actor takes traffic
                    self._maybe_restore_actor(spec)
                creation = spec.get("actor_creation") or {}
                if creation.get("max_concurrency", 1) > 1:
                    self._exec_pool = concurrent.futures.ThreadPoolExecutor(
                        max_workers=creation["max_concurrency"])
                return {"returns": []}
            elif spec["type"] == common.ACTOR_TASK:
                method = getattr(self._actor_instance, spec["method_name"])
                result = self._run_callable(method, args, kwargs)
            else:
                fn = self.fetch_function(spec["fn_id"], spec["job_id"])
                result = self._run_callable(fn, args, kwargs)
            return self._pack_returns(spec, result)
        except exc.TaskCancelledError:
            raise
        except BaseException as e:
            if isinstance(e, (SystemExit, KeyboardInterrupt)):
                raise
            if (isinstance(e, exc.RayTpuError)
                    and not isinstance(e, exc.GetTimeoutError)):
                # Typed runtime errors (ObjectLostError surfaced by an
                # arg fetch, ReplicaGroupDied raised by a serve group
                # leader, ...) propagate AS THEMSELVES — wrapping them in
                # TaskError would strip the type the caller's retry/
                # degradation logic dispatches on (reference: RayError
                # subclasses cross the task boundary untranslated).
                # GetTimeoutError stays wrapped: a remote task's internal
                # get timeout must not masquerade as the CALLER's own
                # get() timing out (the chaos harness reads that as a
                # hang).
                return self._pack_error(spec, e)
            error = exc.TaskError(type(e).__name__, repr(e),
                                  traceback.format_exc())
            return self._pack_error(spec, error)
        finally:
            self._task_ctx.task_id = None

    def _maybe_restore_actor(self, spec):
        """Restore drained-away actor state: fetch actor_ckpt:<id> from
        the GCS KV and feed it to the actor's __ray_restore__ hook.
        Missing checkpoint or missing hook -> stateless restart (the
        pre-drain behavior); a failing hook is surfaced as a creation
        error so the GCS records a real death cause."""
        hook = getattr(self._actor_instance, "__ray_restore__", None)
        if not callable(hook):
            return
        key = f"actor_ckpt:{ActorID(spec['actor_id']).hex()}"
        try:
            data = self._io.run(self.gcs.call("kv_get", {"key": key}),
                                timeout=10)
        except Exception:
            logger.warning("checkpoint lookup for %s failed; restarting "
                           "stateless", key)
            return
        if data is not None:
            hook(serialization.loads(data))

    def _run_callable(self, fn, args, kwargs):
        import inspect

        if inspect.iscoroutinefunction(fn):
            if self._async_loop is None:
                self._async_loop = rpc.EventLoopThread(name="actor-async")
            return self._async_loop.run(fn(*args, **kwargs))
        return fn(*args, **kwargs)

    def _resolve_args(self, descs):
        args = []
        kwargs = {}
        for desc in descs:
            if desc["kind"] == "inline":
                args.append(serialization.loads(desc["data"]))
            elif desc["kind"] == "kwargs":
                kwargs = serialization.loads(desc["data"])
            else:  # ref
                ref = self.deserialize_ref(desc)
                args.append(self._get_one(ref, timeout=None))
        return args, kwargs

    def _pack_returns(self, spec, result) -> dict:
        num_returns = spec["num_returns"]
        if num_returns == 0:
            return {"returns": []}
        if num_returns == 1:
            values = [result]
        else:
            values = list(result)
            if len(values) != num_returns:
                raise ValueError(
                    f"task declared num_returns={num_returns} but returned "
                    f"{len(values)} values")
        returns = []
        for i, value in enumerate(values):
            return_id = ObjectID.for_return(TaskID(spec["task_id"]), i)
            header, buffers = serialization.serialize(value)
            size = serialization.total_size(header, buffers)
            if size <= self.config.max_direct_call_object_size:
                payload = b"".join([header, *[bytes(b) for b in buffers]])
                returns.append({"kind": "inline", "data": payload,
                                "err": False})
            else:
                self.store.put_serialized(return_id, header, buffers)
                self._io.run(self.raylet.call("notify_object_sealed", {
                    "object_id": return_id.binary(), "size": size}))
                returns.append({"kind": "plasma", "size": size})
        return {"returns": returns}

    def _pack_error(self, spec, error) -> dict:
        payload = serialization.dumps(error)
        return {"returns": [
            {"kind": "inline", "data": payload, "err": True}
            for _ in range(max(spec["num_returns"], 1))
        ], "error_repr": str(error)}

    async def h_checkpoint_actor(self, conn, d):
        """Drain-time state snapshot (raylet-driven): run the actor's
        __ray_checkpoint__() hook and hand the pickled result back —
        the raylet lands it in the GCS KV and the relocated incarnation
        restores it via __ray_restore__. Actors without the hook return
        None and relocate stateless. In a normal drain the raylet has
        already waited out in-flight leases, so the hook runs on a
        quiet actor; under a compressed preemption drain it may race a
        running method — that's the documented best-effort tradeoff."""
        actor = self._actor_instance
        hook = getattr(actor, "__ray_checkpoint__", None)
        if actor is None or not callable(hook):
            return {"state": None}
        state = await asyncio.get_running_loop().run_in_executor(None, hook)
        return {"state": serialization.dumps(state)}

    async def h_exit(self, conn, d):
        self._exiting = True
        self._shutdown = True

        def _die():
            time.sleep(0.1)
            os._exit(0)

        threading.Thread(target=_die, daemon=True).start()
        return True

    async def h_cancel_task(self, conn, d):
        # Best-effort: only tasks still queued (not yet executing) can be
        # cancelled without force; force interrupts the dispatcher thread.
        # Tasks queued in the direct task channel's socket buffer are
        # caught by this marker when their frame is read. Bounded: a
        # marker for an already-finished task is never consumed, so cap
        # the set (dropping an arbitrary stale marker only downgrades a
        # best-effort cancel to a no-op).
        if len(self._cancelled_tasks) >= 4096:
            self._cancelled_tasks.pop()
        self._cancelled_tasks.add(d["task_id"])
        cancelled = []
        drained = []
        while True:
            try:
                item = self._exec_queue.get_nowait()
            except queue_mod.Empty:
                break
            spec, complete = item
            if spec["task_id"] == d["task_id"]:
                err = exc.TaskCancelledError(spec["task_id"].hex())
                complete(self._pack_error(spec, err))
                cancelled.append(spec["task_id"])
                self._cancelled_tasks.discard(spec["task_id"])
            else:
                drained.append(item)
        for item in drained:
            self._exec_queue.put(item)
        return bool(cancelled)

    # ------------------------------------------------------------------
    # misc
    # ------------------------------------------------------------------

    async def _peer(self, address: str) -> rpc.Connection:
        conn = self._peer_conns.get(address)
        if conn is not None and not conn.closed:
            return conn
        lock = self._peer_dial_locks.setdefault(address, asyncio.Lock())
        async with lock:
            conn = self._peer_conns.get(address)
            if conn is not None and not conn.closed:
                return conn
            conn = await rpc.connect(self._maybe_uds(address),
                                     handlers=self._handlers(),
                                     name=f"cw->{address}")
            self._cache_peer(address, conn)
        return conn

    def _cache_peer(self, address: str, conn: rpc.Connection) -> None:
        """Install a freshly dialed peer conn, CLOSING any live one it
        replaces: a silently dropped connection strands its in-flight
        calls in a GC-able island (they never resume), while close()
        errors them with ConnectionLost so every waiter takes a typed
        failure path."""
        old = self._peer_conns.get(address)
        self._peer_conns[address] = conn
        if old is not None and old is not conn and not old.closed:
            asyncio.ensure_future(old.close())

    def as_future(self, ref: ObjectRef) -> concurrent.futures.Future:
        """Future resolving to the object, WITHOUT a parked thread per
        call: a memstore ready-callback resolves small results inline
        (reference analog: memory_store GetAsync), and only IN_PLASMA
        values — which may pull or reconstruct — hop to a small shared
        pool. A thread-per-call here capped serve HTTP at ~1k qps."""
        fut: concurrent.futures.Future = concurrent.futures.Future()
        object_id = ref.id()

        def deliver(result=None, exception=None):
            # the caller may have cancelled (e.g. aiohttp killing a
            # handler task on client disconnect) — never raise back into
            # the putter's callback loop
            if fut.cancelled():
                return
            try:
                if exception is not None:
                    fut.set_exception(exception)
                else:
                    fut.set_result(result)
            except concurrent.futures.InvalidStateError:
                pass

        def resolve_blocking():
            try:
                deliver(self._get_one(ref, None))
            except BaseException as e:
                deliver(exception=e)

        def on_ready():
            found, value, is_exc = self.memstore.get_if_ready(object_id)
            if not found or value is IN_PLASMA:
                # raced a reset(), or plasma-resident: the pull/restore
                # can block for seconds — a dedicated thread (the old
                # per-call design) avoids head-of-line blocking behind
                # other slow resolutions
                threading.Thread(target=resolve_blocking,
                                 daemon=True).start()
                return
            try:
                result = serialization.deserialize(value)
            except BaseException as e:
                deliver(exception=e)
                return
            if is_exc:
                deliver(exception=result)
            else:
                deliver(result)

        self._ensure_fetch(ref)
        self.memstore.add_ready_callback(object_id, on_ready)
        return fut

    def resolve_async(self, ref: ObjectRef) -> asyncio.Future:
        """Asyncio-native get: an asyncio.Future on the CALLING loop that
        resolves to the value. Unlike `as_future` + `wrap_future` (a
        concurrent.Future plus one call_soon_threadsafe per ref), delivery
        rides the loop's coalesced call queue — a task reply carrying N
        awaited results costs one loop wakeup, not N. This is what
        `await ref` uses under an event loop (the serve proxy hot path)."""
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        caller = rpc.loop_call_queue(loop)
        object_id = ref.id()

        def deliver(result, is_exc):
            def _set():
                if fut.cancelled():
                    return
                if is_exc:
                    fut.set_exception(result)
                else:
                    fut.set_result(result)
            try:
                caller.call(_set)
            except RuntimeError:
                pass  # caller's loop closed: nobody is waiting

        def resolve_blocking():
            try:
                deliver(self._get_one(ref, None), False)
            except BaseException as e:
                deliver(e, True)

        def on_ready():
            found, value, is_exc = self.memstore.get_if_ready(object_id)
            if not found or value is IN_PLASMA:
                # raced a reset(), or plasma-resident: the pull/restore can
                # block for seconds — resolve on a thread, off this loop
                threading.Thread(target=resolve_blocking,
                                 daemon=True).start()
                return
            try:
                result = serialization.deserialize(value)
            except BaseException as e:
                deliver(e, True)
                return
            deliver(result, is_exc)

        self._ensure_fetch(ref)
        self.memstore.add_ready_callback(object_id, on_ready)
        return fut

    def cluster_info(self) -> dict:
        return self._io.run(self.raylet.call("cluster_info", {}))

    # internal kv (reference: python/ray/experimental/internal_kv.py —
    # GCS-backed KV used by libraries for rendezvous/config)
    def kv_put(self, key: str, value: bytes, overwrite=True) -> bool:
        return self._io.run(self.gcs.call("kv_put", {
            "key": key, "value": value, "overwrite": overwrite}))

    def kv_get(self, key: str) -> bytes | None:
        return self._io.run(self.gcs.call("kv_get", {"key": key}))

    def kv_del(self, key: str) -> bool:
        return self._io.run(self.gcs.call("kv_del", {"key": key}))

    def kv_exists(self, key: str) -> bool:
        return self._io.run(self.gcs.call("kv_exists", {"key": key}))

    def kv_keys(self, prefix: str) -> list[str]:
        return self._io.run(self.gcs.call("kv_keys", {"prefix": prefix}))

    def notify_actor_exiting(self):
        try:
            self._io.run(self.raylet.call("actor_exiting", {}))
        except Exception:
            pass

    def shutdown(self):
        if self._shutdown:
            return
        if (self.mode == DRIVER
                and os.environ.get("RAY_TPU_FINAL_SNAPSHOT", "")
                not in ("", "0")):
            # flight-recorder tail (opt-in; tests/conftest.py arms it):
            # one bounded cluster snapshot BEFORE teardown, so post-
            # mortem checks (the leak check) can name unreturned leases
            # / leaked pins / orphan workers from state instead of bare
            # pids and paths. Off by default — a production driver exit
            # should not pay a cluster sweep nobody reads.
            try:
                _debug.note_final_snapshot(
                    self.get_cluster_state(timeout=1.5))
            except Exception:
                pass
        self._shutdown = True
        _sprof.stop()

        async def _close():
            for key, leases in list(self.leases.items()):
                for lease in leases:
                    try:
                        await lease.raylet_conn.call(
                            "return_worker",
                            {"lease_id": lease.lease_id})
                    except Exception:
                        pass
            await self.server.close()
            for conn in list(self._peer_conns.values()):
                await conn.close()
            if self.raylet is not None:
                await self.raylet.close()
            if self.gcs is not None:
                await self.gcs.close()

        try:
            self._io.run(_close(), timeout=5)
        except Exception:
            pass
        self._io.stop()
        global_state.set_core_worker(None)
