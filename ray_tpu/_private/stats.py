"""Process-local metrics: counters, gauges, histograms (reference:
src/ray/stats/metric.h Count/Gauge/Histogram + metric_defs.cc).

Each runtime process (gcs, raylet, worker, driver) keeps one registry;
raylets and the GCS expose theirs over RPC ("get_metrics"), aggregated by
`ray-tpu metrics` / api.cluster_metrics(). No external metrics daemon: the
control-plane RPC layer is the export path (the reference pushes to
OpenCensus/Prometheus exporters instead).

Histograms carry **exemplars** (the OpenMetrics idea): observe() may
attach a trace id, and each bucket keeps its most recent and its
max-valued exemplar — so a p99 read off the snapshot links straight to
one real outlier's trace tree (`ray-tpu trace --trace-id`). Disable
with RAY_TPU_EXEMPLARS=0."""

from __future__ import annotations

import logging
import os
import threading
import time
from bisect import bisect_right

logger = logging.getLogger("ray_tpu.stats")

# Exemplar knob: exemplars cost one dict write per observe-with-exemplar;
# 0 disables capture everywhere (snapshots then carry no "exemplars").
EXEMPLARS_ENABLED = os.environ.get("RAY_TPU_EXEMPLARS", "1") not in (
    "0", "false", "")


class Metric:
    def __init__(self, name: str, description: str = ""):
        self.name = name
        self.description = description
        existing = _REGISTRY.register(self)
        if existing is not self:
            # same-named re-registration: the FIRST instance stays the
            # registered truth; this instance becomes a proxy to it so
            # neither side's updates are lost (a replaced counter used
            # to silently drop all prior increments)
            self._delegate_to(existing)

    def _delegate_to(self, existing: "Metric") -> None:  # pragma: no cover
        pass


class Count(Metric):
    """Monotonic counter (reference: metric.h Count)."""

    def __init__(self, name: str, description: str = ""):
        self._value = 0.0
        self._lock = threading.Lock()
        super().__init__(name, description)

    def inc(self, by: float = 1.0):
        with self._lock:
            self._value += by

    def snapshot(self):
        with self._lock:
            return {"type": "count", "value": self._value}

    def _delegate_to(self, existing):
        self.inc = existing.inc
        self.snapshot = existing.snapshot


class Gauge(Metric):
    """Last-set value (reference: metric.h Gauge)."""

    def __init__(self, name: str, description: str = ""):
        self._value = 0.0
        self._lock = threading.Lock()
        super().__init__(name, description)

    def set(self, value: float):
        # Locked: an unlocked float store racing add() could be lost OR
        # land mid-read of a snapshot (set/add/snapshot all serialize).
        with self._lock:
            self._value = float(value)

    def add(self, delta: float):
        """Thread-safe relative update — for gauges tracking a live count
        (e.g. in-flight transfer chunks) incremented/decremented from
        many worker threads."""
        with self._lock:
            self._value += delta

    def snapshot(self):
        with self._lock:
            return {"type": "gauge", "value": self._value}

    def _delegate_to(self, existing):
        self.set = existing.set
        self.add = existing.add
        self.snapshot = existing.snapshot


class Histogram(Metric):
    """Fixed-boundary histogram (reference: metric.h Histogram), with
    optional per-bucket trace-id exemplars."""

    def __init__(self, name: str, boundaries: list[float],
                 description: str = ""):
        self.boundaries = sorted(boundaries)
        self._counts = [0] * (len(self.boundaries) + 1)
        self._sum = 0.0
        self._n = 0
        # bucket index -> {"last": exemplar, "max": exemplar}; exemplar =
        # {"trace_id", "value", "ts"}. Bounded by construction: <=2 per
        # bucket, only buckets that ever saw an exemplar have an entry.
        self._exemplars: dict[int, dict] = {}
        self._lock = threading.Lock()
        super().__init__(name, description)

    def observe(self, value: float, exemplar: str | None = None):
        """Record one observation; `exemplar` is the hex trace id of the
        call that produced it (threaded from the traced seams), kept as
        the bucket's most recent and — separately — max-valued link."""
        i = bisect_right(self.boundaries, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._n += 1
            if exemplar and EXEMPLARS_ENABLED:
                ex = {"trace_id": exemplar, "value": float(value),
                      "ts": time.time()}
                slot = self._exemplars.get(i)
                if slot is None:
                    slot = self._exemplars[i] = {}
                slot["last"] = ex
                cur_max = slot.get("max")
                if cur_max is None or value >= cur_max["value"]:
                    slot["max"] = ex

    def snapshot(self):
        # Locked: without it a snapshot can read a torn (counts, sum, n)
        # triple while observe() is mid-update on another thread.
        with self._lock:
            snap = {"type": "histogram", "boundaries": self.boundaries,
                    "counts": list(self._counts), "sum": self._sum,
                    "count": self._n}
            if self._exemplars:
                # str bucket keys: the snapshot crosses msgpack AND the
                # dashboard's JSON surfaces (JSON objects key by string)
                snap["exemplars"] = {
                    str(i): {k: dict(v) for k, v in slot.items()}
                    for i, slot in self._exemplars.items()}
            return snap

    def reset_exemplars(self):
        with self._lock:
            self._exemplars.clear()

    def _delegate_to(self, existing):
        self.boundaries = existing.boundaries
        self.observe = existing.observe
        self.snapshot = existing.snapshot
        self.reset_exemplars = existing.reset_exemplars


class Registry:
    def __init__(self):
        self._metrics: dict[str, Metric] = {}
        self._lock = threading.Lock()

    def register(self, metric: Metric) -> Metric:
        """Register `metric`, returning the canonical instance for its
        name: the existing one when a same-typed metric is already
        registered (with a warning — the caller's instance proxies to
        it), else `metric` itself. A same-named metric of a DIFFERENT
        type replaces (the old registration was wrong), still warned."""
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None and existing is not metric:
                if type(existing) is type(metric):
                    logger.warning(
                        "metric %r registered twice; keeping the "
                        "existing instance (prior values preserved)",
                        metric.name)
                    return existing
                logger.warning(
                    "metric %r re-registered as %s (was %s); replacing",
                    metric.name, type(metric).__name__,
                    type(existing).__name__)
            self._metrics[metric.name] = metric
            return metric

    def get(self, name: str) -> Metric | None:
        # Locked (satellite fix): an unlocked dict read can race a
        # register() rehash on another thread.
        with self._lock:
            return self._metrics.get(name)

    def snapshot(self) -> dict:
        with self._lock:
            return {name: m.snapshot() for name, m in self._metrics.items()}

    def reset_exemplars(self) -> None:
        """Clear every histogram's exemplars. Exemplar trace ids are
        CLUSTER-scoped (they resolve against one GCS trace table): a
        process connecting to a new cluster must not keep advertising
        outliers whose trees died with the previous one."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            reset = getattr(m, "reset_exemplars", None)
            if reset is not None:
                reset()


_REGISTRY = Registry()


def registry() -> Registry:
    return _REGISTRY


def snapshot() -> dict:
    return _REGISTRY.snapshot()


def reset_exemplars() -> None:
    _REGISTRY.reset_exemplars()


# Log-spaced seconds boundaries shared by the per-hop latency histograms
# (task queue-wait/lease/exec/reply/e2e, serve router queue/e2e).
LATENCY_BOUNDARIES_S = [0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                        0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0]

# Wider band for jit-compile wall time (compiles run 10ms..minutes; the
# task-latency band would saturate at 10s and hide a compile storm).
COMPILE_BOUNDARIES_S = [0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                        5.0, 10.0, 30.0, 60.0, 120.0]


def percentile(hist_snapshot: dict, q: float,
               with_saturation: bool = False):
    """Estimate the q-quantile (0..1) from a histogram SNAPSHOT — the
    upper boundary of the bucket containing the quantile (how the serve
    autoscaler reads router p99 from cluster_metrics()). Quantiles
    landing in the unbounded overflow bucket CLAMP to the top boundary
    (Prometheus histogram_quantile convention; inf would not survive
    the JSON surfaces) — a clamped reading means "at least this much".

    `with_saturation=True` returns `(value, saturated)` instead, where
    `saturated` is True exactly when the quantile landed in the
    overflow bucket — the explicit signal consumers (`ray-tpu top`'s
    `≥` rendering, the stall doctor) need to tell saturation from a
    real reading."""
    counts = hist_snapshot.get("counts") or []
    boundaries = hist_snapshot.get("boundaries") or []
    total = hist_snapshot.get("count", 0)
    if not total or not counts:
        return (0.0, False) if with_saturation else 0.0
    target = q * total
    acc = 0
    value, saturated = 0.0, False
    for i, c in enumerate(counts):
        acc += c
        if acc >= target:
            saturated = i >= len(boundaries)
            value = (boundaries[i] if i < len(boundaries)
                     else boundaries[-1] if boundaries else 0.0)
            break
    else:
        saturated = True
        value = boundaries[-1] if boundaries else 0.0
    return (value, saturated) if with_saturation else value


def overflow_count(hist_snapshot: dict) -> int:
    """Observations in the unbounded overflow bucket (surfaced beside
    .p99 in the metrics-history flattening)."""
    counts = hist_snapshot.get("counts") or []
    boundaries = hist_snapshot.get("boundaries") or []
    if len(counts) <= len(boundaries):
        return 0
    return int(counts[len(boundaries)])


def quantile_exemplar(hist_snapshot: dict, q: float = 0.99) -> dict | None:
    """The exemplar that best explains the q-quantile: the max-valued
    exemplar of the highest populated bucket at/above the quantile
    bucket (i.e. one real outlier whose trace id a p99 row can print).
    Falls back to lower buckets' max exemplar when the tail carried
    none. Returns {"trace_id", "value", "ts"} or None."""
    exemplars = hist_snapshot.get("exemplars")
    if not exemplars:
        return None
    counts = hist_snapshot.get("counts") or []
    total = hist_snapshot.get("count", 0)
    if not total:
        return None
    target = q * total
    acc = 0
    q_bucket = len(counts) - 1
    for i, c in enumerate(counts):
        acc += c
        if acc >= target:
            q_bucket = i
            break
    best = None
    for key, slot in exemplars.items():
        try:
            i = int(key)
        except (TypeError, ValueError):
            continue
        ex = slot.get("max") or slot.get("last")
        if ex is None:
            continue
        # prefer the highest bucket >= the quantile bucket; else the
        # highest bucket below it
        rank = (1, i) if i >= q_bucket else (0, i)
        if best is None or rank > best[0]:
            best = (rank, ex)
    return dict(best[1]) if best else None
