"""Process-local metrics: counters, gauges, histograms (reference:
src/ray/stats/metric.h Count/Gauge/Histogram + metric_defs.cc).

Each runtime process (gcs, raylet, worker, driver) keeps one registry;
raylets and the GCS expose theirs over RPC ("get_metrics"), aggregated by
`ray-tpu metrics` / api.cluster_metrics(). No external metrics daemon: the
control-plane RPC layer is the export path (the reference pushes to
OpenCensus/Prometheus exporters instead)."""

from __future__ import annotations

import threading
from bisect import bisect_right


class Metric:
    def __init__(self, name: str, description: str = ""):
        self.name = name
        self.description = description
        _REGISTRY.register(self)


class Count(Metric):
    """Monotonic counter (reference: metric.h Count)."""

    def __init__(self, name: str, description: str = ""):
        self._value = 0.0
        self._lock = threading.Lock()
        super().__init__(name, description)

    def inc(self, by: float = 1.0):
        with self._lock:
            self._value += by

    def snapshot(self):
        with self._lock:
            return {"type": "count", "value": self._value}


class Gauge(Metric):
    """Last-set value (reference: metric.h Gauge)."""

    def __init__(self, name: str, description: str = ""):
        self._value = 0.0
        self._lock = threading.Lock()
        super().__init__(name, description)

    def set(self, value: float):
        # Locked: an unlocked float store racing add() could be lost OR
        # land mid-read of a snapshot (set/add/snapshot all serialize).
        with self._lock:
            self._value = float(value)

    def add(self, delta: float):
        """Thread-safe relative update — for gauges tracking a live count
        (e.g. in-flight transfer chunks) incremented/decremented from
        many worker threads."""
        with self._lock:
            self._value += delta

    def snapshot(self):
        with self._lock:
            return {"type": "gauge", "value": self._value}


class Histogram(Metric):
    """Fixed-boundary histogram (reference: metric.h Histogram)."""

    def __init__(self, name: str, boundaries: list[float],
                 description: str = ""):
        self.boundaries = sorted(boundaries)
        self._counts = [0] * (len(self.boundaries) + 1)
        self._sum = 0.0
        self._n = 0
        self._lock = threading.Lock()
        super().__init__(name, description)

    def observe(self, value: float):
        with self._lock:
            self._counts[bisect_right(self.boundaries, value)] += 1
            self._sum += value
            self._n += 1

    def snapshot(self):
        # Locked: without it a snapshot can read a torn (counts, sum, n)
        # triple while observe() is mid-update on another thread.
        with self._lock:
            return {"type": "histogram", "boundaries": self.boundaries,
                    "counts": list(self._counts), "sum": self._sum,
                    "count": self._n}


class Registry:
    def __init__(self):
        self._metrics: dict[str, Metric] = {}
        self._lock = threading.Lock()

    def register(self, metric: Metric):
        with self._lock:
            self._metrics[metric.name] = metric

    def get(self, name: str) -> Metric | None:
        return self._metrics.get(name)

    def snapshot(self) -> dict:
        with self._lock:
            return {name: m.snapshot() for name, m in self._metrics.items()}


_REGISTRY = Registry()


def registry() -> Registry:
    return _REGISTRY


def snapshot() -> dict:
    return _REGISTRY.snapshot()


# Log-spaced seconds boundaries shared by the per-hop latency histograms
# (task queue-wait/lease/exec/reply/e2e, serve router queue/e2e).
LATENCY_BOUNDARIES_S = [0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                        0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0]


def percentile(hist_snapshot: dict, q: float) -> float:
    """Estimate the q-quantile (0..1) from a histogram SNAPSHOT — the
    upper boundary of the bucket containing the quantile (how the serve
    autoscaler reads router p99 from cluster_metrics()). Quantiles
    landing in the unbounded overflow bucket CLAMP to the top boundary
    (Prometheus histogram_quantile convention; inf would not survive
    the JSON surfaces) — a reading AT the top boundary means "at least
    this", and consumers watching for saturation should pair it with
    the .count rate."""
    counts = hist_snapshot.get("counts") or []
    boundaries = hist_snapshot.get("boundaries") or []
    total = hist_snapshot.get("count", 0)
    if not total or not counts:
        return 0.0
    target = q * total
    acc = 0
    for i, c in enumerate(counts):
        acc += c
        if acc >= target:
            return (boundaries[i] if i < len(boundaries)
                    else boundaries[-1] if boundaries else 0.0)
    return boundaries[-1] if boundaries else 0.0
