"""Structured cluster events (the RAY_EVENT analog; reference:
src/ray/util/event.h:36 RAY_EVENT macro, EventManager :84,
LogEventReporter :51 — structured severity/label events written to an
event log dir and surfaced to operators).

Each runtime process calls `init_events(source_type, source_id,
log_dir)` once; `report_event()` then appends a JSON line to
<log_dir>/events/event_<source_type>.log and, when a forwarder is
registered (runtime processes forward to the GCS), mirrors the event to
the cluster-wide ring buffer read by `ray-tpu events` and the dashboard
`/api/events` view."""

from __future__ import annotations

import json
import os
import threading
import time

INFO, WARNING, ERROR, FATAL = "INFO", "WARNING", "ERROR", "FATAL"

_lock = threading.Lock()
_state = {"source_type": "unknown", "source_id": "", "path": None,
          "forward": None}


def init_events(source_type: str, source_id: str,
                log_dir: str | None = None, forward=None):
    """forward: callable(event_dict) — fire-and-forget mirror (the
    runtime passes a GCS notify)."""
    with _lock:
        _state["source_type"] = source_type
        _state["source_id"] = source_id
        _state["forward"] = forward
        if log_dir:
            event_dir = os.path.join(log_dir, "events")
            os.makedirs(event_dir, exist_ok=True)
            _state["path"] = os.path.join(
                event_dir, f"event_{source_type}.log")


def report_event(severity: str, label: str, message: str, **fields):
    """reference: RAY_EVENT(severity, label) << message."""
    event = {
        "timestamp": time.time(),
        "severity": severity,
        "label": label,
        "message": message,
        "source_type": _state["source_type"],
        "source_id": _state["source_id"],
        "source_pid": os.getpid(),
        **({"custom_fields": fields} if fields else {}),
    }
    path = _state["path"]
    if path:
        try:
            with _lock, open(path, "a") as f:
                f.write(json.dumps(event) + "\n")
        except OSError:
            pass
    forward = _state["forward"]
    if forward is not None:
        try:
            forward(event)
        except Exception:
            pass
    return event


def read_events(log_dir: str, source_type: str | None = None) -> list[dict]:
    """Parse events back from an event log dir (test/CLI helper)."""
    event_dir = os.path.join(log_dir, "events")
    if not os.path.isdir(event_dir):
        return []
    out = []
    for name in sorted(os.listdir(event_dir)):
        if source_type and name != f"event_{source_type}.log":
            continue
        with open(os.path.join(event_dir, name)) as f:
            for line in f:
                if line.strip():
                    out.append(json.loads(line))
    out.sort(key=lambda e: e["timestamp"])
    return out
