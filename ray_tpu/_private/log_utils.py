"""Per-process logging setup: each service logs to its own file in the
session dir (reference behavior: per-process files in the session dir,
src/ray/util/logging.h RAY_LOG + python log_monitor tailing)."""

from __future__ import annotations

import logging
import os
import sys


class _ForwardingStream:
    """Wraps a worker's stdout/stderr: lines still reach the local log
    file AND are published to the driver through GCS pubsub — the analog
    of the reference's log_monitor.py:48 tail-and-republish (without the
    extra tailing process: the worker pushes directly)."""

    def __init__(self, original, publish, stream_name: str):
        self._original = original
        self._publish = publish
        self._stream = stream_name
        self._buf = ""

    def write(self, data):
        n = self._original.write(data)
        self._buf += data
        while "\n" in self._buf:
            line, self._buf = self._buf.split("\n", 1)
            if line.strip():
                try:
                    self._publish(line, self._stream)
                except Exception:
                    pass
        return n

    def flush(self):
        self._original.flush()

    def __getattr__(self, name):
        return getattr(self._original, name)


def install_stdout_forwarder(core_worker):
    """Route this worker's print()/stderr output to the driver(s)."""
    sys.stdout = _ForwardingStream(sys.stdout, core_worker.publish_log,
                                   "stdout")
    sys.stderr = _ForwardingStream(sys.stderr, core_worker.publish_log,
                                   "stderr")


def setup_process_logging(name: str, log_file: str | None = None,
                          level=logging.INFO):
    fmt = logging.Formatter(
        f"[%(asctime)s %(levelname).1s {name} pid={os.getpid()}] "
        "%(name)s: %(message)s"
    )
    root = logging.getLogger()
    root.setLevel(level)
    if log_file:
        os.makedirs(os.path.dirname(log_file), exist_ok=True)
        handler: logging.Handler = logging.FileHandler(log_file)
    else:
        handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(fmt)
    root.addHandler(handler)
    return root
