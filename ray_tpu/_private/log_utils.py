"""Per-process logging setup: each service logs to its own file in the
session dir (reference behavior: per-process files in the session dir,
src/ray/util/logging.h RAY_LOG + python log_monitor tailing)."""

from __future__ import annotations

import logging
import os
import sys


def setup_process_logging(name: str, log_file: str | None = None,
                          level=logging.INFO):
    fmt = logging.Formatter(
        f"[%(asctime)s %(levelname).1s {name} pid={os.getpid()}] "
        "%(name)s: %(message)s"
    )
    root = logging.getLogger()
    root.setLevel(level)
    if log_file:
        os.makedirs(os.path.dirname(log_file), exist_ok=True)
        handler: logging.Handler = logging.FileHandler(log_file)
    else:
        handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(fmt)
    root.addHandler(handler)
    return root
