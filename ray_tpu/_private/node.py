"""Node bootstrap: spawn/stop the GCS and raylet service processes
(reference: python/ray/node.py:52 Node, start_head_processes :854,
start_ray_processes :875; python/ray/_private/services.py spawners)."""

from __future__ import annotations

import atexit
import json
import logging
import os
import signal
import subprocess
import sys
import time
import uuid

from ray_tpu._private.config import Config
from ray_tpu._private.ids import NodeID
from ray_tpu._private.object_store import default_store_root

logger = logging.getLogger("ray_tpu.node")


def new_session_dir() -> str:
    base = os.environ.get("RAY_TPU_TMPDIR", "/tmp/ray_tpu")
    session = f"session_{time.strftime('%Y%m%d_%H%M%S')}_{uuid.uuid4().hex[:8]}"
    path = os.path.join(base, session)
    os.makedirs(os.path.join(path, "logs"), exist_ok=True)
    return path


def _wait_ready(ready_file: str, proc: subprocess.Popen, what: str,
                timeout: float = 30.0) -> str:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(ready_file):
            with open(ready_file) as f:
                return f.read().strip()
        if proc.poll() is not None:
            raise RuntimeError(
                f"{what} exited with code {proc.returncode} during startup")
        time.sleep(0.02)
    raise TimeoutError(f"{what} did not become ready in {timeout}s")


class ServiceProcess:
    def __init__(self, name: str, proc: subprocess.Popen):
        self.name = name
        self.proc = proc

    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self, sig=signal.SIGKILL):
        if self.alive():
            try:
                os.killpg(os.getpgid(self.proc.pid), sig)
            except (ProcessLookupError, PermissionError):
                try:
                    self.proc.kill()
                except ProcessLookupError:
                    pass
        try:
            self.proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            pass


def strip_tpu_plugin_env(env: dict) -> dict:
    """Remove TPU-plugin activation vars so pure control-plane processes
    skip the expensive jax/PJRT import their sitecustomize would trigger
    (observed ~2s per process; catastrophic on few-core hosts)."""
    for key in ("PALLAS_AXON_POOL_IPS",):
        env.pop(key, None)
    # If the ambient env pins jax to the stripped plugin's platform, the
    # child would fail backend init ("axon not in known backends") — let
    # jax pick from what's actually registered there.
    if env.get("JAX_PLATFORMS", "").lower() not in ("", "cpu"):
        env["JAX_PLATFORMS"] = ""
    return env


def _spawn(cmd: list[str], config: Config, name: str) -> ServiceProcess:
    env = strip_tpu_plugin_env(dict(os.environ))
    env.update(config.child_env())
    proc = subprocess.Popen(
        cmd, env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        start_new_session=True)
    return ServiceProcess(name, proc)


def start_gcs(session_dir: str, config: Config, port: int = 0) -> tuple[ServiceProcess, str]:
    ready = os.path.join(session_dir, f"gcs_ready_{uuid.uuid4().hex[:6]}")
    log_file = os.path.join(session_dir, "logs", "gcs_server.log")
    svc = _spawn([
        sys.executable, "-m", "ray_tpu.gcs.server",
        "--port", str(port),
        "--ready-file", ready,
        "--log-file", log_file,
    ], config, "gcs_server")
    actual_port = _wait_ready(ready, svc.proc, "gcs_server")
    return svc, f"127.0.0.1:{actual_port}"


def start_raylet(session_dir: str, gcs_address: str, config: Config, *,
                 node_id: NodeID | None = None, num_cpus: float | None = None,
                 num_tpus: float = 0, resources: dict | None = None,
                 labels: dict | None = None, is_head=False,
                 store_root: str | None = None) -> tuple[ServiceProcess, str, NodeID, str]:
    node_id = node_id or NodeID.from_random()
    ready = os.path.join(session_dir, f"raylet_ready_{node_id.hex()[:8]}")
    log_file = os.path.join(session_dir, "logs",
                            f"raylet-{node_id.hex()[:8]}.log")
    if store_root is None:
        store_root = os.path.join(default_store_root(session_dir),
                                  node_id.hex()[:8])
    cmd = [
        sys.executable, "-m", "ray_tpu.raylet.raylet",
        "--gcs-address", gcs_address,
        "--session-dir", session_dir,
        "--store-root", store_root,
        "--node-id", node_id.hex(),
        "--resources", json.dumps(resources or {}),
        "--labels", json.dumps(labels or {}),
        "--ready-file", ready,
        "--log-file", log_file,
    ]
    if num_cpus is not None:
        cmd += ["--num-cpus", str(num_cpus)]
    if num_tpus:
        cmd += ["--num-tpus", str(num_tpus)]
    if is_head:
        cmd += ["--is-head"]
    svc = _spawn(cmd, config, f"raylet-{node_id.hex()[:8]}")
    address = _wait_ready(ready, svc.proc, "raylet")
    return svc, address, node_id, store_root


class Node:
    """A local cluster head (GCS + one raylet) or an added worker node."""

    def __init__(self, *, config: Config, session_dir: str | None = None,
                 gcs_address: str | None = None, num_cpus=None, num_tpus=0,
                 resources=None, labels=None):
        self.config = config
        self.session_dir = session_dir or new_session_dir()
        self.processes: list[ServiceProcess] = []
        self.is_head = gcs_address is None
        if gcs_address is None:
            gcs_proc, gcs_address = start_gcs(self.session_dir, config,
                                              config.gcs_port)
            self.processes.append(gcs_proc)
        self.gcs_address = gcs_address
        raylet_proc, raylet_addr, node_id, store_root = start_raylet(
            self.session_dir, gcs_address, config,
            num_cpus=num_cpus, num_tpus=num_tpus, resources=resources,
            labels=labels, is_head=self.is_head)
        self.processes.append(raylet_proc)
        self.raylet_address = raylet_addr
        self.node_id = node_id
        self.store_root = store_root
        atexit.register(self.kill_all_processes)

    def kill_all_processes(self):
        for svc in reversed(self.processes):
            svc.kill()
        self.processes.clear()

    def kill_raylet(self):
        """Fault injection: kill this node's raylet (reference test idiom:
        Node._kill_process_type, node.py:894)."""
        for svc in self.processes:
            if svc.name.startswith("raylet"):
                svc.kill()
