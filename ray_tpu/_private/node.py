"""Node bootstrap: spawn/stop the GCS and raylet service processes
(reference: python/ray/node.py:52 Node, start_head_processes :854,
start_ray_processes :875; python/ray/_private/services.py spawners)."""

from __future__ import annotations

import atexit
import json
import logging
import os
import signal
import subprocess
import sys
import time
import uuid

from ray_tpu._private.config import Config
from ray_tpu._private.ids import NodeID
from ray_tpu._private.object_store import default_store_root

logger = logging.getLogger("ray_tpu.node")


def new_session_dir() -> str:
    base = os.environ.get("RAY_TPU_TMPDIR", "/tmp/ray_tpu")
    session = f"session_{time.strftime('%Y%m%d_%H%M%S')}_{uuid.uuid4().hex[:8]}"
    path = os.path.join(base, session)
    os.makedirs(os.path.join(path, "logs"), exist_ok=True)
    return path


def _wait_ready(ready_file: str, proc: subprocess.Popen, what: str,
                timeout: float = 30.0) -> str:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(ready_file):
            with open(ready_file) as f:
                return f.read().strip()
        if proc.poll() is not None:
            raise RuntimeError(
                f"{what} exited with code {proc.returncode} during startup")
        time.sleep(0.02)
    raise TimeoutError(f"{what} did not become ready in {timeout}s")


class ServiceProcess:
    def __init__(self, name: str, proc: subprocess.Popen):
        self.name = name
        self.proc = proc

    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self, sig=signal.SIGKILL):
        if self.alive():
            try:
                os.killpg(os.getpgid(self.proc.pid), sig)
            except (ProcessLookupError, PermissionError):
                try:
                    self.proc.kill()
                except ProcessLookupError:
                    pass
        try:
            self.proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            pass


_TPU_ENV_KEYS = ("PALLAS_AXON_POOL_IPS",)


def strip_tpu_plugin_env(env: dict) -> dict:
    """Remove TPU-plugin activation vars so pure control-plane processes
    skip the expensive jax/PJRT import their sitecustomize would trigger
    (observed ~2s per process; catastrophic on few-core hosts).

    The stripped values are stashed in RAY_TPU_TPU_ENV so the raylet can
    hand them back to workers spawned for TPU-resource leases
    (restore_tpu_plugin_env) even though the raylet itself runs without
    them."""
    saved = {k: env[k] for k in _TPU_ENV_KEYS if k in env}
    if saved and "RAY_TPU_TPU_ENV" not in env:
        saved["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "")
        env["RAY_TPU_TPU_ENV"] = json.dumps(saved)
    for key in _TPU_ENV_KEYS:
        env.pop(key, None)
    # If the ambient env pins jax to the stripped plugin's platform, the
    # child would fail backend init ("axon not in known backends") — let
    # jax pick from what's actually registered there.
    if env.get("JAX_PLATFORMS", "").lower() not in ("", "cpu"):
        env["JAX_PLATFORMS"] = ""
    return env


def restore_tpu_plugin_env(env: dict) -> dict:
    """Give a TPU-designated worker back the plugin env that
    strip_tpu_plugin_env stashed on the raylet's way up."""
    saved = env.pop("RAY_TPU_TPU_ENV", None)
    if saved:
        vals = json.loads(saved)
        jax_platforms = vals.pop("JAX_PLATFORMS", "")
        if jax_platforms:
            env["JAX_PLATFORMS"] = jax_platforms
        else:
            env.pop("JAX_PLATFORMS", None)
        env.update(vals)
    return env


def _spawn(cmd: list[str], config: Config, name: str) -> ServiceProcess:
    env = strip_tpu_plugin_env(dict(os.environ))
    env.update(config.child_env())
    # `python -m ray_tpu...` children must import the package regardless
    # of the caller's cwd (the CLI runs from anywhere; without this,
    # `ray-tpu start` only worked inside the repo checkout)
    pkg_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    existing = env.get("PYTHONPATH")
    if pkg_root not in (existing or "").split(os.pathsep):
        env["PYTHONPATH"] = (pkg_root + os.pathsep + existing
                             if existing else pkg_root)
    proc = subprocess.Popen(
        cmd, env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        start_new_session=True)
    return ServiceProcess(name, proc)


def start_gcs(session_dir: str, config: Config, port: int = 0,
              shard_addresses: list[str] | None = None) -> tuple[ServiceProcess, str]:
    ready = os.path.join(session_dir, f"gcs_ready_{uuid.uuid4().hex[:6]}")
    log_file = os.path.join(session_dir, "logs", "gcs_server.log")
    cmd = [
        sys.executable, "-m", "ray_tpu.gcs.server",
        "--port", str(port),
        "--ready-file", ready,
        "--log-file", log_file,
    ]
    if config.gcs_persistence:
        cmd += ["--store-dir", os.path.join(session_dir, "gcs_store")]
    if shard_addresses:
        cmd += ["--shard-addresses", ",".join(shard_addresses)]
    cmd += ["--uds-dir", os.path.join(session_dir, "sock")]
    svc = _spawn(cmd, config, "gcs_server")
    actual_port = _wait_ready(ready, svc.proc, "gcs_server")
    return svc, f"{config.node_ip_address}:{actual_port}"


def start_gcs_shard(session_dir: str, config: Config, index: int,
                    port: int = 0) -> tuple[ServiceProcess, str]:
    """Spawn one GCS store shard (gcs/shard.py). A restart reuses the
    same port + journal dir, so client-side key routing never remaps."""
    ready = os.path.join(session_dir,
                         f"gcs_shard_ready_{index}_{uuid.uuid4().hex[:6]}")
    log_file = os.path.join(session_dir, "logs", f"gcs_shard_{index}.log")
    cmd = [
        sys.executable, "-m", "ray_tpu.gcs.shard",
        "--index", str(index),
        "--port", str(port),
        "--ready-file", ready,
        "--log-file", log_file,
    ]
    if config.gcs_persistence:
        cmd += ["--store-dir",
                os.path.join(session_dir, f"gcs_shard_{index}")]
    cmd += ["--uds-dir", os.path.join(session_dir, "sock")]
    svc = _spawn(cmd, config, f"gcs_shard_{index}")
    actual_port = _wait_ready(ready, svc.proc, f"gcs_shard_{index}")
    svc.shard_index = index
    svc.shard_port = int(actual_port)
    return svc, f"{config.node_ip_address}:{actual_port}"


def start_gcs_shards(session_dir: str,
                     config: Config) -> tuple[list[ServiceProcess], list[str]]:
    """Spawn the store-shard tier (config.gcs_shards processes; none at
    the default of 1 — single-GCS layout preserved)."""
    if config.gcs_shards <= 1:
        return [], []
    procs, addrs = [], []
    for i in range(config.gcs_shards):
        svc, addr = start_gcs_shard(session_dir, config, i)
        procs.append(svc)
        addrs.append(addr)
    return procs, addrs


def restart_gcs(session_dir: str, config: Config, gcs_address: str,
                shard_addresses: list[str] | None = None) -> ServiceProcess:
    """Bring a (crashed) GCS back on its old port against its persisted
    store, so clients' redial loops land on a server that remembers them
    (reference: test_gcs_fault_tolerance.py restart path)."""
    port = int(gcs_address.rsplit(":", 1)[1])
    svc, _addr = start_gcs(session_dir, config, port,
                           shard_addresses=shard_addresses)
    return svc


def start_raylet(session_dir: str, gcs_address: str, config: Config, *,
                 node_id: NodeID | None = None, num_cpus: float | None = None,
                 num_tpus: float = 0, resources: dict | None = None,
                 labels: dict | None = None, is_head=False,
                 store_root: str | None = None,
                 tpu_slice: dict | None = None,
                 topology: dict | None = None) -> tuple[ServiceProcess, str, NodeID, str]:
    node_id = node_id or NodeID.from_random()
    ready = os.path.join(session_dir, f"raylet_ready_{node_id.hex()[:8]}")
    log_file = os.path.join(session_dir, "logs",
                            f"raylet-{node_id.hex()[:8]}.log")
    if store_root is None:
        store_root = os.path.join(default_store_root(session_dir),
                                  node_id.hex()[:8])
    cmd = [
        sys.executable, "-m", "ray_tpu.raylet.raylet",
        "--gcs-address", gcs_address,
        "--session-dir", session_dir,
        "--store-root", store_root,
        "--node-id", node_id.hex(),
        "--resources", json.dumps(resources or {}),
        "--labels", json.dumps(labels or {}),
        "--ready-file", ready,
        "--log-file", log_file,
    ]
    if num_cpus is not None:
        cmd += ["--num-cpus", str(num_cpus)]
    if num_tpus:
        cmd += ["--num-tpus", str(num_tpus)]
    if tpu_slice:
        if hasattr(tpu_slice, "to_dict"):  # TpuSliceDescriptor
            tpu_slice = tpu_slice.to_dict()
        cmd += ["--tpu-slice", json.dumps(tpu_slice)]
    if topology:
        if hasattr(topology, "to_dict"):  # topology.TopologyCoord
            topology = topology.to_dict()
        cmd += ["--topology", json.dumps(topology)]
    if is_head:
        cmd += ["--is-head"]
    svc = _spawn(cmd, config, f"raylet-{node_id.hex()[:8]}")
    address = _wait_ready(ready, svc.proc, "raylet")
    return svc, address, node_id, store_root


class Node:
    """A local cluster head (GCS + one raylet) or an added worker node."""

    def __init__(self, *, config: Config, session_dir: str | None = None,
                 gcs_address: str | None = None, num_cpus=None, num_tpus=0,
                 resources=None, labels=None, tpu_slice=None):
        self.config = config
        self.session_dir = session_dir or new_session_dir()
        self.processes: list[ServiceProcess] = []
        self.is_head = gcs_address is None
        self.shard_addresses: list[str] = []
        if gcs_address is None:
            # Store-shard tier first (the director advertises their
            # addresses via get_shard_map); none at gcs_shards=1.
            shard_procs, self.shard_addresses = start_gcs_shards(
                self.session_dir, config)
            self.processes.extend(shard_procs)
            gcs_proc, gcs_address = start_gcs(
                self.session_dir, config, config.gcs_port,
                shard_addresses=self.shard_addresses)
            self.processes.append(gcs_proc)
        self.gcs_address = gcs_address
        raylet_proc, raylet_addr, node_id, store_root = start_raylet(
            self.session_dir, gcs_address, config,
            num_cpus=num_cpus, num_tpus=num_tpus, resources=resources,
            labels=labels, is_head=self.is_head, tpu_slice=tpu_slice)
        self.processes.append(raylet_proc)
        self.raylet_address = raylet_addr
        self.node_id = node_id
        self.store_root = store_root
        self._stopping = False
        atexit.register(self.kill_all_processes)
        if self.is_head and config.gcs_persistence and config.gcs_auto_restart:
            self._start_gcs_monitor()

    def _start_gcs_monitor(self):
        """Supervise the GCS: a crashed GCS is restarted on its old port
        against its persisted tables (the process-level analog of the
        reference's externally-supervised gcs_server + Redis durability;
        behavior: python/ray/tests/test_gcs_fault_tolerance.py)."""
        import threading

        def _watch():
            while not self._stopping:
                time.sleep(0.5)
                self._respawn_dead_shards()
                gcs = next((s for s in self.processes
                            if s.name == "gcs_server"), None)
                if gcs is None or self._stopping:
                    continue
                if not gcs.alive():
                    if self._stopping:
                        continue
                    logger.warning("GCS exited (rc=%s); restarting on %s",
                                   gcs.proc.returncode, self.gcs_address)
                    try:
                        new = restart_gcs(self.session_dir, self.config,
                                          self.gcs_address,
                                          shard_addresses=self.shard_addresses)
                    except Exception:
                        logger.exception("GCS restart failed")
                        continue
                    # Shutdown may have started while we were spawning
                    # (kill_all sets _stopping before killing): don't leak
                    # an orphan GCS outliving the driver.
                    if self._stopping:
                        new.kill()
                        continue
                    try:
                        self.processes[self.processes.index(gcs)] = new
                    except ValueError:
                        if self._stopping:
                            new.kill()
                        else:
                            self.processes.append(new)

        threading.Thread(target=_watch, name="gcs-monitor",
                         daemon=True).start()

    def _respawn_dead_shards(self):
        """Restart crashed store shards on their FIXED ports against
        their journals (journal replay restores the partition's tables;
        clients' per-shard ReconnectingConnections redial the same
        address, so key routing never remaps)."""
        for i, svc in enumerate(list(self.processes)):
            if (self._stopping or not svc.name.startswith("gcs_shard_")
                    or svc.alive()):
                continue
            index = getattr(svc, "shard_index", None)
            port = getattr(svc, "shard_port", 0)
            if index is None:
                continue
            logger.warning("GCS shard %d exited (rc=%s); restarting on "
                           "port %d", index, svc.proc.returncode, port)
            try:
                new, _addr = start_gcs_shard(self.session_dir, self.config,
                                             index, port=port)
            except Exception:
                logger.exception("GCS shard %d restart failed", index)
                continue
            if self._stopping:
                new.kill()
                continue
            try:
                self.processes[self.processes.index(svc)] = new
            except ValueError:
                if self._stopping:
                    new.kill()
                else:
                    self.processes.append(new)

    def kill_all_processes(self):
        self._stopping = True
        for svc in reversed(self.processes):
            svc.kill()
        self.processes.clear()

    def kill_gcs(self):
        """Fault injection: kill the GCS process (it will be auto-restarted
        by the monitor when gcs_auto_restart is on)."""
        for svc in self.processes:
            if svc.name == "gcs_server":
                svc.kill()

    def kill_gcs_shard(self, index: int = 0):
        """Fault injection: kill one store shard (auto-restarted by the
        monitor when gcs_auto_restart is on)."""
        for svc in self.processes:
            if getattr(svc, "shard_index", None) == index:
                svc.kill()

    def kill_raylet(self):
        """Fault injection: kill this node's raylet (reference test idiom:
        Node._kill_process_type, node.py:894)."""
        for svc in self.processes:
            if svc.name.startswith("raylet"):
                svc.kill()
