"""Topology plane: physical pod shape in the resource model (ROADMAP
item 2 / ISSUE 14 tentpole).

Every raylet derives a `TopologyCoord` — (slice id, torus coords, host
id) — and registers it into the GCS node table; placement, spillback
ordering, and locality tie-breaking all consume the same graded
`distance()` metric:

    same-process/host  <  same-slice-by-ICI-hops  <  cross-slice (DCN)

Coords come from (in priority order):
  1. an explicit coord dict (cluster_utils.add_node(topology=...), the
     scale-sim's spoofed raylets, raylet --topology);
  2. the `RAY_TPU_TOPOLOGY` env var (JSON: {"slice_id","coords","dims"})
     — how CPU clusters and sim processes synthesize a torus without
     TPU hardware;
  3. the node's TpuSliceDescriptor (util/accelerators.py): host_index
     laid onto a host grid factored from the slice's chip topology;
  4. none — the node has no coord; ICI_RING falls back to PACK (counted
     by `gcs.placement_topology_fallbacks_total`).

The placement *cost model* is a first-class pluggable object
(`PlacementCostModel.score(bundles, candidates) -> cost`, lower wins):
the default scores candidate ring orderings by torus circumference; a
registered alternative (by name, or a "module:attr" spec the GCS
imports — the Placeto direction, scored from the PR 6/13 metrics
history via `bind_context`) can replace the heuristic per request and
be A/B'd in the scale-sim harness.
"""

from __future__ import annotations

import dataclasses
import json
import os

# distance grading constants: any same-slice distance (1 + hops) must
# stay strictly below a cross-slice one — torus dims are physically
# bounded (largest public slice topologies are O(100) hops across), so
# a 4-digit base keeps the bands disjoint without float games.
D_SAME_PROCESS = 0.0
D_SAME_HOST = 0.5
D_CROSS_SLICE = 1.0e4

ENV_VAR = "RAY_TPU_TOPOLOGY"


@dataclasses.dataclass(frozen=True)
class TopologyCoord:
    """One node's position in the pod's physical shape.

    slice_id: opaque ICI-domain id (equal slice_id <=> ICI-reachable)
    coords:   this host's torus coordinates within the slice
    dims:     torus dimensions (wraparound lengths per axis)
    host_id:  node identity (node-id hex) — equal host_id <=> the same
              raylet/host, the shm domain
    """

    slice_id: str
    coords: tuple[int, ...]
    dims: tuple[int, ...]
    host_id: str = ""

    def to_dict(self) -> dict:
        return {"slice_id": self.slice_id, "coords": list(self.coords),
                "dims": list(self.dims), "host_id": self.host_id}

    @classmethod
    def from_dict(cls, d: dict | None) -> "TopologyCoord | None":
        if not d or not d.get("slice_id"):
            return None
        return cls(slice_id=str(d["slice_id"]),
                   coords=tuple(int(c) for c in d.get("coords") or ()),
                   dims=tuple(int(x) for x in d.get("dims") or ()),
                   host_id=str(d.get("host_id") or ""))


def _host_grid(num_hosts: int, topology: tuple[int, ...]) -> tuple[int, ...]:
    """Factor `num_hosts` into a grid roughly proportional to the chip
    topology (hosts tile the slice along its major axes). Greedy: peel
    the largest factor of num_hosts that divides each topology axis."""
    if num_hosts <= 1:
        return (1,)
    remaining = num_hosts
    grid = []
    for axis in topology:
        f = 1
        # largest divisor of `remaining` that fits the axis
        for cand in range(min(axis, remaining), 0, -1):
            if remaining % cand == 0:
                f = cand
                break
        grid.append(f)
        remaining //= f
        if remaining == 1:
            break
    if remaining > 1:
        grid.append(remaining)
    return tuple(grid)


def _coords_of_index(index: int, dims: tuple[int, ...]) -> tuple[int, ...]:
    """Row-major coords of a flat index in a grid."""
    out = []
    for d in reversed(dims):
        out.append(index % d)
        index //= d
    return tuple(reversed(out))


def derive_coord(*, node_id_hex: str, tpu_slice: dict | None = None,
                 labels: dict | None = None, explicit: dict | None = None,
                 env: dict | None = None) -> TopologyCoord | None:
    """Derive this node's TopologyCoord deterministically (no randomness:
    a restarted raylet must land on the same coord). Returns None when
    the node has no topology identity at all — placement then falls
    back and counts it, rather than inventing fake adjacency."""
    env = os.environ if env is None else env
    for source in (explicit, _parse_env(env), (labels or {}).get("topology")):
        coord = TopologyCoord.from_dict(source) if isinstance(source, dict) \
            else None
        if coord is not None:
            if not coord.host_id:
                coord = dataclasses.replace(coord, host_id=node_id_hex)
            return coord
    if tpu_slice and tpu_slice.get("slice_id"):
        topo = tuple(int(t) for t in tpu_slice.get("topology") or (1,))
        num_hosts = int(tpu_slice.get("num_hosts") or 1)
        grid = _host_grid(num_hosts, topo)
        return TopologyCoord(
            slice_id=str(tpu_slice["slice_id"]),
            coords=_coords_of_index(int(tpu_slice.get("host_index") or 0),
                                    grid),
            dims=grid, host_id=node_id_hex)
    return None


def _parse_env(env) -> dict | None:
    raw = env.get(ENV_VAR) if env else None
    if not raw:
        return None
    try:
        d = json.loads(raw)
        return d if isinstance(d, dict) else None
    except (ValueError, TypeError):
        return None


# ---------------------------------------------------------------------------
# distance
# ---------------------------------------------------------------------------


def torus_hops(a: tuple[int, ...], b: tuple[int, ...],
               dims: tuple[int, ...]) -> int:
    """ICI hop count between two coords on a wraparound torus (per-axis
    minimum of forward/backward walks, summed — the physical link
    count). Missing axes/dims degrade to non-wrapping manhattan."""
    hops = 0
    for i in range(max(len(a), len(b))):
        ai = a[i] if i < len(a) else 0
        bi = b[i] if i < len(b) else 0
        delta = abs(ai - bi)
        if i < len(dims) and dims[i] > 0:
            delta = min(delta, dims[i] - delta)
        hops += delta
    return hops


def distance(a: TopologyCoord | None, b: TopologyCoord | None) -> float:
    """Graded wire distance between two nodes: same host < same slice
    (1 + ICI hops) < cross-slice/DCN. Unknown coords read as cross-slice
    — an unlocatable node is never preferred over a located one."""
    if a is None or b is None:
        return D_CROSS_SLICE
    if a.host_id and a.host_id == b.host_id:
        return D_SAME_PROCESS if a.coords == b.coords else D_SAME_HOST
    if a.slice_id != b.slice_id:
        return D_CROSS_SLICE
    return 1.0 + torus_hops(a.coords, b.coords, a.dims or b.dims)


def nearest_first(origin: TopologyCoord | None, items: list,
                  key) -> list:
    """Stable-sort `items` by graded distance from `origin` (`key`
    extracts each item's TopologyCoord-or-None). Unknown origin leaves
    the order untouched — no coords, no opinion; equal distances keep
    their input order so callers' prior ranking survives as the
    tie-break within a band."""
    if origin is None:
        return list(items)
    return sorted(items, key=lambda it: distance(origin, key(it)))


# ---------------------------------------------------------------------------
# ring ordering (the ICI_RING strategy's geometry)
# ---------------------------------------------------------------------------


def snake_key(coord: TopologyCoord) -> tuple:
    """Boustrophedon (snake) ordering key over the torus grid:
    consecutive positions in snake order are ICI neighbors, so any
    contiguous window of located nodes forms a low-circumference ring.
    Odd-indexed rows reverse, per axis, like a pmap device raster."""
    c, dims = coord.coords, coord.dims
    key = []
    flip = False
    for i, v in enumerate(c):
        d = dims[i] if i < len(dims) else 0
        key.append((d - 1 - v) if (flip and d) else v)
        # parity of everything placed so far decides the next axis's
        # direction; approximate with this axis's parity
        flip = bool(v % 2) ^ flip
    return tuple(key)


def ring_circumference(coords: list[TopologyCoord | None]) -> float:
    """Total wire distance around the bundle ring, including the wrap
    hop rank N-1 -> rank 0 (what the collective ring transports pay per
    pass). Same-host consecutive ranks count 0."""
    n = len(coords)
    if n <= 1:
        return 0.0
    total = 0.0
    for i in range(n):
        a, b = coords[i], coords[(i + 1) % n]
        if a is not None and b is not None and a.host_id \
                and a.host_id == b.host_id:
            continue  # same host: the hop is shm/loopback, not a wire
        if a is None or b is None or a.slice_id != b.slice_id:
            total += D_CROSS_SLICE
        else:
            total += float(torus_hops(a.coords, b.coords,
                                      a.dims or b.dims))
    return total


# ---------------------------------------------------------------------------
# device-count -> (data, fsdp) mesh shapes (SNIPPETS [2]; public home:
# parallel/mesh.py re-exports — this module stays jax-free so the GCS
# placement scorer can share the table)
# ---------------------------------------------------------------------------

# Rationale (SNIPPETS [2]): fsdp=4 saturates the fastest ICI links (4
# chips per tray share them), data scales linearly with pod size; tiny
# slices stay pure-DP.
MESH_SHAPES: dict[int, tuple[int, int]] = {
    1: (1, 1),
    2: (2, 1),
    4: (4, 1),
    8: (8, 1),       # v5p-8: pure DP
    16: (8, 2),
    32: (8, 4),
    64: (16, 4),
    128: (32, 4),
    256: (64, 4),
    512: (128, 4),
    768: (192, 4),
}


def mesh_shape_for(num_devices: int) -> tuple[int, int]:
    """(data, fsdp) mesh shape for `num_devices` devices. Table sizes
    resolve directly; other counts synthesize per the same rationale —
    fsdp is the largest power-of-two divisor up to 4 (the ICI-saturating
    tray width), data fills the rest. Always satisfies
    data * fsdp == num_devices."""
    if num_devices < 1:
        raise ValueError(f"num_devices must be >= 1, got {num_devices}")
    shape = MESH_SHAPES.get(num_devices)
    if shape is not None:
        return shape
    fsdp = 4 if num_devices % 4 == 0 else (2 if num_devices % 2 == 0 else 1)
    return (num_devices // fsdp, fsdp)


# ---------------------------------------------------------------------------
# pluggable placement cost model (Placeto direction, PAPERS.md)
# ---------------------------------------------------------------------------


class PlacementCostModel:
    """Scores one candidate bundle->node assignment; the GCS picks the
    candidate with the LOWEST score. `candidates` is the assignment
    as a list of TopologyCoord-or-None, one per bundle rank, in rank
    order. Implementations may define `bind_context(ctx)` to receive
    {"metrics_history": ...} before a scoring round."""

    name = "base"

    def bind_context(self, ctx: dict) -> None:  # pragma: no cover - hook
        pass

    def score(self, bundles: list[dict],
              candidates: list) -> float:
        raise NotImplementedError


class RingDistanceCostModel(PlacementCostModel):
    """Default heuristic: the ring circumference of the assignment —
    minimal total ICI wire around consecutive ranks (incl. the wrap)."""

    name = "ring"

    def score(self, bundles, candidates) -> float:
        return ring_circumference(list(candidates))


class MetricsTrendCostModel(PlacementCostModel):
    """Metrics-history-scored model (the learned-policy socket, per
    Placeto): ring circumference plus a penalty per node whose raylet
    reported rising spillback counts over the bound history window —
    hot nodes repel new gangs. The GCS binds its live
    `metrics_history` rings before each scoring round; scored offline
    it degrades to the plain ring heuristic."""

    name = "metrics"

    def __init__(self, history: int = 30, penalty: float = 2.0):
        self._history = history
        self._penalty = penalty
        self._hot: set[str] = set()

    def bind_context(self, ctx: dict) -> None:
        hot: set[str] = set()
        for source, rings in (ctx.get("metrics_history") or {}).items():
            ring = rings.get("raylet.spillbacks_total")
            if not ring:
                continue
            window = list(ring)[-self._history:]
            if len(window) >= 2 and window[-1][1] > window[0][1]:
                # source is "<node8>/raylet": key by the node-id prefix
                hot.add(source.split("/", 1)[0])
        # coords registered with an EXPLICIT host_id never equal the
        # node-id hex the metric sources carry; the GCS passes its
        # node8 -> coord-host_id map so those nodes stay penalizable
        for n8, host_id in (ctx.get("node_hosts") or {}).items():
            if n8 in hot and host_id:
                hot.add(host_id)
        self._hot = hot

    def score(self, bundles, candidates) -> float:
        cost = ring_circumference(list(candidates))
        for c in candidates:
            if c is not None and (c.host_id in self._hot
                                  or c.host_id[:8] in self._hot):
                cost += self._penalty
        return cost


_COST_MODELS: dict[str, PlacementCostModel] = {}


def register_cost_model(model: PlacementCostModel,
                        name: str | None = None) -> None:
    """Register a model instance under `name` (defaults to model.name)
    in THIS process. The GCS resolves names through this registry, so
    in-process registration only reaches a GCS running in the same
    process (unit tests); cross-process, pass a "module:attr" spec
    instead — the GCS imports it."""
    _COST_MODELS[name or model.name] = model


def resolve_cost_model(spec: str | None) -> PlacementCostModel:
    """Resolve a cost-model spec: None/"" /"ring" -> the default ring
    heuristic; a registered name; or "module:attr" imported dynamically
    (attr may be an instance or a zero-arg class). Raises ValueError on
    an unknown spec — placement_group() surfaces it typed at creation,
    not as a silently-wrong placement."""
    if not spec or spec == "ring":
        return _DEFAULT_MODEL
    if spec in _COST_MODELS:
        return _COST_MODELS[spec]
    if ":" in spec:
        mod_name, _, attr = spec.partition(":")
        import importlib

        try:
            obj = getattr(importlib.import_module(mod_name), attr)
        except (ImportError, AttributeError) as e:
            raise ValueError(
                f"placement cost model {spec!r} failed to import: {e}")
        model = obj() if isinstance(obj, type) else obj
        if not hasattr(model, "score"):
            raise ValueError(
                f"placement cost model {spec!r} has no score()")
        _COST_MODELS[spec] = model
        return model
    raise ValueError(
        f"unknown placement cost model {spec!r}; registered: "
        f"{sorted(_COST_MODELS) + ['ring']} or a 'module:attr' spec")


_DEFAULT_MODEL = RingDistanceCostModel()
register_cost_model(_DEFAULT_MODEL)
register_cost_model(MetricsTrendCostModel())


# ---------------------------------------------------------------------------
# placement-derived collective transport
# ---------------------------------------------------------------------------


def transport_plan(pg_record: dict | None) -> dict | None:
    """Derive the collective transport tier a gang formed from this
    placement record should use — the placement GUARANTEED the
    geometry, so the group skips the unanimous probe round (shm
    rendezvous / device vote) entirely. Returns
    {"transport", "ranks": [{"node","slice_id","coords"}...],
     "ring_circumference"} or None when the record carries no topology
    plan (ad-hoc groups keep probing).

    Tier choice from the gang's geometry: every rank on one node ->
    shm; every rank in one ICI slice with TPU chips reserved AND a live
    TPU backend in the deriving process -> device; >2 ranks ->
    pipelined ring; else hub (a 2-rank ring degenerates). The backend
    check keeps a CPU box from pinning a tier the gang cannot build —
    that would demote at runtime (host_backend._demote_derived) and
    re-open the probe rounds the derivation exists to skip. A derived
    tier stays a SOFT pin: ranks whose runtime still cannot build it
    demote to auto routing in unison instead of raising like a
    user-forced transport."""
    if not pg_record or pg_record.get("state") != "CREATED":
        return None
    plan = pg_record.get("topology_plan")
    bundles = pg_record.get("bundles") or []
    if not plan or not bundles:
        return None
    coords = [TopologyCoord.from_dict(b.get("topology")) for b in bundles]
    nodes = [b.get("node_id") for b in bundles]
    ranks = [{"node": (n.hex()[:8] if isinstance(n, bytes) else str(n)),
              "slice_id": c.slice_id if c else None,
              "coords": list(c.coords) if c else None}
             for n, c in zip(nodes, coords)]
    world = len(bundles)
    if world > 1 and len(set(nodes)) == 1:
        transport = "shm"
    elif (world > 1 and all(c is not None for c in coords)
          and len({c.slice_id for c in coords}) == 1
          and all(_bundle_tpu(b) > 0 for b in bundles)
          and _tpu_backend_live()):
        # the same ICI geometry that admits the device tier admits the
        # fused-kernel refinement; PALLAS stays opt-in
        # (RAY_TPU_PALLAS_DERIVE=1) because a derived pin is still a
        # pin — ops under pallas_max_bytes run the kernel tier, larger
        # ones fall through to device — and the default AUTO route
        # already prefers pallas for small device arrays
        transport = ("pallas" if _pallas_derive_enabled() else "device")
    elif world > 2:
        transport = "ring"
    else:
        transport = "hub"
    return {"transport": transport, "ranks": ranks,
            "ring_circumference": ring_circumference(coords),
            "cost_model": pg_record.get("cost_model") or "ring",
            "strategy": pg_record.get("strategy")}


def _pallas_derive_enabled() -> bool:
    """Whether ICI_RING placement records derive the PALLAS tier
    instead of DEVICE (both soft pins; pallas additionally needs the
    kernel machinery importable in the deriving process)."""
    import os

    if os.environ.get("RAY_TPU_PALLAS_DERIVE", "0") in ("0", "false", ""):
        return False
    try:
        from ray_tpu.collective.backends.pallas_backend import (
            pallas_supported)

        return pallas_supported()
    except Exception:
        return False


def _tpu_backend_live() -> bool:
    """Whether THIS process runs a live TPU jax backend. Lazy import:
    the module stays importable in jax-free processes (GCS scorer)."""
    try:
        import jax

        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _bundle_tpu(bundle: dict) -> float:
    res = bundle.get("resources") or {}
    try:
        from ray_tpu._private.common import ResourceSet

        return ResourceSet.from_raw(res).get("TPU")
    except Exception:
        return float(res.get("TPU", 0) or 0)
