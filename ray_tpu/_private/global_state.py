"""Process-global singletons (the analog of python/ray/worker.py's global
`Worker` object, reference: worker.py:80)."""

from __future__ import annotations

_core_worker = None


def get_core_worker():
    return _core_worker


def set_core_worker(cw) -> None:
    global _core_worker
    _core_worker = cw


def require_core_worker():
    if _core_worker is None:
        raise RuntimeError(
            "ray_tpu has not been initialized; call ray_tpu.init() first")
    return _core_worker
