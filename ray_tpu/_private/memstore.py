"""In-process memory store: futures + small-object values.

The analog of the reference's CoreWorkerMemoryStore (reference:
src/ray/core_worker/store_provider/memory_store/memory_store.h:26): every
ObjectRef known to this process resolves here first. An entry is either
PENDING (a future — the producing task hasn't replied yet), a concrete
value, an error, or IN_PLASMA (sentinel meaning: fetch the bytes from the
shared-memory store).
"""

from __future__ import annotations

import threading
from typing import Any

from ray_tpu._private import failpoints as _fp
from ray_tpu._private.ids import ObjectID

IN_PLASMA = object()  # sentinel value


class _Entry:
    __slots__ = ("value", "is_exception", "ready", "callbacks")

    def __init__(self):
        self.value = None
        self.is_exception = False
        self.ready = False
        self.callbacks = None  # list[callable] | None, fired on ready


class MemoryStore:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._entries: dict[ObjectID, _Entry] = {}

    def open(self, object_id: ObjectID) -> None:
        """Ensure a pending entry exists (called at submit time)."""
        with self._lock:
            self._entries.setdefault(object_id, _Entry())

    def open_many(self, object_ids) -> None:
        """open() for a task's whole return set under one lock hop."""
        with self._lock:
            for object_id in object_ids:
                self._entries.setdefault(object_id, _Entry())

    def put(self, object_id: ObjectID, value: Any, is_exception=False) -> None:
        self.put_many([(object_id, value, is_exception)])

    def put_many(self, items) -> None:
        """put() for a batch of (object_id, value, is_exception) triples:
        one lock acquisition and one notify_all for a whole task reply
        (a serve batch reply is num_returns puts in a tight loop — the
        per-put lock/notify churn was measurable on the HTTP path)."""
        fired = []
        with self._cv:
            for object_id, value, is_exception in items:
                entry = self._entries.setdefault(object_id, _Entry())
                if entry.ready:
                    continue  # first write wins
                entry.value = value
                entry.is_exception = is_exception
                entry.ready = True
                if entry.callbacks:
                    fired.extend(entry.callbacks)
                entry.callbacks = None
            self._cv.notify_all()
        self._fire(fired)

    @staticmethod
    def _fire(callbacks) -> None:
        for cb in callbacks:  # outside the lock: callbacks may re-enter
            try:
                if _fp.ARMED:
                    # ready-callback seam: `raise` models one broken
                    # waiter (must not starve siblings or the putter);
                    # `exit` kills the process mid-delivery
                    _fp.fire_strict("memstore.ready_callback")
                cb()
            except Exception:
                # a broken waiter (cancelled future, dead loop) must not
                # starve sibling callbacks or abort the putter's loop
                # over a task's remaining returns
                import logging

                logging.getLogger("ray_tpu").exception(
                    "memstore ready-callback failed")

    def add_ready_callback(self, object_id: ObjectID, cb,
                           create: bool = True) -> bool:
        """Invoke cb() once the entry becomes ready — immediately if it
        already is. The async-get primitive: no thread parks per waiter
        (reference analog: memory_store.h GetAsync). A `delete` of a
        pending entry ALSO fires its callbacks (the waiter re-checks
        `get_if_ready`, sees not-found, and maps that to object loss), so
        an owner dropping an object can never strand a callback waiter.

        With create=False, a missing entry is NOT re-created (the caller
        races entry deletion and must not resurrect a released object);
        returns False and does not register in that case."""
        with self._lock:
            if create:
                entry = self._entries.setdefault(object_id, _Entry())
            else:
                entry = self._entries.get(object_id)
                if entry is None:
                    return False
            if not entry.ready:
                if entry.callbacks is None:
                    entry.callbacks = []
                entry.callbacks.append(cb)
                return True
        cb()
        return True

    def remove_ready_callback(self, object_id: ObjectID, cb) -> None:
        """Forget a pending ready-callback (waiter gave up — timeout or
        disconnected client); no-op if it already fired or never existed."""
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is not None and entry.callbacks:
                try:
                    entry.callbacks.remove(cb)
                except ValueError:
                    pass

    def put_in_plasma(self, object_id: ObjectID) -> None:
        self.put(object_id, IN_PLASMA)

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            entry = self._entries.get(object_id)
            return entry is not None and entry.ready

    def get_if_ready(self, object_id: ObjectID):
        """Returns (found, value, is_exception)."""
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None or not entry.ready:
                return False, None, False
            return True, entry.value, entry.is_exception

    def wait(self, object_ids, num_returns: int, timeout: float | None):
        """Block until num_returns of object_ids are ready. Returns ready set."""
        deadline = None
        if timeout is not None:
            deadline = threading.TIMEOUT_MAX if timeout < 0 else timeout

        def ready_set():
            return {
                oid
                for oid in object_ids
                if (e := self._entries.get(oid)) is not None and e.ready
            }

        import time

        end = time.monotonic() + deadline if deadline is not None else None
        with self._cv:
            while True:
                ready = ready_set()
                if len(ready) >= num_returns:
                    return ready
                remaining = None
                if end is not None:
                    remaining = end - time.monotonic()
                    if remaining <= 0:
                        return ready
                self._cv.wait(remaining)

    def reset(self, object_id: ObjectID) -> None:
        """Return an entry to PENDING (object reconstruction: the lost
        value is being recomputed, so `put` must win again)."""
        with self._lock:
            old = self._entries.get(object_id)
            fresh = _Entry()
            if old is not None and not old.ready:
                fresh.callbacks = old.callbacks  # waiters follow the redo
            self._entries[object_id] = fresh

    def delete(self, object_id: ObjectID) -> None:
        with self._lock:
            entry = self._entries.pop(object_id, None)
            fired = entry.callbacks if entry is not None else None
            if entry is not None:
                entry.callbacks = None
        if fired:
            self._fire(fired)

    def size(self) -> int:
        with self._lock:
            return len(self._entries)
