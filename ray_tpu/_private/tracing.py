"""Distributed tracing — causally-linked spans across every hop.

A compact trace context `(trace_id, span_id, parent_span_id, sampled)`
is minted at every entry point (driver `.remote()`, Serve HTTP ingress,
collective op, bulk object pull) and threaded through the existing
seams: task spec -> lease request -> raylet grant -> worker exec ->
reply, router -> replica, pull request -> chunk stream. Spans record
into the process's bounded ProfileBuffer (profiling.py) alongside plain
profile events, flush in batches to the GCS (profile table + trace
table), and export as Perfetto/chrome-trace JSON with cross-process
flow arrows (reference analog: the OpenTelemetry tracing hooks in
python/ray/util/tracing — here head-sampled and zero-dependency).

Head sampling: `RAY_TPU_TRACE_SAMPLE` (default 1%) at process start, or
live cluster-wide via `ray_tpu.set_trace_sampling(rate)` — the rate
rides the internal KV (KV_KEY) + pubsub (CHANNEL), exactly like the
failpoints arming plane. Propagated contexts are always honored: the
sampling decision is made once, at the trace root.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import random
import threading

KV_KEY = "ray_tpu:trace_sample"
CHANNEL = "trace_config"

_DEFAULT_RATE = 0.01


def _env_rate() -> float:
    raw = os.environ.get("RAY_TPU_TRACE_SAMPLE", "")
    if not raw:
        return _DEFAULT_RATE
    try:
        return min(1.0, max(0.0, float(raw)))
    except ValueError:
        return _DEFAULT_RATE


_rate = _env_rate()
_rng = random.Random()
_lock = threading.Lock()
_buffer = None  # ProfileBuffer this process records spans into

# Ambient context: set around task execution / request handling so any
# nested entry point (a task submitted from inside a traced task, a
# collective op inside a traced replica call) joins the same tree.
_CTX: contextvars.ContextVar = contextvars.ContextVar(
    "ray_tpu_trace_ctx", default=None)


class TraceContext:
    """One node of a trace tree. Only sampled contexts exist — an
    unsampled entry point yields None everywhere, so the unsampled hot
    path carries no per-call state at all."""

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id: bytes, span_id: bytes,
                 parent_id: bytes | None = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id

    def __repr__(self):
        return (f"TraceContext({self.trace_id.hex()}, {self.span_id.hex()},"
                f" parent={self.parent_id.hex() if self.parent_id else None})")


def sample_rate() -> float:
    return _rate


def set_sample_rate(rate: float) -> None:
    global _rate
    _rate = min(1.0, max(0.0, float(rate)))


def apply_kv_value(value) -> None:
    """Apply a live override published through the GCS KV/pubsub (the
    value is the rate as a string, e.g. b"1.0")."""
    if value is None:
        return
    if isinstance(value, bytes):
        value = value.decode(errors="replace")
    try:
        set_sample_rate(float(value))
    except (TypeError, ValueError):
        pass


def bind_buffer(buffer) -> None:
    """Bind this process's ProfileBuffer (core worker / raylet call this
    at startup) so spans land in the same flush pipeline as profile
    events."""
    global _buffer
    _buffer = buffer


def _get_buffer():
    global _buffer
    if _buffer is None:
        with _lock:
            if _buffer is None:
                from ray_tpu._private import failpoints as _fp
                from ray_tpu._private.profiling import ProfileBuffer

                _buffer = ProfileBuffer(_fp.get_role() or "process")
    return _buffer


def new_context() -> TraceContext:
    """Fresh root context (unconditional — callers wanting head sampling
    use maybe_trace)."""
    return TraceContext(os.urandom(8), os.urandom(8))


def child(ctx: TraceContext) -> TraceContext:
    return TraceContext(ctx.trace_id, os.urandom(8), ctx.span_id)


def maybe_trace() -> TraceContext | None:
    """Entry-point mint: continue the ambient trace when one is active
    (nested submit, traced request handler), else head-sample a fresh
    root at the current rate. Returns None when not sampled."""
    cur = _CTX.get()
    if cur is not None:
        return child(cur)
    if _rate <= 0.0 or _rng.random() >= _rate:
        return None
    return new_context()


# --- wire format -----------------------------------------------------------
# msgpack-plain [trace_id, span_id, parent_span_id, sampled]: span_id is
# the SENDER's span — the receiver records its spans as children of it.

def to_wire(ctx: TraceContext) -> list:
    return [ctx.trace_id, ctx.span_id, ctx.parent_id or b"", 1]


def from_wire(wire) -> TraceContext | None:
    if not wire:
        return None
    try:
        trace_id, span_id, parent, sampled = wire
    except (TypeError, ValueError):
        return None
    if not sampled:
        return None
    return TraceContext(bytes(trace_id), bytes(span_id),
                        bytes(parent) or None)


# --- ambient context -------------------------------------------------------

def current() -> TraceContext | None:
    return _CTX.get()


def current_id() -> str | None:
    """Hex trace id of the ambient context (the histogram-exemplar
    form), or None when the current call is unsampled."""
    ctx = _CTX.get()
    return ctx.trace_id.hex() if ctx is not None else None


def exemplar_of(ctx: TraceContext | None) -> str | None:
    """Hex trace id of `ctx` for Histogram.observe(exemplar=...)."""
    return ctx.trace_id.hex() if ctx is not None else None


def push(ctx: TraceContext | None):
    """Set the ambient context (even to None — execution scopes shadow
    any caller-thread leftovers); returns the reset token."""
    return _CTX.set(ctx)


def pop(token) -> None:
    try:
        _CTX.reset(token)
    except ValueError:
        pass  # token from another context (executor-pool reuse)


@contextlib.contextmanager
def use(ctx: TraceContext | None):
    token = _CTX.set(ctx)
    try:
        yield ctx
    finally:
        pop(token)


# --- span recording --------------------------------------------------------

def record_span(name: str, start: float, end: float,
                ctx: TraceContext | None, extra: dict | None = None) -> None:
    """Record one span into the bound ProfileBuffer. With ctx=None this
    degrades to a plain profile event (no trace linkage) — used by the
    unconditional task-execution event."""
    fields = dict(extra) if extra else {}
    if ctx is not None:
        fields["tid"] = ctx.trace_id.hex()
        fields["sid"] = ctx.span_id.hex()
        if ctx.parent_id:
            fields["psid"] = ctx.parent_id.hex()
    _get_buffer().record(name, start, end, fields)


@contextlib.contextmanager
def span(name: str, ctx: TraceContext | None, extra: dict | None = None,
         ambient: bool = False):
    """Context manager recording `name` over the with-block when ctx is
    not None; `ambient=True` additionally makes ctx the current context
    inside the block (so nested entry points join the tree)."""
    import time

    if ctx is None:
        yield None
        return
    token = _CTX.set(ctx) if ambient else None
    start = time.time()
    try:
        yield ctx
    finally:
        record_span(name, start, time.time(), ctx, extra)
        if token is not None:
            pop(token)
