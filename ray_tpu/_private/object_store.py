"""Node-local shared-memory object store (the plasma equivalent).

Capability parity with the reference's plasma store (reference:
src/ray/object_manager/plasma/store.h:53, client.h) with a TPU-host-native
design instead of a store server process + fd passing: each sealed object is
one file under /dev/shm/<session>/objects, created as `<hex>.build`, written
through mmap, and sealed by an atomic rename. Any process on the node mmaps
sealed objects read-only — creation and reads are zero-copy and lock-free;
there is no store server in the data path at all. Capacity accounting,
eviction, and spill-to-disk live in the raylet's LocalObjectManager
(reference: src/ray/raylet/local_object_manager.h), which is the only
deleter. A C++ slab-allocator backend can replace the file-per-object layout
behind this same interface (see native/store).
"""

from __future__ import annotations

import mmap
import os
import tempfile

from ray_tpu._private.ids import ObjectID


class ObjectBuffer:
    """A writable or read-only mmap view of one object."""

    def __init__(self, path: str, size: int, create: bool):
        self.path = path
        self.size = size
        if create:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
            try:
                os.ftruncate(fd, size)
                self._mmap = mmap.mmap(fd, size) if size else None
            finally:
                os.close(fd)
        else:
            fd = os.open(path, os.O_RDONLY)
            try:
                size = os.fstat(fd).st_size
                self.size = size
                self._mmap = (
                    mmap.mmap(fd, size, prot=mmap.PROT_READ) if size else None
                )
            finally:
                os.close(fd)
        self.view = memoryview(self._mmap) if self._mmap else memoryview(b"")

    def close(self):
        try:
            self.view.release()
            if self._mmap is not None:
                self._mmap.close()
        except (BufferError, ValueError):
            # Still-referenced views keep the mapping alive; the OS reclaims
            # on process exit.
            pass


class LocalObjectStore:
    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, object_id: ObjectID) -> str:
        return os.path.join(self.root, object_id.hex())

    def create(self, object_id: ObjectID, size: int) -> ObjectBuffer:
        return ObjectBuffer(self._path(object_id) + ".build", size, create=True)

    def seal(self, object_id: ObjectID) -> None:
        os.rename(self._path(object_id) + ".build", self._path(object_id))

    def abort(self, object_id: ObjectID) -> None:
        try:
            os.unlink(self._path(object_id) + ".build")
        except FileNotFoundError:
            pass

    def contains(self, object_id: ObjectID) -> bool:
        return os.path.exists(self._path(object_id))

    def get(self, object_id: ObjectID) -> ObjectBuffer | None:
        try:
            return ObjectBuffer(self._path(object_id), 0, create=False)
        except FileNotFoundError:
            return None

    # files-backend reads are already zero-copy mmaps with explicit
    # close(); the native backend's get_raw contract maps onto get()
    get_raw = get

    def size_of(self, object_id: ObjectID) -> int:
        return os.stat(self._path(object_id)).st_size

    def delete(self, object_id: ObjectID) -> int:
        """Returns freed bytes."""
        try:
            size = self.size_of(object_id)
            os.unlink(self._path(object_id))
            return size
        except FileNotFoundError:
            return 0

    def put_serialized(self, object_id: ObjectID, header: bytes,
                       buffers: list[memoryview]) -> int:
        """Write header+buffers and seal. Returns total size.

        Uses one writev() straight from the caller's buffers instead of
        an mmap write: tmpfs pages are then allocated inside the kernel
        in one pass rather than via ~2.5k user-space page faults per
        10MB (measured ~1.5x faster), and nothing is copied in user
        space."""
        total = len(header) + sum(b.nbytes for b in buffers)
        path = self._path(object_id) + ".build"
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o600)
        try:
            iov = [memoryview(header)]
            iov += [b.cast("B") if (b.ndim != 1 or b.format != "B") else b
                    for b in buffers]
            iov = [b for b in iov if b.nbytes]
            written = 0
            # IOV_MAX (1024 on Linux) caps vectors per writev; objects
            # with thousands of out-of-band buffers go in slices.
            iov_max = 1024
            while iov:
                n = os.writev(fd, iov[:iov_max])
                written += n
                if written >= total:
                    break
                # partial write: drop fully-written buffers, slice the rest
                while iov and n >= iov[0].nbytes:
                    n -= iov[0].nbytes
                    iov.pop(0)
                if iov and n:
                    iov[0] = iov[0][n:]
            os.close(fd)
            self.seal(object_id)
        except BaseException:
            try:
                os.close(fd)
            except OSError:
                pass
            self.abort(object_id)
            raise
        return total

    def put_bytes(self, object_id: ObjectID, data: bytes | memoryview) -> int:
        return self.put_serialized(object_id, b"", [memoryview(data).cast("B")])

    def list_objects(self) -> list[ObjectID]:
        out = []
        for name in os.listdir(self.root):
            if not name.endswith(".build"):
                try:
                    out.append(ObjectID.from_hex(name))
                except ValueError:
                    pass
        return out


def make_store(root: str, config=None):
    """Backend factory: the C++ shared-arena slab store (native/store,
    the default — pinned zero-copy reads make deletion safe) or the
    python file-per-object store ("files", also the automatic fallback
    when no C++ toolchain is present). Raylet and workers on one node
    must agree; the fallback is deterministic per box (same compiler
    probe), so they do."""
    backend = "native"
    if config is not None:
        backend = getattr(config, "object_store_backend", "native")
    if backend == "native":
        from ray_tpu.native.store import native_store_available

        if native_store_available():
            # Any failure past this point must be FATAL, not a fallback:
            # a per-process fallback would split one node across two
            # incompatible backends (raylet arena vs worker files) and
            # every cross-process get would hang.
            from ray_tpu.native.store import NativeObjectStore

            capacity = getattr(config, "object_store_memory", 1 << 30)
            return NativeObjectStore(root, capacity=capacity)
        import logging

        logging.getLogger("ray_tpu").warning(
            "native object store unavailable (no C++ toolchain / build "
            "failure — deterministic per box); using the "
            "file-per-object backend")
    return LocalObjectStore(root)


def default_store_root(session_dir: str) -> str:
    """Prefer /dev/shm (true shared memory) when available."""
    shm = "/dev/shm"
    if os.path.isdir(shm) and os.access(shm, os.W_OK):
        base = os.path.join(shm, "ray_tpu", os.path.basename(session_dir))
    else:  # pragma: no cover
        base = os.path.join(tempfile.gettempdir(), "ray_tpu_store",
                            os.path.basename(session_dir))
    return os.path.join(base, "objects")
