"""Task/event profiling -> cluster timeline (reference:
src/ray/core_worker/profiling.h:28 ProfileEvent batches pushed to the GCS
profile table; python/ray/state.py:946 timeline() chrome-trace export).

Workers record spans into a bounded local buffer; the core worker flushes
batches to the GCS, and `ray_tpu.timeline()` renders everything as a
chrome://tracing / Perfetto JSON document. Events carrying trace ids
(tracing.py `tid`/`sid`/`psid` extra fields) additionally land in the
GCS trace table and export with cross-process flow arrows."""

from __future__ import annotations

import collections
import contextlib
import os
import threading
import time

from ray_tpu._private import stats as _stats

# Flush failures (GCS unreachable) requeue drained events locally; only
# events evicted by the deque bound are actually lost — and counted here
# instead of disappearing invisibly.
M_EVENTS_DROPPED = _stats.Count(
    "profiling.events_dropped_total",
    "profile/trace events dropped by the local buffer bound")

# jit-compile observability (drift-gated): every recompile the runtime
# can see — _DeviceOps cache fills, the paged-KV jax update, Trainer
# step shape changes — counts here and lands as a `jax.compile` span,
# so a recompile storm reads as a flamegraph band + a rising
# jax.compiles_total rate + a doctor finding instead of a mystery stall.
M_COMPILES = _stats.Count(
    "jax.compiles_total",
    "jit compile events observed at the runtime's compile seams "
    "(_DeviceOps cache fill, KV-cache jax update, Trainer step)")
M_COMPILE_S = _stats.Histogram(
    "jax.compile_s", _stats.COMPILE_BOUNDARIES_S,
    "wall seconds per observed jit compile (first dispatch of a new "
    "shape class — compile + first execution)")

# recent-compile window for debug_state / the stall doctor's
# compile-storm finding (bounded ring; pruned on read)
COMPILE_RECENT_WINDOW_S = 60.0
_compile_recent: collections.deque = collections.deque(maxlen=256)
_compile_lock = threading.Lock()


def record_compile(key: str, start: float, end: float) -> None:
    """Record one observed jit compile: metrics + a `jax.compile` span
    (joining the ambient trace when one is active) + the recent window
    the doctor reads."""
    from ray_tpu._private import tracing

    seconds = max(0.0, end - start)
    M_COMPILES.inc()
    M_COMPILE_S.observe(seconds)
    with _compile_lock:
        _compile_recent.append((end, seconds, key))
    tracing.record_span("jax.compile", start, end, tracing.current(),
                        {"name": f"jax.compile {key}", "key": key,
                         "compile_s": round(seconds, 4)})


def compile_state() -> dict:
    """Compile activity summary for debug_state snapshots: total count
    plus the last-60s window (count, wall seconds, last key) — the
    stall doctor's compile-storm signal."""
    now = time.time()
    with _compile_lock:
        recent = [(ts, s, k) for ts, s, k in _compile_recent
                  if now - ts <= COMPILE_RECENT_WINDOW_S]
        last = _compile_recent[-1] if _compile_recent else None
    return {
        "total": int(M_COMPILES.snapshot()["value"]),
        "recent_60s": len(recent),
        "recent_s": round(sum(s for _, s, _ in recent), 4),
        "last_key": last[2] if last else "",
        "last_age_s": round(now - last[0], 3) if last else None,
    }


class CompileProbe:
    """First-dispatch-per-shape-class timer for jitted callables.

    jit recompiles exactly when the traced shape class changes, so the
    first dispatch of a new key carries the compile; `watch(key)` times
    that first call and records it via record_compile (later calls of a
    seen key cost one set lookup). The measured time includes the first
    execution — the standard proxy when the runtime can't hook XLA
    directly."""

    def __init__(self, name: str):
        self.name = name
        self._seen: set = set()
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def watch(self, *key_parts):
        key = ":".join(str(p) for p in key_parts)
        with self._lock:
            fresh = key not in self._seen
            if fresh:
                self._seen.add(key)
        if not fresh:
            yield False
            return
        t0 = time.time()
        try:
            yield True
        except BaseException:
            # a failed first dispatch (transient OOM, interrupt) did
            # not prove a compile: un-mark the key so the retry is
            # timed, and record nothing for the failed attempt
            with self._lock:
                self._seen.discard(key)
            raise
        record_compile(f"{self.name}:{key}", t0, time.time())


def shape_class(batch) -> str:
    """Stable shape-class key for a (possibly nested) batch of arrays —
    the thing whose change forces a jit recompile."""
    shapes: list[str] = []

    def walk(x):
        shape = getattr(x, "shape", None)
        if shape is not None:
            shapes.append("x".join(map(str, shape)) or "scalar")
        elif isinstance(x, dict):
            for k in sorted(x):
                walk(x[k])
        elif isinstance(x, (list, tuple)):
            for v in x:
                walk(v)

    walk(batch)
    return ",".join(shapes) or "none"


class ProfileBuffer:
    def __init__(self, component_type: str, maxlen: int = 20_000):
        self.component_type = component_type
        self.component_id = os.getpid()
        self._events: collections.deque = collections.deque(maxlen=maxlen)
        self._lock = threading.Lock()

    def record(self, event_type: str, start: float, end: float,
               extra: dict | None = None):
        with self._lock:
            if len(self._events) == self._events.maxlen:
                M_EVENTS_DROPPED.inc()
            self._events.append({
                "event_type": event_type,
                "start_time": start,
                "end_time": end,
                "extra_data": extra or {},
            })

    def drain(self) -> list[dict]:
        with self._lock:
            out = list(self._events)
            self._events.clear()
        return out

    def requeue(self, events: list[dict]) -> int:
        """Put drained-but-unflushed events back at the FRONT (a failed
        GCS flush retries them on the next cycle). Keeps the newest
        events when they no longer all fit; returns how many were
        dropped (also counted in profiling.events_dropped_total)."""
        if not events:
            return 0
        with self._lock:
            space = self._events.maxlen - len(self._events)
            dropped = max(0, len(events) - space)
            if dropped:
                M_EVENTS_DROPPED.inc(dropped)
                events = events[dropped:]
            self._events.extendleft(reversed(events))
        return dropped

    def __len__(self):
        with self._lock:
            return len(self._events)

    def profile(self, event_type: str, extra: dict | None = None):
        return _Span(self, event_type, extra)


class _Span:
    def __init__(self, buf: ProfileBuffer, event_type: str, extra):
        self._buf = buf
        self._event_type = event_type
        self._extra = extra

    def __enter__(self):
        self._start = time.time()
        return self

    def __exit__(self, *exc):
        self._buf.record(self._event_type, self._start, time.time(),
                         self._extra)
        return False


def to_chrome_trace(events: list[dict], flow: bool = True) -> list[dict]:
    """GCS profile-table rows -> chrome-trace 'X' (complete) events
    (reference: state.py:946 timeline). Span events (tracing.py: extra
    `sid`/`psid`) additionally get flow arrows ('s'/'f' pairs keyed by
    the child span id) so Perfetto draws the cross-process tree."""
    trace = []
    by_sid: dict[str, dict] = {}
    for batch in events:
        pid = f"{batch['component_type']} {batch.get('node_id', b'').hex()[:8] if isinstance(batch.get('node_id'), bytes) else ''}".strip()
        for ev in batch["events"]:
            extra = ev.get("extra_data", {})
            tev = {
                "cat": ev["event_type"],
                "name": extra.get("name", ev["event_type"]),
                "ph": "X",
                "ts": ev["start_time"] * 1e6,
                "dur": (ev["end_time"] - ev["start_time"]) * 1e6,
                "pid": pid,
                "tid": batch["component_id"],
                "args": extra,
            }
            trace.append(tev)
            sid = extra.get("sid")
            if sid:
                by_sid[sid] = tev
    if flow:
        links = []
        for tev in trace:
            sid = tev["args"].get("sid")
            parent = by_sid.get(tev["args"].get("psid", ""))
            if not sid or parent is None or parent is tev:
                continue
            # anchor the flow start inside the parent slice (chrome
            # binds flow events to the enclosing slice by timestamp)
            start_ts = min(max(tev["ts"], parent["ts"]),
                           parent["ts"] + parent["dur"])
            links.append({"ph": "s", "cat": "trace", "name": "span",
                          "id": sid, "pid": parent["pid"],
                          "tid": parent["tid"], "ts": start_ts})
            links.append({"ph": "f", "bp": "e", "cat": "trace",
                          "name": "span", "id": sid, "pid": tev["pid"],
                          "tid": tev["tid"], "ts": tev["ts"]})
        trace.extend(links)
    return trace


def spans_to_chrome_trace(rows: list[dict], flow: bool = True) -> list[dict]:
    """Flat GCS trace-TABLE rows (get_trace_spans) -> chrome-trace JSON:
    regroups rows into per-process pseudo-batches and reuses
    to_chrome_trace, so `ray-tpu trace` / `/api/trace` render one
    trace's cross-process tree with the same flow arrows as the full
    timeline."""
    batches: dict[tuple, dict] = {}
    for r in rows:
        nid = r.get("node_id")
        key = (r["component_type"], r["component_id"],
               nid if isinstance(nid, bytes) else b"")
        b = batches.get(key)
        if b is None:
            b = batches[key] = {"component_type": r["component_type"],
                                "component_id": r["component_id"],
                                "node_id": nid, "events": []}
        b["events"].append({"event_type": r["event_type"],
                            "start_time": r["start_time"],
                            "end_time": r["end_time"],
                            "extra_data": r.get("extra_data", {})})
    return to_chrome_trace(list(batches.values()), flow=flow)
