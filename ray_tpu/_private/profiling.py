"""Task/event profiling -> cluster timeline (reference:
src/ray/core_worker/profiling.h:28 ProfileEvent batches pushed to the GCS
profile table; python/ray/state.py:946 timeline() chrome-trace export).

Workers record spans into a bounded local buffer; the core worker flushes
batches to the GCS, and `ray_tpu.timeline()` renders everything as a
chrome://tracing / Perfetto JSON document."""

from __future__ import annotations

import collections
import os
import threading
import time


class ProfileBuffer:
    def __init__(self, component_type: str, maxlen: int = 20_000):
        self.component_type = component_type
        self.component_id = os.getpid()
        self._events: collections.deque = collections.deque(maxlen=maxlen)
        self._lock = threading.Lock()

    def record(self, event_type: str, start: float, end: float,
               extra: dict | None = None):
        with self._lock:
            self._events.append({
                "event_type": event_type,
                "start_time": start,
                "end_time": end,
                "extra_data": extra or {},
            })

    def drain(self) -> list[dict]:
        with self._lock:
            out = list(self._events)
            self._events.clear()
        return out

    def profile(self, event_type: str, extra: dict | None = None):
        return _Span(self, event_type, extra)


class _Span:
    def __init__(self, buf: ProfileBuffer, event_type: str, extra):
        self._buf = buf
        self._event_type = event_type
        self._extra = extra

    def __enter__(self):
        self._start = time.time()
        return self

    def __exit__(self, *exc):
        self._buf.record(self._event_type, self._start, time.time(),
                         self._extra)
        return False


def to_chrome_trace(events: list[dict]) -> list[dict]:
    """GCS profile-table rows -> chrome-trace 'X' (complete) events
    (reference: state.py:946 timeline)."""
    trace = []
    for batch in events:
        pid = f"{batch['component_type']} {batch.get('node_id', b'').hex()[:8] if isinstance(batch.get('node_id'), bytes) else ''}".strip()
        for ev in batch["events"]:
            trace.append({
                "cat": ev["event_type"],
                "name": ev.get("extra_data", {}).get(
                    "name", ev["event_type"]),
                "ph": "X",
                "ts": ev["start_time"] * 1e6,
                "dur": (ev["end_time"] - ev["start_time"]) * 1e6,
                "pid": pid,
                "tid": batch["component_id"],
                "args": ev.get("extra_data", {}),
            })
    return trace
