"""Task/event profiling -> cluster timeline (reference:
src/ray/core_worker/profiling.h:28 ProfileEvent batches pushed to the GCS
profile table; python/ray/state.py:946 timeline() chrome-trace export).

Workers record spans into a bounded local buffer; the core worker flushes
batches to the GCS, and `ray_tpu.timeline()` renders everything as a
chrome://tracing / Perfetto JSON document. Events carrying trace ids
(tracing.py `tid`/`sid`/`psid` extra fields) additionally land in the
GCS trace table and export with cross-process flow arrows."""

from __future__ import annotations

import collections
import os
import threading
import time

from ray_tpu._private import stats as _stats

# Flush failures (GCS unreachable) requeue drained events locally; only
# events evicted by the deque bound are actually lost — and counted here
# instead of disappearing invisibly.
M_EVENTS_DROPPED = _stats.Count(
    "profiling.events_dropped_total",
    "profile/trace events dropped by the local buffer bound")


class ProfileBuffer:
    def __init__(self, component_type: str, maxlen: int = 20_000):
        self.component_type = component_type
        self.component_id = os.getpid()
        self._events: collections.deque = collections.deque(maxlen=maxlen)
        self._lock = threading.Lock()

    def record(self, event_type: str, start: float, end: float,
               extra: dict | None = None):
        with self._lock:
            if len(self._events) == self._events.maxlen:
                M_EVENTS_DROPPED.inc()
            self._events.append({
                "event_type": event_type,
                "start_time": start,
                "end_time": end,
                "extra_data": extra or {},
            })

    def drain(self) -> list[dict]:
        with self._lock:
            out = list(self._events)
            self._events.clear()
        return out

    def requeue(self, events: list[dict]) -> int:
        """Put drained-but-unflushed events back at the FRONT (a failed
        GCS flush retries them on the next cycle). Keeps the newest
        events when they no longer all fit; returns how many were
        dropped (also counted in profiling.events_dropped_total)."""
        if not events:
            return 0
        with self._lock:
            space = self._events.maxlen - len(self._events)
            dropped = max(0, len(events) - space)
            if dropped:
                M_EVENTS_DROPPED.inc(dropped)
                events = events[dropped:]
            self._events.extendleft(reversed(events))
        return dropped

    def __len__(self):
        with self._lock:
            return len(self._events)

    def profile(self, event_type: str, extra: dict | None = None):
        return _Span(self, event_type, extra)


class _Span:
    def __init__(self, buf: ProfileBuffer, event_type: str, extra):
        self._buf = buf
        self._event_type = event_type
        self._extra = extra

    def __enter__(self):
        self._start = time.time()
        return self

    def __exit__(self, *exc):
        self._buf.record(self._event_type, self._start, time.time(),
                         self._extra)
        return False


def to_chrome_trace(events: list[dict], flow: bool = True) -> list[dict]:
    """GCS profile-table rows -> chrome-trace 'X' (complete) events
    (reference: state.py:946 timeline). Span events (tracing.py: extra
    `sid`/`psid`) additionally get flow arrows ('s'/'f' pairs keyed by
    the child span id) so Perfetto draws the cross-process tree."""
    trace = []
    by_sid: dict[str, dict] = {}
    for batch in events:
        pid = f"{batch['component_type']} {batch.get('node_id', b'').hex()[:8] if isinstance(batch.get('node_id'), bytes) else ''}".strip()
        for ev in batch["events"]:
            extra = ev.get("extra_data", {})
            tev = {
                "cat": ev["event_type"],
                "name": extra.get("name", ev["event_type"]),
                "ph": "X",
                "ts": ev["start_time"] * 1e6,
                "dur": (ev["end_time"] - ev["start_time"]) * 1e6,
                "pid": pid,
                "tid": batch["component_id"],
                "args": extra,
            }
            trace.append(tev)
            sid = extra.get("sid")
            if sid:
                by_sid[sid] = tev
    if flow:
        links = []
        for tev in trace:
            sid = tev["args"].get("sid")
            parent = by_sid.get(tev["args"].get("psid", ""))
            if not sid or parent is None or parent is tev:
                continue
            # anchor the flow start inside the parent slice (chrome
            # binds flow events to the enclosing slice by timestamp)
            start_ts = min(max(tev["ts"], parent["ts"]),
                           parent["ts"] + parent["dur"])
            links.append({"ph": "s", "cat": "trace", "name": "span",
                          "id": sid, "pid": parent["pid"],
                          "tid": parent["tid"], "ts": start_ts})
            links.append({"ph": "f", "bp": "e", "cat": "trace",
                          "name": "span", "id": sid, "pid": tev["pid"],
                          "tid": tev["tid"], "ts": tev["ts"]})
        trace.extend(links)
    return trace


def spans_to_chrome_trace(rows: list[dict], flow: bool = True) -> list[dict]:
    """Flat GCS trace-TABLE rows (get_trace_spans) -> chrome-trace JSON:
    regroups rows into per-process pseudo-batches and reuses
    to_chrome_trace, so `ray-tpu trace` / `/api/trace` render one
    trace's cross-process tree with the same flow arrows as the full
    timeline."""
    batches: dict[tuple, dict] = {}
    for r in rows:
        nid = r.get("node_id")
        key = (r["component_type"], r["component_id"],
               nid if isinstance(nid, bytes) else b"")
        b = batches.get(key)
        if b is None:
            b = batches[key] = {"component_type": r["component_type"],
                                "component_id": r["component_id"],
                                "node_id": nid, "events": []}
        b["events"].append({"event_type": r["event_type"],
                            "start_time": r["start_time"],
                            "end_time": r["end_time"],
                            "extra_data": r.get("extra_data", {})})
    return to_chrome_trace(list(batches.values()), flow=flow)
