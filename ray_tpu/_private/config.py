"""Global config registry — the RAY_CONFIG-equivalent.

The reference defines ~90 `RAY_CONFIG(type, name, default)` flags in a single
header (reference: src/ray/common/ray_config_def.h) initialized from a JSON
`_system_config` and propagated to every spawned process. We keep the same
single-source-of-truth + env/JSON override design: every knob is declared
here, overridable via the RAY_TPU_SYSTEM_CONFIG env var (JSON) or the
`_system_config` argument to `ray_tpu.init`, and child processes inherit the
merged dict through that env var.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

_ENV_VAR = "RAY_TPU_SYSTEM_CONFIG"


@dataclasses.dataclass
class Config:
    # --- object plane ---
    # Objects at or below this size are passed inline through the owner's
    # in-process memory store instead of the shared-memory store
    # (reference: ray_config_def.h max_direct_call_object_size=100KB).
    max_direct_call_object_size: int = 100 * 1024
    # Default shared-memory store capacity per node (bytes).
    object_store_memory: int = 2 * 1024**3
    # "files" = file-per-object mmap store; "native" = the C++ shared-arena
    # slab allocator (native/store/store.cc), built on demand with g++.
    object_store_backend: str = "native"
    # Chunk size for node-to-node object transfer.
    object_transfer_chunk_size: int = 5 * 1024**2
    # Admission control: concurrent inbound object transfers per raylet
    # (reference: pull_manager.h bounded active pulls).
    max_concurrent_object_pulls: int = 4
    # Work-stealing unit for multi-source striped pulls: each source
    # streams ranges of this size off a shared queue, so a slow source
    # naturally ends up transferring fewer bytes and a dead one's
    # remaining ranges are resumed by survivors (Hoplite-style
    # multi-source fetch).
    object_transfer_stripe_size: int = 8 * 1024**2
    # Max sources striped across in one pull (extra directory entries are
    # kept as failover spares).
    max_pull_sources: int = 4
    # Sender-side transfer pin lease: an object being served to a puller
    # is protected from free/eviction for this long past the last
    # activity, so a dead puller cannot pin the arena forever
    # (reference: the pinned_objects set in object_manager.h, bounded
    # here by time instead of by connection liveness alone).
    transfer_pin_ttl_s: float = 20.0
    # A pull whose GCS directory lookup stays EMPTY for this long (no
    # node claims a copy) propagates typed object loss to its waiters
    # instead of spinning the lookup forever.
    pull_no_location_timeout_s: float = 10.0
    # Per-socket IO timeout on the bulk transfer channel (recv/send of
    # one chunk): a stalled peer mid-stream surfaces as a socket timeout
    # and the remaining ranges fail over to other sources.
    bulk_transfer_io_timeout_s: float = 30.0

    # --- locality-aware scheduling ---
    # Weigh lease targets by resident plasma-arg bytes (GCS object
    # directory): a task whose args live on another node is leased there
    # instead of pulling the args here (reference: lease_policy.h
    # locality-aware lease targeting). Spillback/queueing still apply on
    # the target.
    locality_aware_leasing: bool = True
    # Only redirect when the best remote node holds at least this many
    # MORE resident arg bytes than the local node (small args are cheaper
    # to move than the task round trip).
    locality_min_arg_bytes: int = 1024 * 1024
    # Spill directory ("" = session dir /spill).
    object_spilling_path: str = ""
    # Spill when store usage exceeds this fraction.
    object_spilling_threshold: float = 0.8

    # --- control plane ---
    # Heartbeat cadence + miss tolerance (reference: raylet 100ms beats,
    # declared dead after 300 misses; we beat less often, die faster).
    heartbeat_interval_s: float = 0.5
    num_heartbeats_timeout: int = 20
    gcs_port: int = 0  # 0 = pick free port
    # GCS fault tolerance: clients (raylets, workers, drivers) redial a
    # restarted GCS for this long before giving up (reference:
    # gcs_rpc_server_reconnect_timeout_s in ray_config_def.h); the node
    # monitor respawns a crashed GCS when enabled.
    gcs_reconnect_timeout_s: float = 30.0
    gcs_persistence: bool = True
    gcs_auto_restart: bool = True

    # --- sharded control plane ---
    # Number of GCS store-shard processes the high-rate tables (KV,
    # object directory, actor/pg read mirrors) are key-partitioned over
    # (gcs/shard.py; client-side crc32 routing in gcs/client.py). 1 (the
    # default, also settable via RAY_TPU_GCS_SHARDS) spawns no shard
    # processes and preserves the single-GCS layout exactly.
    gcs_shards: int = 1

    # --- scheduling ---
    # Max in-flight lease-reused tasks pushed to one worker
    # (reference: direct_task_transport.h max_tasks_in_flight_per_worker).
    max_tasks_in_flight_per_worker: int = 10
    # Raylet→raylet lease spillback: a raylet that can't grant FORWARDS
    # the lease request to its chosen peer (hop-capped, cycle-guarded)
    # and relays the grant, instead of bouncing the owner back out for
    # another round trip per hop. False restores the owner-mediated
    # redial chain (the legacy A/B arm; also RAY_TPU_SPILLBACK_LEGACY=1).
    lease_spillback_forwarding: bool = True
    # Max raylet hops a forwarded lease request may chain through before
    # the last raylet queues it locally (stops ping-pong on a saturated
    # cluster; matches the legacy hop cap).
    lease_spillback_max_hops: int = 3
    # Lease pre-warm: max leases asked for in one batched
    # request_worker_lease RPC (soft target is ceil(queue / in-flight
    # cap), clamped here; reference: pipelined lease requests in
    # direct_task_transport.h).
    max_lease_batch: int = 4
    # While ≥1 lease is working a key, extra lease requests are SOFT
    # (granted from idle workers only, never spawning); they escalate to
    # hard — may spawn a worker — once the queue has waited this long.
    lease_escalation_s: float = 1.0
    # Idle leases are returned to the raylet after this grace (single
    # shared reaper; also bounds how long a drained-queue prewarm lease
    # can strand a worker).
    lease_idle_grace_s: float = 0.25
    # Initial worker-pool size per node; workers are also started on demand.
    # -1 = auto (min(num_cpus, 8)). Prestarting matters on TPU hosts: every
    # Python start pays the jax/plugin import cost, so cold workers are slow.
    num_initial_workers: int = -1
    # Hard cap on worker processes per node (0 = num_cpus).
    max_workers_per_node: int = 0
    worker_register_timeout_s: float = 30.0

    # --- fault tolerance ---
    task_max_retries: int = 3
    actor_max_restarts: int = 0
    lineage_pinning_enabled: bool = True

    # --- TPU topology ---
    # Logical ICI slice size used by the slice-aware scheduler when packing
    # STRICT_PACK placement groups onto TPU hosts.
    tpu_slice_hosts: int = 1
    tpu_chips_per_host: int = 4

    # --- elastic membership ---
    # Graceful drain budget: a DRAINING raylet keeps serving its
    # in-flight leases and migrating plasma objects to survivors for at
    # most this long; whatever is still running at the deadline is
    # reclaimed through the normal typed lease machinery (exactly the
    # crash path, but scoped to the leftovers).
    drain_deadline_s: float = 30.0
    # Compressed-drain budget on a preemption notice (TPU spot gives
    # seconds, not minutes): actor/gang checkpoints run first, object
    # migration is best-effort inside whatever remains of this window.
    preempt_drain_deadline_s: float = 5.0
    # Cap on concurrent object migrations pushed off a draining node
    # (each is a striped pull on the survivor; bounding it keeps the
    # bulk channel from thundering-herding the survivors).
    drain_migrate_concurrency: int = 4
    # Grace past the drain deadline before the GCS heartbeat checker may
    # declare a DRAINING node DEAD (covers the final migrate/ack RTT).
    drain_grace_s: float = 5.0

    # --- training ---
    # Batches each train worker keeps in flight against its DatasetShard
    # ingest actor (train/ingest.py). 2 = double buffering: the next
    # batch transfers over the bulk channel while the current step
    # computes, so a healthy pipeline shows train.ingest_wait_s p50 ~ 0.
    train_ingest_prefetch_depth: int = 2

    # --- rpc ---
    rpc_connect_timeout_s: float = 10.0
    rpc_call_timeout_s: float = 0.0  # 0 = no timeout
    # Address this node advertises to peers (GCS/raylet/worker servers).
    # The default keeps everything loopback-only (single machine); the
    # cluster launcher sets each host's reachable IP, which also flips
    # the listeners to 0.0.0.0 (reference: ray start --node-ip-address).
    node_ip_address: str = "127.0.0.1"

    @property
    def bind_host(self) -> str:
        return ("127.0.0.1" if self.node_ip_address == "127.0.0.1"
                else "0.0.0.0")

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @classmethod
    def load(cls, overrides: dict[str, Any] | None = None) -> "Config":
        cfg = cls()
        env = os.environ.get(_ENV_VAR)
        merged: dict[str, Any] = {}
        if env:
            merged.update(json.loads(env))
        if overrides:
            merged.update(overrides)
        # Dedicated env toggles (checked only when the JSON/overrides did
        # not already pin the knob, so _system_config stays authoritative).
        if "gcs_shards" not in merged and os.environ.get("RAY_TPU_GCS_SHARDS"):
            merged["gcs_shards"] = int(os.environ["RAY_TPU_GCS_SHARDS"])
        if ("lease_spillback_forwarding" not in merged
                and os.environ.get("RAY_TPU_SPILLBACK_LEGACY", "")
                not in ("", "0", "false", "False")):
            merged["lease_spillback_forwarding"] = False
        known = {f.name for f in dataclasses.fields(cls)}
        for key, value in merged.items():
            if key not in known:
                raise ValueError(f"Unknown system config key: {key}")
            setattr(cfg, key, value)
        return cfg

    def child_env(self) -> dict[str, str]:
        """Env vars to propagate this config to spawned processes."""
        return {_ENV_VAR: self.to_json()}


_global_config: Config | None = None


def get_config() -> Config:
    global _global_config
    if _global_config is None:
        _global_config = Config.load()
    return _global_config


def set_config(cfg: Config) -> None:
    global _global_config
    _global_config = cfg
