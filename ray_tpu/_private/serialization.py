"""Value serialization: msgpack envelope + pickle5 out-of-band buffers.

Mirrors the reference's SerializationContext capability (reference:
python/ray/serialization.py:66,:251 _serialize_to_pickle5): values are
cloudpickled with protocol 5; large contiguous buffers (numpy arrays, the
host copy of jax.Arrays) ride out-of-band so the object-store write and the
deserializing read are zero-copy. The envelope is
    msgpack([meta, pickled_bytes, nbuffers]) + raw buffer concatenation
with buffer sizes recorded in meta, so a reader can mmap the object and map
each out-of-band buffer straight onto the shared memory.

ObjectRefs and ActorHandles found inside values are swapped for plain
descriptors at serialize time and rehydrated at deserialize time through
thread-local hooks installed by the core worker — this is what lets refs and
handles be passed freely between processes while the owner tracks borrows.
"""

from __future__ import annotations

import pickle
import threading
from typing import Any, Callable

import cloudpickle
import msgpack

_local = threading.local()


def set_context(
    serialize_ref: Callable[[Any], dict] | None,
    deserialize_ref: Callable[[dict], Any] | None,
    serialize_handle: Callable[[Any], dict] | None = None,
    deserialize_handle: Callable[[dict], Any] | None = None,
):
    _local.serialize_ref = serialize_ref
    _local.deserialize_ref = deserialize_ref
    _local.serialize_handle = serialize_handle
    _local.deserialize_handle = deserialize_handle


def get_ref_serializer():
    return getattr(_local, "serialize_ref", None)


def get_ref_deserializer():
    return getattr(_local, "deserialize_ref", None)


def get_handle_serializer():
    return getattr(_local, "serialize_handle", None)


def get_handle_deserializer():
    return getattr(_local, "deserialize_handle", None)


def _to_host(value):
    """Convert device-resident arrays to host buffers for serialization.

    jax.Array is serialized as its numpy host copy; fully-sharded arrays must
    be gathered by the caller first (the trainer checkpoints sharded state via
    orbax instead of passing it through the object store).
    """
    import numpy as np

    try:
        import jax
    except Exception:  # pragma: no cover - jax always present in this image
        return value
    if isinstance(value, jax.Array):
        return np.asarray(value)
    return value


class _Pickler(cloudpickle.Pickler):
    def __init__(self, file, buffers):
        super().__init__(file, protocol=5, buffer_callback=buffers.append)

    def persistent_id(self, obj):
        return None

    def reducer_override(self, obj):
        import jax

        if isinstance(obj, jax.Array):
            arr = _to_host(obj)
            return (_rebuild_jax_array, (arr,))
        # Delegate to cloudpickle's reducer, NOT NotImplemented: cloudpickle
        # implements by-value pickling of local/interactively-defined
        # functions and classes through reducer_override, so returning
        # NotImplemented here silently downgraded task args to stock
        # pickle (locally-defined functions inside args failed to ship).
        return super().reducer_override(obj)


def _rebuild_jax_array(np_arr):
    # Rehydrate lazily as numpy; callers move data to device explicitly
    # (device placement is a property of the computation, not the value).
    return np_arr


def serialize(value: Any) -> tuple[bytes, list[memoryview]]:
    """Returns (envelope_header, buffers). The full object payload is
    header + b''.join(buffers); buffers may be written directly to shm."""
    import io

    buffers: list[pickle.PickleBuffer] = []
    bio = io.BytesIO()
    _Pickler(bio, buffers).dump(value)
    pickled = bio.getvalue()
    raw: list[memoryview] = []
    sizes: list[int] = []
    for buf in buffers:
        mv = buf.raw()
        raw.append(mv)
        sizes.append(mv.nbytes)
    meta = {"buffer_sizes": sizes}
    header = msgpack.packb([meta, pickled, len(raw)], use_bin_type=True)
    return _frame_header(header), raw


def _frame_header(header: bytes) -> bytes:
    import struct

    return struct.pack(">I", len(header)) + header


def deserialize(payload: memoryview | bytes) -> Any:
    import struct

    payload = memoryview(payload)
    (hlen,) = struct.unpack(">I", payload[:4])
    meta, pickled, nbuf = msgpack.unpackb(payload[4 : 4 + hlen], raw=False)
    offset = 4 + hlen
    buffers = []
    for size in meta["buffer_sizes"]:
        buffers.append(payload[offset : offset + size])
        offset += size
    return pickle.loads(pickled, buffers=buffers)


def total_size(header: bytes, buffers: list[memoryview]) -> int:
    return len(header) + sum(b.nbytes for b in buffers)


def dumps(value: Any) -> bytes:
    """One-shot serialize to contiguous bytes (for RPC payloads)."""
    header, buffers = serialize(value)
    if not buffers:
        return header
    return b"".join([header, *[bytes(b) for b in buffers]])


def loads(data: bytes | memoryview) -> Any:
    return deserialize(data)
