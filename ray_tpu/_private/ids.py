"""Binary IDs for jobs, tasks, actors, objects, nodes, placement groups.

Capability parity with the reference's ID scheme (reference:
src/ray/common/id.h, src/ray/design_docs/id_specification.md) but simplified:
every ID is a fixed-width random byte string; ObjectIDs embed the creating
TaskID plus a return/put index so lineage is recoverable from the ID alone.

Sizes (bytes): JobID=4, ActorID=12 (job-suffixed), TaskID=16, ObjectID=24
(TaskID + 4-byte kind/index + 4 random), NodeID/WorkerID/PlacementGroupID=16.
"""

from __future__ import annotations

import os
import random
import struct
import threading

_JOB_ID_SIZE = 4
_ACTOR_ID_SIZE = 12
_TASK_ID_SIZE = 16
_OBJECT_ID_SIZE = 24
_UNIQUE_ID_SIZE = 16

# Object "kind" tags baked into the index word of an ObjectID.
_KIND_PUT = 1
_KIND_RETURN = 2

# Hot-path randomness: ids need collision resistance, not secrecy, and
# os.urandom is a ~50µs syscall that showed up at 5% of the actor-call
# microbenchmark. One urandom-seeded Mersenne Twister per process (and
# per fork — reseeded via the pid guard) is plenty.
_rng_lock = threading.Lock()
_rng = random.Random(os.urandom(16))
_rng_pid = os.getpid()


def _rand_bytes(n: int) -> bytes:
    global _rng, _rng_pid
    with _rng_lock:
        if os.getpid() != _rng_pid:  # forked child must not clone ids
            _rng = random.Random(os.urandom(16))
            _rng_pid = os.getpid()
        return _rng.getrandbits(n * 8).to_bytes(n, "big")


class BaseID:
    SIZE = _UNIQUE_ID_SIZE
    __slots__ = ("_bytes", "_hash")

    def __init__(self, id_bytes: bytes):
        if not isinstance(id_bytes, bytes) or len(id_bytes) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {id_bytes!r}"
            )
        self._bytes = id_bytes
        self._hash = hash(id_bytes)

    @classmethod
    def from_random(cls):
        return cls(_rand_bytes(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\xff" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\xff" * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __lt__(self, other):
        return self._bytes < other._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    SIZE = _JOB_ID_SIZE

    @classmethod
    def from_int(cls, value: int):
        return cls(struct.pack(">I", value))


class WorkerID(BaseID):
    SIZE = _UNIQUE_ID_SIZE


class NodeID(BaseID):
    SIZE = _UNIQUE_ID_SIZE


class PlacementGroupID(BaseID):
    SIZE = _UNIQUE_ID_SIZE


class ActorID(BaseID):
    SIZE = _ACTOR_ID_SIZE

    @classmethod
    def of(cls, job_id: JobID):
        return cls(_rand_bytes(cls.SIZE - JobID.SIZE) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[-JobID.SIZE :])


class TaskID(BaseID):
    SIZE = _TASK_ID_SIZE

    @classmethod
    def for_driver(cls, job_id: JobID):
        return cls(b"\x00" * (cls.SIZE - JobID.SIZE) + job_id.binary())

    @classmethod
    def for_task(cls, job_id: JobID):
        return cls(_rand_bytes(cls.SIZE - JobID.SIZE) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[-JobID.SIZE :])


class ObjectID(BaseID):
    SIZE = _OBJECT_ID_SIZE

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int):
        tag = struct.pack(">I", (_KIND_PUT << 24) | (put_index & 0xFFFFFF))
        return cls(task_id.binary() + tag + _rand_bytes(4))

    @classmethod
    def for_return(cls, task_id: TaskID, return_index: int):
        # Deterministic: a task's i-th return ObjectID is computable by anyone
        # holding the TaskID (used for lineage-based recovery).
        tag = struct.pack(">I", (_KIND_RETURN << 24) | (return_index & 0xFFFFFF))
        return cls(task_id.binary() + tag + b"\x00\x00\x00\x00")

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[: TaskID.SIZE])

    def is_return(self) -> bool:
        return self._bytes[TaskID.SIZE] == _KIND_RETURN

    def return_index(self) -> int:
        (word,) = struct.unpack(">I", self._bytes[TaskID.SIZE : TaskID.SIZE + 4])
        return word & 0xFFFFFF


class _PutIndexCounter:
    """Per-task monotonically increasing put index (thread-safe)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0

    def next(self) -> int:
        with self._lock:
            self._value += 1
            return self._value
