"""Lightweight asyncio RPC: length-prefixed msgpack frames over UDS/TCP.

Plays the role of the reference's gRPC layer (reference: src/ray/rpc/
grpc_server.h, grpc_client.h, client_call.h) for the control plane. Design
differences are deliberate: a single multiplexed duplex connection per
client with integer-correlated requests, msgpack payloads (bytes pass
through zero-copy on the read side), and first-class server->client pushes
(used for pubsub and task dispatch) instead of gRPC streaming.

Wire format: 4-byte big-endian frame length, then
    msgpack([msgtype, msgid, method, data])
msgtype: 0=request 1=reply-ok 2=reply-err 3=oneway 4=push.
`data` is any msgpack value; application payloads that need pickling are
passed as bytes.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import pickle
import socket
import struct
import threading
import traceback
from typing import Any, Awaitable, Callable

import msgpack

logger = logging.getLogger(__name__)

REQUEST, REPLY_OK, REPLY_ERR, ONEWAY, PUSH = 0, 1, 2, 3, 4

_HDR = struct.Struct(">I")
_MAX_FRAME = 1 << 31

# Churn instrumentation (tier-1 guarded: tests assert the per-task hop
# count stays bounded so per-call wakeups can't silently regrow).
# A "wakeup" is one self-pipe write onto an event loop — a real syscall.
from ray_tpu._private import failpoints as _fp
from ray_tpu._private import stats as _stats

M_LOOP_WAKEUPS = _stats.Count(
    "rpc.loop_wakeups_total",
    "cross-thread event-loop wakeups (self-pipe writes)")
M_FRAMES_SENT = _stats.Count(
    "rpc.frames_sent_total", "rpc frames queued for send")
M_SOCKET_FLUSHES = _stats.Count(
    "rpc.socket_flushes_total", "transport writes (coalesced frame bursts)")


def _set_nodelay(writer: asyncio.StreamWriter) -> None:
    """Disable Nagle on every TCP peer connection. asyncio sets this for
    transports it creates, but the guarantee is per-implementation — the
    40ms delayed-ACK/Nagle interplay showed up as multi-ms stalls in the
    1:1 actor-call microbenchmark, so the runtime verifies it explicitly
    on both the dialing and the accepting side."""
    sock = writer.get_extra_info("socket")
    if sock is None or sock.family not in (socket.AF_INET, socket.AF_INET6):
        return
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:  # pragma: no cover - transport already closed
        pass


def _chaos_config():
    """Fault-injection knobs (the race/sanitizer tier — role parity with
    the reference's ASAN/TSAN test strategy, SURVEY §5: instead of
    compiler sanitizers, perturb the control plane's timing so ordering
    assumptions break loudly under test).

    RAY_TPU_CHAOS="delay_p=0.2,delay_ms=25[,kill_conn_p=0.001]"
      delay_p      probability a frame send is delayed
      delay_ms     max extra latency (uniform 0..delay_ms)
      kill_conn_p  probability a send instead hard-drops the connection
                   (exercises redial/retry paths)
    Parsed once per process; inherited by spawned runtime processes.

    Evaluation now rides the failpoints registry: the two knobs are the
    predefined points `rpc.send.delay` / `rpc.send.drop_conn`
    (failpoints.send_fault), sharing its seeded RNG and hit counters; the
    deterministic registry (`RAY_TPU_FAILPOINTS`, live KV arming) layers
    any further action onto the same `rpc.send` seam."""
    import os

    raw = os.environ.get("RAY_TPU_CHAOS")
    if not raw:
        return None
    cfg = {"delay_p": 0.0, "delay_ms": 10.0, "kill_conn_p": 0.0}
    try:
        for part in raw.split(","):
            k, _, v = part.partition("=")
            if k.strip() in cfg:
                cfg[k.strip()] = float(v)
    except ValueError as e:
        raise ValueError(
            f"malformed RAY_TPU_CHAOS={raw!r} (expected "
            f"'delay_p=0.2,delay_ms=25[,kill_conn_p=0.001]'): {e}"
        ) from None
    return cfg


_CHAOS = _chaos_config()


class RpcError(Exception):
    pass


class RemoteError(RpcError):
    """Handler on the other side raised; carries its pickled exception."""

    def __init__(self, exc: BaseException, tb: str):
        self.exc = exc
        self.tb = tb
        super().__init__(f"{exc!r}\nRemote traceback:\n{tb}")


class ConnectionLost(RpcError):
    pass


class ConnectionGaveUp(ConnectionLost):
    """A ReconnectingConnection exhausted its redial budget: the peer is
    being treated as permanently gone. Every queued/future caller gets
    this typed error (not a bare timeout), carrying who gave up on what."""

    def __init__(self, name: str, address: str, cause: str = ""):
        self.conn_name = name
        self.address = address
        self.cause = cause
        super().__init__(
            f"{name}: gave up redialing {address}"
            + (f" ({cause})" if cause else ""))

    def __reduce__(self):
        # travels inside rpc error replies: a handler that hit a
        # given-up connection must not become an unpicklable payload
        # that tears down the receiving side's whole connection
        return (ConnectionGaveUp,
                (self.conn_name, self.address, self.cause))


def _pack(msg) -> bytes:
    body = msgpack.packb(msg, use_bin_type=True)
    return _HDR.pack(len(body)) + body


async def _read_frame(reader: asyncio.StreamReader):
    hdr = await reader.readexactly(_HDR.size)
    (length,) = _HDR.unpack(hdr)
    if length > _MAX_FRAME:
        raise RpcError(f"frame too large: {length}")
    body = await reader.readexactly(length)
    return msgpack.unpackb(body, raw=False)


def deferred(fn):
    """Mark an rpc handler as deferred-reply: it is invoked as
    fn(conn, data, msgid) synchronously on the read loop and owes the
    caller a later `conn.reply_deferred(msgid, method, reply)` — from any
    thread. This lets a handler hand work to another thread WITHOUT an
    asyncio future + task + coroutine resume per request (the worker's
    task-execution path: read loop → dispatcher thread → coalesced reply
    enqueue, two hops total)."""
    fn._rpc_deferred = True
    return fn


class Connection:
    """One duplex connection; usable as both caller and callee side."""

    def __init__(self, reader, writer, handlers, on_disconnect=None, name=""):
        self._loop = asyncio.get_running_loop()
        self._reader = reader
        self._writer = writer
        self._handlers = handlers
        self._on_disconnect = on_disconnect
        self.name = name
        self._msgid = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._push_handler: Callable[[str, Any], Awaitable[None]] | None = None
        self._send_lock = asyncio.Lock()
        self._undrained = 0
        # outbound frame coalescing: frames queue here and one call_soon
        # callback writes them in a single transport write per loop tick
        # (a submit burst of 100 small calls = a handful of socket sends
        # instead of 100 — sock.send was 15% of the n:n microbenchmark)
        self._outbuf: list[bytes] = []
        self._flush_scheduled = False
        self._closed = False
        self._reader_task = asyncio.create_task(self._read_loop())
        # Opaque per-connection state slot for servers (e.g. worker identity).
        self.context: dict[str, Any] = {}

    def set_push_handler(self, fn):
        self._push_handler = fn

    async def _read_loop(self):
        try:
            while True:
                msg = await _read_frame(self._reader)
                if _fp.ARMED:
                    # inbound-frame seam: drop_conn tears this connection
                    # down exactly as a peer reset would; raise simulates
                    # a poisoned frame (read loop dies -> full shutdown)
                    if await _fp.fire_async("rpc.recv") == "drop_conn":
                        break
                msgtype = msg[0]
                if msgtype == REQUEST:
                    if not self._dispatch_fast(msg[1], msg[2], msg[3]):
                        asyncio.create_task(
                            self._dispatch(msg[1], msg[2], msg[3]))
                elif msgtype in (REPLY_OK, REPLY_ERR):
                    fut = self._pending.pop(msg[1], None)
                    if fut is not None and not fut.done():
                        if msgtype == REPLY_OK:
                            fut.set_result(msg[3])
                        else:
                            exc, tb = pickle.loads(msg[3][0]), msg[3][1]
                            fut.set_exception(RemoteError(exc, tb))
                elif msgtype == ONEWAY:
                    if not self._dispatch_fast(None, msg[2], msg[3]):
                        asyncio.create_task(
                            self._dispatch(None, msg[2], msg[3]))
                elif msgtype == PUSH:
                    if self._push_handler is not None:
                        asyncio.create_task(self._push_handler(msg[2], msg[3]))
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        except Exception:
            logger.exception("rpc read loop error (%s)", self.name)
        finally:
            await self._shutdown()

    async def _shutdown(self):
        if self._closed:
            return
        self._flush()  # don't strand queued frames (e.g. a last reply)
        self._closed = True
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionLost(f"connection {self.name} lost"))
        self._pending.clear()
        try:
            self._writer.close()
        except Exception:
            pass
        if self._on_disconnect is not None:
            try:
                await self._on_disconnect(self)
            except Exception:
                logger.exception("on_disconnect callback failed")

    async def _dispatch(self, msgid, method, data):
        handler = self._handlers.get(method)
        try:
            if handler is None:
                raise RpcError(f"no handler for method {method!r}")
            result = handler(self, data)
            if asyncio.iscoroutine(result):
                result = await result
            if msgid is not None:
                await self._send([REPLY_OK, msgid, method, result])
        except Exception as e:
            if msgid is not None:
                payload = [pickle.dumps(e), traceback.format_exc()]
                try:
                    await self._send([REPLY_ERR, msgid, method, payload])
                except Exception:
                    pass
            else:
                logger.exception("oneway handler %s failed", method)

    def _dispatch_fast(self, msgid, method, data) -> bool:
        """Run a request inline on the read loop when the handler is
        synchronous, skipping the per-request task spawn; async handlers
        get their (already-created) coroutine handed to one awaiting task.
        A small sync handler's whole request→reply turnaround becomes
        plain function calls plus one coalesced flush — this path carries
        task replies and control acks, the per-call churn the task
        microbenchmark pays for. Returns False to fall back to the
        task-per-request slow path (unknown method → its error reply)."""
        handler = self._handlers.get(method)
        if handler is None:
            return False
        try:
            if _fp.ARMED and _fp.fire("rpc.dispatch") == "drop_conn":
                asyncio.ensure_future(self.close())
                return True
            if getattr(handler, "_rpc_deferred", False):
                handler(self, data, msgid)
                return True
            result = handler(self, data)
        except Exception as e:
            if msgid is not None:
                payload = [pickle.dumps(e), traceback.format_exc()]
                self._queue_reply([REPLY_ERR, msgid, method, payload])
            else:
                logger.exception("oneway handler %s failed", method)
            return True
        if asyncio.iscoroutine(result):
            asyncio.create_task(self._dispatch_await(msgid, method, result))
            return True
        if msgid is not None:
            try:
                self._queue_reply([REPLY_OK, msgid, method, result])
            except Exception as e:
                # unpackable result — surface as a remote error, like the
                # slow path would
                payload = [pickle.dumps(RpcError(
                    f"unserializable reply from {method!r}: {e}")),
                    traceback.format_exc()]
                self._queue_reply([REPLY_ERR, msgid, method, payload])
        return True

    async def _dispatch_await(self, msgid, method, coro):
        """Finish a coroutine handler started by the fast dispatch."""
        try:
            result = await coro
            if msgid is not None:
                await self._send([REPLY_OK, msgid, method, result])
        except Exception as e:
            if msgid is not None:
                payload = [pickle.dumps(e), traceback.format_exc()]
                try:
                    await self._send([REPLY_ERR, msgid, method, payload])
                except Exception:
                    pass
            else:
                logger.exception("oneway handler %s failed", method)

    def _queue_reply(self, msg):
        """Queue an outbound frame from loop context without awaiting;
        falls back to an async send under chaos, backpressure, or for
        large frames (those need a real drain)."""
        try:
            if not self._send_nowait(msg):
                asyncio.create_task(self._send_checked(msg))
        except ConnectionLost:
            pass  # reader shutdown path already notified the peer futures

    async def _send_checked(self, msg):
        try:
            await self._send(msg)
        except Exception:
            logger.debug("queued reply dropped on %s (connection dying)",
                         self.name)

    def reply_deferred(self, msgid, method, result=None, error=None,
                       tb: str = ""):
        """Complete a `deferred` handler — callable from ANY thread;
        delivery rides the connection loop's coalesced call queue, so a
        burst of completions from a worker thread costs one loop wakeup."""
        if msgid is None:
            return
        if _fp.ARMED and error is None:
            # deferred-completion seam: `raise` models the completing
            # thread dying AFTER execution but BEFORE delivery — the
            # request must error, never hang; `drop_conn` drops the
            # reply WITH its connection (the owner sees ConnectionLost);
            # `exit` kills the process
            try:
                if _fp.fire("rpc.reply_deferred") == "drop_conn":
                    try:
                        loop_call_queue(self._loop).call(
                            lambda: asyncio.ensure_future(self.close()))
                    except RuntimeError:
                        pass
                    return
            except _fp.FailpointError as e:
                error, tb = e, ""
        if error is not None:
            msg = [REPLY_ERR, msgid, method,
                   [pickle.dumps(error), tb]]
        else:
            msg = [REPLY_OK, msgid, method, result]
        try:
            loop_call_queue(self._loop).call(self._reply_deferred_on_loop,
                                             msg)
        except RuntimeError:
            pass  # loop closed: caller's future got ConnectionLost

    def _reply_deferred_on_loop(self, msg):
        try:
            self._queue_reply(msg)
        except ConnectionLost:
            pass
        except Exception as e:
            try:
                payload = [pickle.dumps(RpcError(
                    f"unserializable reply from {msg[2]!r}: {e}")),
                    traceback.format_exc()]
                self._queue_reply([REPLY_ERR, msg[1], msg[2], payload])
            except Exception:
                pass

    def _send_nowait(self, msg) -> bool:
        """Synchronous enqueue of one small frame onto the coalesced
        flush. Returns False when the caller must take the async path:
        chaos tier active (frames must keep their delay/kill injection),
        a concurrent sender holds the drain lock (backpressure in
        progress), or the frame/budget needs a writer drain."""
        if self._closed:
            raise ConnectionLost(f"connection {self.name} closed")
        if (_CHAOS is not None or _fp.ARMED
                or self._send_lock.locked()):
            # armed fault tier: frames must keep their injection point
            return False
        data = _pack(msg)
        if len(data) > 65536 or self._undrained + len(data) > (1 << 20):
            return False
        self._enqueue(data)
        return True

    async def _send(self, msg):
        if self._closed:
            raise ConnectionLost(f"connection {self.name} closed")
        if _CHAOS is not None or _fp.ARMED:
            # outbound-frame seam: the legacy RAY_TPU_CHAOS knobs and the
            # registry's `rpc.send` point evaluate together (send_fault)
            fault = _fp.send_fault(_CHAOS)
            if fault is not None:
                kind, delay = fault
                if kind == "drop_conn":
                    await self._shutdown()
                    raise ConnectionLost(
                        f"connection {self.name} killed by fault injection")
                if kind == "delay":
                    await asyncio.sleep(delay)
                elif kind == "raise":
                    raise _fp.FailpointError("rpc.send")
                elif kind == "exit":
                    _fp._hard_exit("rpc.send")
        data = _pack(msg)
        async with self._send_lock:
            try:
                self._enqueue(data)
                # drain() per frame costs a syscall-sized stall on every
                # small control message (it was the top cost in the
                # actor-call microbenchmark). Small frames skip it, but
                # only up to an un-drained budget — an unbounded skip
                # would let a one-way flood (e.g. worker log lines) grow
                # the transport buffer without backpressure.
                if len(data) > 65536 or self._undrained > (1 << 20):
                    self._flush()
                    await self._writer.drain()
                    self._undrained = 0
            except (ConnectionError, OSError, RuntimeError) as e:
                # RuntimeError: asyncio raises it for writes on a
                # transport closed under us (chaos kill, peer reset)
                # normalize transport failures mid-send: retry layers
                # (ReconnectingConnection) only understand ConnectionLost
                raise ConnectionLost(
                    f"connection {self.name} lost mid-send: {e}") from e

    def _enqueue(self, data: bytes) -> None:
        """Queue one packed frame for the coalesced per-tick flush."""
        self._outbuf.append(data)
        M_FRAMES_SENT.inc()
        self._undrained += len(data)
        if not self._flush_scheduled:
            self._flush_scheduled = True
            asyncio.get_running_loop().call_soon(self._flush)

    def _flush(self):
        """Write every queued frame in one transport call. Runs on the
        event loop (call_soon AFTER the burst of _send callbacks that
        queued frames, preserving FIFO order with the immediate
        large-frame path, which calls this synchronously first)."""
        self._flush_scheduled = False
        if not self._outbuf or self._closed:
            self._outbuf.clear()
            return
        buf = (self._outbuf[0] if len(self._outbuf) == 1
               else b"".join(self._outbuf))
        self._outbuf.clear()
        M_SOCKET_FLUSHES.inc()
        try:
            self._writer.write(buf)
        except (ConnectionError, OSError, RuntimeError):
            # the reader loop notices the dead transport and runs the
            # full shutdown path; callers see ConnectionLost there
            logger.debug("flush failed on %s (connection dying)",
                         self.name)

    async def call(self, method: str, data: Any = None, timeout: float | None = None):
        msgid = next(self._msgid)
        fut = asyncio.get_running_loop().create_future()
        self._pending[msgid] = fut
        try:
            await self._send([REQUEST, msgid, method, data])
        except BaseException:
            # abandon our own future cleanly — _shutdown may already have
            # set ConnectionLost on it, which would otherwise be logged
            # as "exception was never retrieved"
            fut = self._pending.pop(msgid, fut)
            if not fut.done():
                fut.cancel()
            else:
                fut.exception()  # mark retrieved
            raise
        if timeout:
            return await asyncio.wait_for(fut, timeout)
        return await fut

    async def notify(self, method: str, data: Any = None):
        await self._send([ONEWAY, None, method, data])

    async def push(self, channel: str, data: Any = None):
        await self._send([PUSH, None, channel, data])

    @property
    def closed(self) -> bool:
        return self._closed

    async def close(self):
        self._reader_task.cancel()
        await self._shutdown()


class Server:
    """RPC server bound to a UDS path and/or TCP port."""

    def __init__(self, handlers: dict[str, Callable], on_disconnect=None,
                 on_connect=None, name="server"):
        self.handlers = handlers
        self.on_disconnect = on_disconnect
        self.on_connect = on_connect
        self.name = name
        self._servers: list[asyncio.AbstractServer] = []
        self.connections: set[Connection] = set()
        self.tcp_port: int | None = None

    async def _accept(self, reader, writer):
        _set_nodelay(writer)
        conn = Connection(reader, writer, self.handlers,
                          on_disconnect=self._handle_disconnect, name=self.name)
        self.connections.add(conn)
        if self.on_connect is not None:
            try:
                res = self.on_connect(conn)
                if asyncio.iscoroutine(res):
                    await res
            except Exception:
                logger.exception("on_connect failed")

    async def _handle_disconnect(self, conn):
        self.connections.discard(conn)
        if self.on_disconnect is not None:
            res = self.on_disconnect(conn)
            if asyncio.iscoroutine(res):
                await res

    async def start_unix(self, path: str):
        srv = await asyncio.start_unix_server(self._accept, path=path)
        self._servers.append(srv)

    async def start_tcp(self, host: str = "127.0.0.1", port: int = 0,
                        uds_dir: str | None = None):
        srv = await asyncio.start_server(self._accept, host=host, port=port)
        self.tcp_port = srv.sockets[0].getsockname()[1]
        self._servers.append(srv)
        if uds_dir is not None:
            # Same-node fast path: a sibling UDS listener whose path is
            # derived from the TCP port, so any local peer can rewrite
            # "ip:port" -> "unix:<dir>/<port>.sock" (uds_address) without
            # any wire-format or directory change. Loopback TCP costs
            # ~0.25ms more per RTT than UDS on the gVisor-style kernels
            # this runs on — that is ~20% of a small-task round trip.
            try:
                os.makedirs(uds_dir, exist_ok=True)
                path = uds_address(uds_dir, self.tcp_port)[len("unix:"):]
                try:
                    os.unlink(path)
                except FileNotFoundError:
                    pass
                await self.start_unix(path)
            except OSError as e:  # pragma: no cover - fs quirks
                logger.warning("no UDS listener beside tcp port %d: %s",
                               self.tcp_port, e)
        return self.tcp_port

    async def close(self):
        for srv in self._servers:
            srv.close()
        for conn in list(self.connections):
            await conn.close()


async def dial_once(address: str, handlers: dict | None = None,
                    on_disconnect=None, name="client") -> Connection:
    """One dial attempt, no retry: 'unix:/path' or 'host:port'. Raises
    the raw OS-level error; retry policy belongs to the caller."""
    if address.startswith("unix:"):
        reader, writer = await asyncio.open_unix_connection(address[5:])
    else:
        host, port = address.rsplit(":", 1)
        reader, writer = await asyncio.open_connection(host, int(port))
        _set_nodelay(writer)
    return Connection(reader, writer, handlers or {},
                      on_disconnect=on_disconnect, name=name)


async def connect(address: str, handlers: dict | None = None,
                  on_disconnect=None, name="client",
                  timeout: float = 10.0) -> Connection:
    """address: 'unix:/path' or 'host:port'."""
    deadline = asyncio.get_running_loop().time() + timeout
    last_err: Exception | None = None
    while asyncio.get_running_loop().time() < deadline:
        try:
            return await dial_once(address, handlers,
                                   on_disconnect=on_disconnect, name=name)
        except (ConnectionError, FileNotFoundError, OSError) as e:
            last_err = e
            await asyncio.sleep(0.05)
    raise ConnectionLost(f"could not connect to {address}: {last_err}")


class ReconnectingConnection:
    """Client connection that survives server restarts (the GCS fault-
    tolerance plane; reference: src/ray/gcs/gcs_client/service_based_gcs_client.h
    reconnection + python/ray/tests/test_gcs_fault_tolerance.py behavior).

    `call()` transparently retries across a connection loss: it redials the
    same address until `retry_timeout` elapses, runs `on_reconnect(conn)`
    on the fresh connection so the caller can re-establish session state
    (re-register, re-subscribe) BEFORE queued calls resume, and then
    replays the call. Handlers/push-handler are re-attached automatically.
    Calls whose reply was lost mid-flight are retried, so server handlers
    reached through this wrapper must be idempotent.

    Redials are paced with exponential backoff plus jitter (capped at
    `redial_cap_s`, ~2s): after a head-node crash every raylet, worker and
    driver redials at once, and a fixed cadence would hammer the
    recovering server in lockstep. When the budget is exhausted the
    wrapper gives up PERMANENTLY: `on_give_up` runs once, and every
    queued and future caller gets the typed `ConnectionGaveUp` (never a
    bare timeout), so callers can distinguish "peer is gone" from "my
    call was slow".
    """

    def __init__(self, address: str, handlers: dict | None = None,
                 name: str = "client", on_reconnect=None,
                 retry_timeout: float = 30.0, on_give_up=None,
                 dial_timeout: float = 10.0, redial_cap_s: float = 2.0):
        self.address = address
        self.name = name
        self._handlers = handlers or {}
        self._on_reconnect = on_reconnect
        self._on_give_up = on_give_up
        self._retry_timeout = retry_timeout
        self._dial_timeout = dial_timeout
        self._redial_cap = redial_cap_s
        self._conn: Connection | None = None
        self._push_handler = None
        self._dial_lock: asyncio.Lock | None = None
        self._ever_connected = False
        self._gave_up = False
        self.context: dict[str, Any] = {}

    async def ensure_connected(self) -> Connection:
        if self._conn is not None and not self._conn.closed:
            return self._conn
        if self._gave_up:
            raise ConnectionGaveUp(self.name, self.address)
        if self._dial_lock is None:
            self._dial_lock = asyncio.Lock()
        async with self._dial_lock:
            if self._conn is not None and not self._conn.closed:
                return self._conn
            if self._gave_up:
                raise ConnectionGaveUp(self.name, self.address)
            timeout = (self._retry_timeout if self._ever_connected
                       else self._dial_timeout)
            try:
                conn = await self._redial(timeout)
            except ConnectionLost:
                if self._ever_connected:
                    self._gave_up = True
                    if self._on_give_up is not None:
                        try:
                            res = self._on_give_up()
                            if asyncio.iscoroutine(res):
                                await res
                        except Exception:
                            logger.exception("%s on_give_up failed", self.name)
                    raise ConnectionGaveUp(self.name, self.address)
                raise
            if self._push_handler is not None:
                conn.set_push_handler(self._push_handler)
            conn.context.update(self.context)
            reconnecting = self._ever_connected
            self._ever_connected = True
            self._conn = conn
            if reconnecting and self._on_reconnect is not None:
                logger.info("%s: reconnected to %s", self.name, self.address)
                try:
                    await self._on_reconnect(conn)
                except Exception:
                    logger.exception("%s on_reconnect failed", self.name)
            return conn

    async def _redial(self, timeout: float) -> Connection:
        """Dial until success or `timeout`, with exponential backoff +
        jitter capped at redial_cap_s. Raises ConnectionLost when the
        budget runs out."""
        import random as _random

        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        attempt = 0
        last_err: Exception | None = None
        while True:
            try:
                return await dial_once(self.address, self._handlers,
                                       on_disconnect=self._lost,
                                       name=self.name)
            except (ConnectionError, FileNotFoundError, OSError) as e:
                last_err = e
                attempt += 1
                remaining = deadline - loop.time()
                if remaining <= 0:
                    raise ConnectionLost(
                        f"could not connect to {self.address} after "
                        f"{attempt} attempts: {last_err}") from last_err
                backoff = min(self._redial_cap,
                              0.05 * (2 ** min(attempt - 1, 12)))
                backoff *= 0.5 + _random.random()  # jitter: 50-150%
                # never forfeit budget: clamp the sleep so a peer that
                # comes back just inside the window still gets one
                # final dial instead of a premature give-up
                await asyncio.sleep(min(backoff, remaining))

    async def _lost(self, conn):
        # Proactive background redial so pubsub pushes resume without
        # waiting for the next outbound call.
        if self._gave_up:
            return
        async def _redial():
            try:
                await self.ensure_connected()
            except Exception:
                pass
        try:
            asyncio.get_running_loop().create_task(_redial())
        except RuntimeError:
            pass

    def set_push_handler(self, fn):
        self._push_handler = fn
        if self._conn is not None:
            self._conn.set_push_handler(fn)

    async def call(self, method: str, data: Any = None,
                   timeout: float | None = None):
        loop = asyncio.get_running_loop()
        deadline = loop.time() + self._retry_timeout
        while True:
            conn = await self.ensure_connected()
            try:
                return await conn.call(method, data, timeout)
            except ConnectionGaveUp:
                raise  # permanent: never retry-spin on a given-up peer
            except ConnectionLost:
                if loop.time() >= deadline:
                    raise
                await asyncio.sleep(0.1)

    async def notify(self, method: str, data: Any = None):
        conn = await self.ensure_connected()
        await conn.notify(method, data)

    async def push(self, channel: str, data: Any = None):
        conn = await self.ensure_connected()
        await conn.push(channel, data)

    @property
    def closed(self) -> bool:
        # A lost underlying connection is redialable, not closed; only a
        # permanent give-up (ConnectionGaveUp to all callers) closes it.
        return self._gave_up

    async def close(self):
        self._gave_up = True
        if self._conn is not None:
            await self._conn.close()


class ThreadsafeCallQueue:
    """Coalesced cross-thread dispatch onto one event loop.

    Every `loop.call_soon_threadsafe` writes a byte to the loop's self-pipe
    — a real syscall per call, and the single largest per-request cost on
    the serve HTTP path (one wakeup per dispatched query + one per result).
    This queue batches them: callers append under a plain lock and only the
    FIRST append per burst schedules a drain, so N wakeups from any number
    of threads collapse into one self-pipe write per loop tick (the same
    trick the Connection send path uses for outbound frames)."""

    def __init__(self, loop):
        self._loop = loop
        self._lock = threading.Lock()
        self._pending: list = []
        self._scheduled = False

    def call(self, fn, *args) -> None:
        """Run fn(*args) on the loop soon; never blocks. Raises
        RuntimeError if the loop is closed (same as call_soon_threadsafe).
        """
        if self._loop.is_closed():
            # checked BEFORE the _scheduled shortcut: a drain scheduled
            # just before the loop stopped never runs, and the shortcut
            # would otherwise swallow every later call silently
            raise RuntimeError("Event loop is closed")
        with self._lock:
            self._pending.append((fn, args))
            if self._scheduled:
                return
            self._scheduled = True
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        try:
            if running is self._loop:
                self._loop.call_soon(self._drain)  # already on-loop: no pipe
            else:
                M_LOOP_WAKEUPS.inc()
                self._loop.call_soon_threadsafe(self._drain)
        except RuntimeError:
            # loop closed: nothing will ever drain. Reset so every later
            # call() retries the schedule and raises too (otherwise they
            # would see _scheduled=True and silently report success).
            # Concurrent winners of the append race lose their items —
            # same as a callback accepted just before close — but any
            # coroutine arguments (submit_nowait) get close()d so they
            # don't leak un-awaited.
            with self._lock:
                self._scheduled = False
                dropped, self._pending = self._pending, []
            for _fn, args in dropped:
                for a in args:
                    if asyncio.iscoroutine(a):
                        a.close()
            raise

    def _drain(self):
        while True:
            with self._lock:
                batch = self._pending
                if not batch:
                    self._scheduled = False
                    return
                self._pending = []
            for fn, args in batch:
                try:
                    fn(*args)
                except Exception:
                    logger.exception("threadsafe call failed")


_loop_queues_lock = threading.Lock()


def loop_call_queue(loop) -> ThreadsafeCallQueue:
    """The shared ThreadsafeCallQueue for `loop` (created on first use).
    Stored as an attribute ON the loop so the queue dies with the loop —
    short-lived loops (tests, proxy restarts) can't pile up in any
    module-global registry."""
    queue = getattr(loop, "_ray_tpu_call_queue", None)
    if queue is None:
        with _loop_queues_lock:
            queue = getattr(loop, "_ray_tpu_call_queue", None)
            if queue is None:
                queue = ThreadsafeCallQueue(loop)
                loop._ray_tpu_call_queue = queue
    return queue


class EventLoopThread:
    """A dedicated asyncio loop on a daemon thread.

    The synchronous driver/worker API (get/put/remote) fronts all its async
    IO through one of these — the analog of the reference core worker's
    io_service threads (reference: core_worker.cc io_service_).
    """

    def __init__(self, name="ray_tpu-io"):
        self.loop = asyncio.new_event_loop()
        # via the registry, so resolve_async/_watch_batch waiters reaching
        # this loop through loop_call_queue() coalesce into the SAME queue
        self._calls = loop_call_queue(self.loop)
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._thread.start()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def run(self, coro, timeout=None):
        M_LOOP_WAKEUPS.inc()
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def submit(self, coro):
        M_LOOP_WAKEUPS.inc()
        return asyncio.run_coroutine_threadsafe(coro, self.loop)

    def call_threadsafe(self, fn, *args):
        """Coalesced call_soon_threadsafe: a burst of calls from worker
        threads costs one loop wakeup, not one per call."""
        self._calls.call(fn, *args)

    def submit_nowait(self, coro):
        """Fire-and-forget coroutine scheduling through the coalesced
        queue — for hot paths that never look at the result (submit()
        builds a concurrent.Future + an uncoalesced wakeup per call)."""
        try:
            self._calls.call(self._spawn, coro)
        except RuntimeError:
            coro.close()  # loop closed: don't leak an unawaited coroutine
            raise

    @staticmethod
    def _spawn(coro):
        asyncio.ensure_future(coro)

    def stop(self):
        def _cancel_all():
            for task in asyncio.all_tasks(self.loop):
                task.cancel()
            self.loop.call_soon(self.loop.stop)

        try:
            self.loop.call_soon_threadsafe(_cancel_all)
        except RuntimeError:
            return
        self._thread.join(timeout=5)


def uds_address(uds_dir: str, port: int) -> str:
    return f"unix:{os.path.join(uds_dir, f'{port}.sock')}"


def prefer_uds(address: str, uds_dir: str | None, local_ips=("127.0.0.1",)):
    """Rewrite a same-node 'ip:port' address to its sibling UDS path when
    that socket exists; remote addresses and missing sockets pass
    through untouched."""
    if uds_dir is None or address.startswith("unix:"):
        return address
    host, _, port = address.rpartition(":")
    if host not in local_ips:
        return address
    candidate = uds_address(uds_dir, int(port))
    if os.path.exists(candidate[len("unix:"):]):
        return candidate
    return address


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port
